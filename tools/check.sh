#!/usr/bin/env bash
# The one entrypoint builders and CI share: static vet + tier-1 tests,
# exactly as ROADMAP.md specifies them.  Usage: tools/check.sh [--vet-only]
set -u
cd "$(dirname "$0")/.."

echo "== karmadactl vet (static analysis, all passes) =="
JAX_PLATFORMS=cpu python -m karmada_tpu.cli vet karmada_tpu/ --format "${VET_FORMAT:-text}"
vet_rc=$?
if [ "$vet_rc" -ne 0 ]; then
  echo "vet failed (rc=$vet_rc)" >&2
  exit "$vet_rc"
fi

if [ "${1:-}" = "--vet-only" ]; then
  exit 0
fi

echo "== incident-plane smoke (obs/incidents: ring + trigger + bundle) =="
JAX_PLATFORMS=cpu python -m pytest tests/test_incidents.py -q -k smoke \
  -p no:cacheprovider -p no:xdist -p no:randomly
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
  echo "incident smoke failed (rc=$smoke_rc)" >&2
  exit "$smoke_rc"
fi

echo "== tier-1 tests (ROADMAP verify command) =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
