"""End-to-end control plane: the whole propagation loop in one process.

Wires the minimum slice of SURVEY.md section 7 step 4: fake member clusters
(capacity simulators) + detector (template+policy -> ResourceBinding) +
batched scheduler + binding->Work rendering + executor + status reflection.
This exercises the reference call stacks 3.1-3.4 without Kubernetes.

Usage:
    cp = ControlPlane()
    cp.add_member("m1", cpu_milli=32000)
    cp.apply_policy(policy)
    cp.apply(deployment_manifest)
    cp.tick()          # one deterministic reconcile round
    cp.member("m1").get("Deployment", "default", "nginx")
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from karmada_tpu.controllers.binding import BindingController
from karmada_tpu.controllers.dependencies import DependenciesDistributor
from karmada_tpu.controllers.descheduler import Descheduler
from karmada_tpu.controllers.detector import ResourceDetector
from karmada_tpu.controllers.execution import ExecutionController
from karmada_tpu.controllers.failover import (
    ApplicationFailoverController,
    ClusterTaintController,
    GracefulEvictionController,
    NoExecuteTaintManager,
)
from karmada_tpu.controllers.extras import (
    ClusterTaintPolicyController,
    FederatedResourceQuotaController,
    RemedyController,
    WorkloadRebalancerController,
)
from karmada_tpu.controllers.namespace import NamespaceSyncController
from karmada_tpu.controllers.status import (
    BindingStatusController,
    ClusterStatusController,
    WorkStatusController,
)
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.cluster import Cluster, ClusterSpec
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.scheduler import Scheduler
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


class ControlPlane:
    def __init__(
        self,
        backend: str = "serial",
        enable_descheduler: bool = False,
        eviction_grace_period_s: float = 600,
        feature_gates: Optional[Dict[str, bool]] = None,
        clock=None,
        persist_dir: Optional[str] = None,
        eviction_rate: float = 100.0,
        waves: int = 8,
        # pipelined chunk executor chunk size (scheduler/pipeline.py)
        pipeline_chunk: int = 1024,
        # solver device mesh shape ("BxC" / (B, C) / "auto"; None = single
        # device) — scheduler/service.py plumbs it to ops/meshing
        mesh_shape=None,
        # --default-not-ready/unreachable-toleration-seconds (webhook flags,
        # 300 in the reference); None disables the defaulted tolerations
        default_toleration_seconds: Optional[int] = 300,
        # --controllers= enable/disable list ("*", "-name", allowlist);
        # filtered at the Runtime (store/worker.parse_controllers).  None
        # rehydrates the spec persisted by `karmadactl serve/tick
        # --controllers` (karmada-system/controller-manager ConfigMap) so
        # every CLI invocation against the plane honors the operator's
        # choice, not just the serve process.
        controllers: Optional[str] = None,
        # mid-serve device-death guard (scheduler/service.py): a device
        # cycle exceeding this degrades to the fastest host backend.
        # None disables (tests / known-good hardware).
        device_cycle_timeout_s: Optional[float] = None,
        # explain plane (serve --explain[=RATE], obs/decisions): sample
        # rate of scheduling cycles recording placement Decision records
        explain: float = 0.0,
        # sustained-traffic controls (scheduler/service.py): batch size
        # cap per cycle, deadline-vs-size batch formation (None = cut
        # immediately), and the bounded-resident admission gate (None =
        # unbounded) — serve --batch-window/--batch-deadline/
        # --admission-limit
        batch_window: int = 4096,
        batch_deadline_s: Optional[float] = None,
        admission_limit: Optional[int] = None,
        # resident-state plane (karmada_tpu/resident, serve --resident):
        # device-resident cluster tensors advanced by watch deltas +
        # per-binding encoded-row cache; device backend only
        resident: bool = False,
        resident_audit_interval: int = 64,
        # fused whole-cycle-on-device steady state (serve --resident
        # --resident-fused, ops/resident_gather): device slot-store
        # gather instead of host batch assembly; host path stays the
        # parity control and fallback
        resident_fused: bool = False,
        # recoverable backend degrade (scheduler/service.py): after this
        # many cycles on the degraded backend, re-probe the device path
        # (None keeps the legacy one-way degrade)
        device_recover_cycles: Optional[int] = None,
        # chaos fault-injection plane (karmada_tpu/chaos, serve --chaos):
        # spec string arming deterministic faults at the named seams
        chaos: Optional[str] = None,
        chaos_seed: int = 0,
        # rebalance plane (karmada_tpu/rebalance, serve --rebalance):
        # interval in seconds of the periodic drain-and-re-place cycle;
        # None leaves it disarmed.  When armed (or when the descheduler
        # is), both evictors share ONE per-cluster pacing budget.
        rebalance: Optional[float] = None,
        rebalance_cfg=None,  # rebalance.RebalanceConfig override
        # hierarchical two-tier solve (ops/shortlist, serve --shortlist):
        # top-k candidate lanes per binding; None/0 keeps every chunk
        # on the full dense dispatch
        shortlist_k: Optional[int] = None,
        shortlist_min_cells: int = 1 << 21,
    ) -> None:
        self.clock = clock if clock is not None else time.time
        from karmada_tpu.utils.events import EventRecorder
        from karmada_tpu.utils.features import FeatureGates
        from karmada_tpu.webhook import AdmissionRegistry, install_default_webhooks

        self.gates = FeatureGates(feature_gates)
        self.admission = AdmissionRegistry()
        if persist_dir is not None:
            from karmada_tpu.store.persistence import load_store

            self.store = load_store(persist_dir, admission=self.admission)
        else:
            self.store = ObjectStore(admission=self.admission)
        install_default_webhooks(
            self.admission, self.store, self.gates,
            default_toleration_seconds=default_toleration_seconds,
        )
        rehydrated = controllers is None
        if rehydrated:
            cm = self.store.try_get(
                "ConfigMap", "karmada-system", "controller-manager")
            controllers = (
                cm.manifest.get("data", {}).get("controllers", "*")
                if cm is not None else "*"
            )
        try:
            self.runtime = Runtime(controllers=controllers)
        except ValueError:
            if not rehydrated:
                raise  # an explicit bad spec must fail loudly
            # a stale persisted spec (name vocabulary drift) must not brick
            # the plane: run everything and let the operator re-set it
            import warnings

            warnings.warn(
                f"ignoring invalid persisted --controllers spec "
                f"{controllers!r}; running all controllers", stacklevel=2)
            self.runtime = Runtime()
        self.members: Dict[str, FakeMemberCluster] = {}
        # the push-side execution/status controllers only drive PUSH-mode
        # members; pull members get a per-member KarmadaAgent instead
        self.push_members: Dict[str, FakeMemberCluster] = {}
        self.agents: Dict[str, object] = {}
        self.dns_detectors: Dict[str, object] = {}
        self.interpreter = ResourceInterpreter()
        self.interpreter.attach_store(self.store)
        self.recorder = EventRecorder()
        self.detector = ResourceDetector(self.store, self.runtime, self.interpreter)
        # shared eviction pacing (rebalance/pacing.py): the rebalance
        # plane's drains and the descheduler's stuck-replica shrinks draw
        # from ONE per-cluster token budget, so the two evictors cannot
        # stampede a cluster in the same interval
        self.eviction_budget_shared = None
        if rebalance or enable_descheduler:
            from karmada_tpu.rebalance import EvictionBudget, RebalanceConfig

            bcfg = rebalance_cfg if rebalance_cfg is not None \
                else RebalanceConfig()
            self.eviction_budget_shared = EvictionBudget(
                per_cluster=bcfg.budget_per_cluster,
                interval_s=bcfg.budget_interval_s, clock=self.clock)
        self.scheduler = Scheduler(self.store, self.runtime, backend=backend,
                                   recorder=self.recorder, waves=waves,
                                   pipeline_chunk=pipeline_chunk,
                                   mesh_shape=mesh_shape,
                                   device_cycle_timeout_s=device_cycle_timeout_s,
                                   explain=explain,
                                   batch_window=batch_window,
                                   batch_deadline_s=batch_deadline_s,
                                   admission_limit=admission_limit,
                                   resident=resident,
                                   resident_audit_interval=(
                                       resident_audit_interval),
                                   resident_fused=resident_fused,
                                   device_recover_cycles=(
                                       device_recover_cycles),
                                   chaos=chaos, chaos_seed=chaos_seed,
                                   shortlist_k=shortlist_k,
                                   shortlist_min_cells=shortlist_min_cells,
                                   rebalance=rebalance,
                                   rebalance_cfg=rebalance_cfg,
                                   rebalance_budget=(
                                       self.eviction_budget_shared),
                                   rebalance_clock=self.clock)
        self.binding_controller = BindingController(
            self.store, self.runtime, self.interpreter
        )
        self.execution = ExecutionController(
            self.store, self.runtime, self.push_members, self.interpreter,
            recorder=self.recorder,
        )
        self.work_status = WorkStatusController(
            self.store, self.runtime, self.push_members, self.interpreter
        )
        self.binding_status = BindingStatusController(
            self.store, self.runtime, self.interpreter
        )
        self.cluster_status = ClusterStatusController(
            self.store, self.runtime, self.push_members, recorder=self.recorder
        )
        # lease staleness monitor: a dead collector/agent degrades its
        # cluster to Ready=Unknown (controllers/lease.py)
        from karmada_tpu.controllers.lease import ClusterLeaseMonitor

        self.lease_monitor = ClusterLeaseMonitor(
            self.store, self.runtime, recorder=self.recorder
        )
        self.cluster_taints = ClusterTaintController(self.store, self.runtime,
                                                     clock=self.clock)
        # taint-driven evictions pace through the rate-limited queue
        # (cluster/eviction_worker.go); lifecycle handles join/unjoin
        from karmada_tpu.controllers.cluster import (
            ClusterLifecycleController,
            RateLimitedEvictionQueue,
        )

        self.cluster_lifecycle = ClusterLifecycleController(self.store, self.runtime)
        self.taint_manager = NoExecuteTaintManager(self.store, self.runtime,
                                                   clock=self.clock)
        self.eviction_queue = RateLimitedEvictionQueue(
            self.runtime, self.taint_manager.evict_one,
            rate_per_s=eviction_rate, clock=self.clock,
            controller_name="taint-manager",
        )
        self.taint_manager.eviction_queue = self.eviction_queue
        self.graceful_eviction = GracefulEvictionController(
            self.store, self.runtime, grace_period_s=eviction_grace_period_s,
            clock=self.clock,
        )
        self.app_failover = ApplicationFailoverController(
            self.store, self.runtime, clock=self.clock,
            recorder=self.recorder)
        self.namespace_sync = NamespaceSyncController(self.store, self.runtime)
        self.dependencies = DependenciesDistributor(
            self.store, self.runtime, self.interpreter
        )
        # the descheduler consumes unschedulable counts over the estimator
        # wire protocol (descheduler.go:141), one in-proc server per member
        from karmada_tpu.estimator.client import AccurateEstimatorClient

        self.descheduler_estimator = AccurateEstimatorClient()
        self.descheduler = (
            Descheduler(self.store, self.runtime, self.members,
                        estimator=self.descheduler_estimator,
                        budget=self.eviction_budget_shared)
            if enable_descheduler
            else None
        )
        # L5 query plane: registry-driven fan-in cache, cluster proxy behind
        # unified auth, and the metrics provider the HPA family consumes
        from karmada_tpu.search import (
            ClusterProxy,
            MultiClusterCache,
            MultiClusterMetricsProvider,
            UnifiedAuthController,
        )

        self.search_cache = MultiClusterCache(self.store, self.runtime, self.members)
        self.unified_auth = UnifiedAuthController(self.store, self.runtime, self.members)
        self.cluster_proxy = ClusterProxy(self.store, self.members, self.unified_auth)
        self.metrics_provider = MultiClusterMetricsProvider(self.members)
        # autoscaling family (FederatedHPA / CronFederatedHPA / marker /
        # replicas syncer), fed by the metrics provider above
        from karmada_tpu.controllers.federatedhpa import (
            CronFederatedHPAController,
            DeploymentReplicasSyncer,
            FederatedHPAController,
            HpaScaleTargetMarker,
        )

        self.federated_hpa = FederatedHPAController(
            self.store, self.runtime, self.metrics_provider, clock=self.clock,
            fast_path=self._hpa_fast_path,
        )
        self.cron_hpa = CronFederatedHPAController(
            self.store, self.runtime, clock=self.clock
        )
        self.hpa_marker = HpaScaleTargetMarker(self.store, self.runtime)
        self.replicas_syncer = DeploymentReplicasSyncer(self.store, self.runtime)
        # MCS slice: service propagation + endpoint-slice collect/dispatch
        from karmada_tpu.controllers.mcs import (
            EndpointSliceCollectController,
            EndpointSliceDispatchController,
            MultiClusterIngressController,
            MultiClusterServiceController,
        )

        self.mcs = MultiClusterServiceController(self.store, self.runtime)
        self.mci = MultiClusterIngressController(self.store, self.runtime)
        # PUSH members only: a pull member is unreachable from the control
        # plane — its agent runs a scoped collect controller inside
        # (cmd/agent/app/agent.go's endpointsliceCollect registration)
        self.eps_collect = EndpointSliceCollectController(
            self.store, self.runtime, self.push_members
        )
        self.eps_dispatch = EndpointSliceDispatchController(self.store, self.runtime)
        self.rebalancer = WorkloadRebalancerController(self.store, self.runtime)
        self.taint_policies = ClusterTaintPolicyController(self.store, self.runtime)
        self.remedies = RemedyController(self.store, self.runtime)
        # agent CSR approval (control-plane side); credential ROTATION is
        # agent-owned — each KarmadaAgent runs its own scoped loop, like
        # the reference's agent binary (cert_rotation_controller.go)
        from karmada_tpu.controllers.certificates import AgentCsrApprover

        self.csr_approver = AgentCsrApprover(self.store, self.runtime,
                                             clock=self.clock)
        self.quotas = FederatedResourceQuotaController(self.store, self.runtime)
        # restart story (SURVEY §5 checkpoint/resume): a restored store
        # resyncs every object through freshly wired controllers, exactly
        # like the reference's informer resync after a component restart
        if persist_dir is not None and len(self.store):
            self.resync()

    def resync(self) -> None:
        from karmada_tpu.store.persistence import resync

        resync(self.store)

    def _hpa_fast_path(self, ns: str, ref, desired: int) -> None:
        """FederatedHPA scale fast path (rebalance plane, ISSUE 10):
        refresh the binding's replica count NOW (the detector will later
        reconcile the same value from the template — idempotent) and
        priority-push it straight into the scheduler queue, so an
        autoscale event re-places in one scheduling cycle instead of
        waiting out the detector resolve."""
        from karmada_tpu.controllers.detector import binding_name
        from karmada_tpu.models.work import ResourceBinding as RB
        from karmada_tpu.scheduler.service import FAST_PATH_PRIORITY
        from karmada_tpu.store.store import NotFoundError

        name = binding_name(ref.kind, ref.name)
        if self.store.try_get(RB.KIND, ns, name) is None:
            return  # no binding rendered yet: the detector path owns it

        def bump(obj) -> None:
            obj.spec.replicas = desired

        try:
            self.store.mutate(RB.KIND, ns, name, bump)
        except NotFoundError:
            return
        from karmada_tpu.utils import events as ev

        ev.emit_key((ns, name), ev.TYPE_NORMAL, ev.REASON_HPA_FAST_PATH,
                    f"FederatedHPA scale to {desired} replicas: "
                    "priority-pushed past the detector round-trip",
                    origin="hpa")
        self.scheduler.promote((ns, name), priority=FAST_PATH_PRIORITY,
                               origin="hpa")

    def checkpoint(self) -> None:
        """Compact the WAL into a fresh snapshot (periodic maintenance)."""
        persistence = getattr(self.store, "persistence", None)
        if persistence is not None:
            persistence.snapshot()

    # -- fleet management ---------------------------------------------------
    def add_member(
        self,
        name: str,
        cpu_milli: int = 64_000,
        memory_gi: int = 256,
        pods: int = 110,
        region: str = "",
        zone: str = "",
        provider: str = "",
        sync_mode: str = "Push",
    ) -> FakeMemberCluster:
        member = FakeMemberCluster(
            name=name,
            cpu_allocatable_milli=cpu_milli,
            memory_allocatable_gi=memory_gi,
            pods_allocatable=pods,
        )
        self.members[name] = member
        if self.store.try_get(Cluster.KIND, "", name) is None:
            cluster = Cluster(
                metadata=ObjectMeta(name=name),
                spec=ClusterSpec(region=region, zone=zone, provider=provider,
                                 sync_mode=sync_mode),
            )
            self.store.create(cluster)
        if sync_mode == "Pull":
            # pull mode: the control plane cannot reach the member; a
            # KarmadaAgent inside it drives execution/status instead
            # (cmd/agent/app/agent.go:140-145), bootstrapping its identity
            # with a CSR the approver honors (karmadactl register flow)
            from karmada_tpu.agent import KarmadaAgent
            from karmada_tpu.controllers.certificates import bootstrap_agent_csr

            bootstrap_agent_csr(self.store, name)
            self.agents[name] = KarmadaAgent(
                self.store, member, self.runtime, self.interpreter,
                recorder=self.recorder, clock=self.clock,
            )
        else:
            # work_status shares the push_members dict by reference; only
            # the member-informer subscription needs per-member wiring
            self.push_members[name] = member
            member.store.bus.subscribe(self.work_status._member_event(name))  # noqa: SLF001
        # per-member estimator server behind the wire transport (the
        # descheduler's unschedulable counts ride this, never the simulator)
        from karmada_tpu.estimator.server import AccurateEstimatorServer
        from karmada_tpu.estimator.wire import LocalTransport

        server = AccurateEstimatorServer(member)
        self.descheduler_estimator.register(name, LocalTransport(server.handle))
        if sync_mode != "Pull":
            self.eps_collect.watch_member(name)
        self.cluster_status.collect_all()
        for agent in self.agents.values():
            agent.cluster_status.collect_all()
        return member

    def member(self, name: str) -> FakeMemberCluster:
        return self.members[name]

    # -- user-facing API ----------------------------------------------------
    def unjoin(self, name: str) -> None:
        """Unregister a member: the lifecycle controller drains its
        execution space, then the finalizer releases the Cluster object.
        Per-member wiring from add_member unwinds here too (estimator
        transport, status informer, slice collection)."""
        from karmada_tpu.store.store import NotFoundError

        try:
            self.store.delete(Cluster.KIND, "", name)
        except NotFoundError:
            pass
        from karmada_tpu.controllers.lease import LEASE_NAMESPACE, Lease

        try:
            self.store.delete(Lease.KIND, LEASE_NAMESPACE, name)
        except NotFoundError:
            pass
        self.descheduler_estimator.deregister(name)
        self.work_status.members.pop(name, None)
        self.eps_collect.unwatch_member(name)
        self.push_members.pop(name, None)
        agent = self.agents.pop(name, None)
        if agent is not None:
            agent.stop()
        det = self.dns_detectors.pop(name, None)
        if det is not None:
            det.stop()
        self.members.pop(name, None)

    def enable_dns_detector(self, name: str, threshold: int = 3):
        """Attach the service-name-resolution detector sidecar to a member
        (cmd/service-name-resolution-detector-example); unjoin stops it."""
        from karmada_tpu.members.dns_detector import ServiceNameResolutionDetector

        det = ServiceNameResolutionDetector(
            self.store, self.member(name), self.runtime, threshold=threshold)
        self.dns_detectors[name] = det
        return det

    def proxy(self, cluster: str, subject: str = "system:admin"):
        """`karmadactl get --cluster=...`-style passthrough to one member
        (aggregated apiserver cluster proxy, proxy.go:73)."""
        return self.cluster_proxy.connect(cluster, subject)

    def apply(self, manifest: dict):
        from karmada_tpu.models.codec import from_manifest_typed

        typed = from_manifest_typed(manifest)
        if typed is not None:
            # a registered karmada API kind: decode to the typed model so
            # admission mutators/validators and controllers see real
            # objects (karmadactl apply -f of a PropagationPolicy etc.)
            existing = self.store.try_get(
                typed.KIND, typed.namespace, typed.name)
            if existing is None:
                return self.store.create(typed)
            typed.metadata.resource_version = existing.metadata.resource_version
            typed.metadata.uid = existing.metadata.uid or typed.metadata.uid
            typed.metadata.generation = existing.metadata.generation
            return self.store.update(typed)
        obj = Unstructured.from_manifest(manifest)
        existing = self.store.try_get(obj.KIND, obj.namespace, obj.name)
        if existing is None:
            return self.store.create(obj)
        assert isinstance(existing, Unstructured)
        existing.manifest = obj.manifest
        existing.metadata.labels = dict(obj.metadata.labels)
        existing.metadata.annotations = dict(obj.metadata.annotations)
        return self.store.update(existing)

    def apply_policy(self, policy) -> None:
        existing = self.store.try_get(
            policy.KIND, policy.metadata.namespace, policy.name
        )
        if existing is None:
            self.store.create(policy)
        else:
            policy.metadata.resource_version = existing.metadata.resource_version
            self.store.update(policy)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.store.delete(kind, namespace, name)

    # -- observability ------------------------------------------------------
    def metrics_dump(self) -> str:
        """Prometheus text exposition of every registered metric."""
        from karmada_tpu.utils.metrics import REGISTRY

        return REGISTRY.dump()

    def events(self, kind=None, namespace=None, name=None):
        return self.recorder.list(kind=kind, namespace=namespace, name=name)

    # -- clock --------------------------------------------------------------
    def tick(self, rounds: int = 3) -> int:
        """One deterministic round: member simulators advance, statuses are
        collected, and every controller queue drains to quiescence."""
        total = 0
        for _ in range(rounds):
            for member in self.members.values():
                member.tick()
            total += self.runtime.tick()
        return total
