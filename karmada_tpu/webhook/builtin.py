"""Built-in admission plugins, mirroring the reference karmada-webhook set.

Covered (reference pkg/webhook/<kind>/{mutating,validating}.go):
  * PropagationPolicy / ClusterPropagationPolicy — placement validation
    (spread-constraint min<=max, static weights positive, toleration
    seconds non-negative, preemption enum) + defaulting.
  * OverridePolicy / ClusterOverridePolicy — overrider plausibility.
  * FederatedResourceQuota — overall quantities non-negative; static
    assignments within overall.
  * ResourceBinding — FederatedResourceQuota ENFORCEMENT (the reference's
    pkg/webhook/resourcebinding/validating.go quota gate behind the
    FederatedQuotaEnforcement feature gate): the scheduler's .spec.clusters
    patch is denied when the namespace's quota would be exceeded, and FRQ
    overallUsed is bumped atomically on success.
"""

from __future__ import annotations

from typing import Dict, Optional

from karmada_tpu.models.config import ResourceInterpreterWebhook
from karmada_tpu.models.extras import FederatedResourceQuota
from karmada_tpu.models.policy import (
    ClusterOverridePolicy,
    ClusterPropagationPolicy,
    OverridePolicy,
    PropagationPolicy,
)
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.utils.features import GATES, FeatureGates
from karmada_tpu.utils.quantity import Quantity
from karmada_tpu.webhook.admission import OP_CREATE, AdmissionRegistry


# -- PropagationPolicy ------------------------------------------------------


def _validate_placement(placement) -> Optional[str]:
    if placement is None:
        return None
    for sc in placement.spread_constraints:
        if sc.min_groups < 0 or sc.max_groups < 0:
            return "spreadConstraint groups must be non-negative"
        if sc.max_groups and sc.min_groups and sc.max_groups < sc.min_groups:
            return "spreadConstraint maxGroups lower than minGroups"
        if sc.spread_by_field and sc.spread_by_label:
            return "spreadByField and spreadByLabel are mutually exclusive"
    for tol in placement.cluster_tolerations:
        if tol.toleration_seconds is not None and tol.toleration_seconds < 0:
            return "tolerationSeconds must be non-negative"
    rs = placement.replica_scheduling
    if rs is not None and rs.weight_preference is not None:
        for w in rs.weight_preference.static_weight_list:
            if w.weight < 0:
                return "staticWeightList weight must be non-negative"
    return None


def validate_propagation_policy(op, p, old) -> Optional[str]:
    if not p.spec.resource_selectors:
        return "resourceSelectors must not be empty"
    if p.spec.preemption not in ("", "Never", "Always"):
        return f"invalid preemption {p.spec.preemption!r}"
    if p.spec.activation_preference not in ("", "Lazy"):
        return f"invalid activationPreference {p.spec.activation_preference!r}"
    return _validate_placement(p.spec.placement)


class DefaultPropagationPolicy:
    """Mutating defaults (pkg/webhook/propagationpolicy/mutating.go),
    including the default NoExecute tolerations for the not-ready and
    unreachable cluster taints (webhook flags
    --default-not-ready-toleration-seconds /
    --default-unreachable-toleration-seconds, 300s): a briefly-flapping
    cluster must not evict workloads the moment it is tainted."""

    NOT_READY = "cluster.karmada.io/not-ready"
    UNREACHABLE = "cluster.karmada.io/unreachable"

    def __init__(self, toleration_seconds: Optional[int] = 300) -> None:
        self.toleration_seconds = toleration_seconds

    def __call__(self, op, p, old) -> None:
        from karmada_tpu.models.policy import Toleration

        if not p.spec.preemption:
            p.spec.preemption = "Never"
        if p.spec.conflict_resolution not in ("Abort", "Overwrite"):
            p.spec.conflict_resolution = "Abort"
        placement = p.spec.placement
        if placement is None or self.toleration_seconds is None:
            return
        present = {t.key for t in placement.cluster_tolerations}
        for key in (self.NOT_READY, self.UNREACHABLE):
            if key not in present:
                placement.cluster_tolerations.append(Toleration(
                    key=key, operator="Exists", effect="NoExecute",
                    toleration_seconds=self.toleration_seconds,
                ))


def default_propagation_policy(op, p, old) -> None:
    """Module-level default chain with the reference's 300s tolerations."""
    DefaultPropagationPolicy()(op, p, old)


# -- OverridePolicy ---------------------------------------------------------


def validate_override_policy(op, p, old) -> Optional[str]:
    for rule in getattr(p.spec, "override_rules", []):
        ov = rule.overriders
        if ov is None:
            continue
        for po in ov.plaintext:
            if po.operator not in ("add", "remove", "replace"):
                return f"invalid plaintext operator {po.operator!r}"
        for io in ov.image_overrider:
            if io.operator not in ("add", "remove", "replace"):
                return f"invalid imageOverrider operator {io.operator!r}"
    return None


# -- FederatedResourceQuota -------------------------------------------------


def validate_interpreter_webhook(op, w, old) -> Optional[str]:
    """ResourceInterpreterWebhook admission (the reference validates these
    in cmd/webhook, webhook.go:186-232): endpoint scheme + non-empty rules
    with explicit wildcards, so a half-built config can never silently
    hijack interpretation (interpreter/webhook._rule_matches)."""
    spec = w.spec
    if not spec.endpoint:
        return "endpoint must not be empty"
    if not (spec.endpoint.startswith("http://")
            or spec.endpoint.startswith("local:")):
        return f"unsupported endpoint scheme {spec.endpoint!r}"
    if not spec.rules:
        return "rules must not be empty"
    for rule in spec.rules:
        if not rule.api_versions or not rule.kinds or not rule.operations:
            return ("every rule needs explicit apiVersions, kinds and "
                    "operations (use \"*\" for wildcard)")
    if spec.timeout_s <= 0:
        return "timeout_s must be positive"
    return None


def validate_frq(op, q, old) -> Optional[str]:
    for name, qty in q.spec.overall.items():
        if qty.milli < 0:
            return f"overall[{name}] must be non-negative"
    summed: Dict[str, int] = {}
    for sa in q.spec.static_assignments:
        for name, qty in sa.hard.items():
            if qty.milli < 0:
                return f"staticAssignments[{sa.cluster_name}][{name}] must be non-negative"
            summed[name] = summed.get(name, 0) + qty.milli
    # the SUM of the static split must stay within overall, or the object
    # distributes more hard quota than it guarantees
    for name, total in summed.items():
        if name in q.spec.overall and total > q.spec.overall[name].milli:
            return f"staticAssignments sum for {name} exceeds overall"
    return None


def validate_federated_hpa(op, hpa, old) -> Optional[str]:
    """FederatedHPA admission (reference pkg/webhook/federatedhpa):
    structural bounds plus metric-target coherence — a target whose type
    doesn't match its set value field would otherwise silently hold the
    workload at current replicas forever (controllers/federatedhpa.py
    refuses to guess)."""
    from karmada_tpu.models.autoscaling import (
        TARGET_AVERAGE_VALUE,
        TARGET_UTILIZATION,
        TARGET_VALUE,
    )

    s = hpa.spec
    if s.max_replicas < 1:
        return "maxReplicas must be >= 1"
    if s.min_replicas < 1 or s.min_replicas > s.max_replicas:
        return "minReplicas must be in [1, maxReplicas]"
    if not s.scale_target_ref.kind or not s.scale_target_ref.name:
        return "scaleTargetRef.kind and .name are required"

    def check_target(where: str, target, allowed) -> Optional[str]:
        if target.type not in allowed:
            return (f"{where}: target type {target.type!r} not supported "
                    f"(allowed: {sorted(allowed)})")
        field_of = {TARGET_UTILIZATION: target.average_utilization,
                    TARGET_AVERAGE_VALUE: target.average_value,
                    TARGET_VALUE: target.value}
        if field_of[target.type] is None:
            return (f"{where}: target type {target.type!r} requires its "
                    "matching value field")
        if field_of[target.type] <= 0:
            return f"{where}: target value must be positive"
        return None

    for i, m in enumerate(s.metrics):
        where = f"metrics[{i}]"
        if m.resource is not None:
            err = check_target(where, m.resource.target,
                               {TARGET_UTILIZATION, TARGET_AVERAGE_VALUE})
        elif m.pods is not None:
            if not m.pods.metric:
                return f"{where}: pods.metric name is required"
            err = check_target(where, m.pods.target, {TARGET_AVERAGE_VALUE})
        elif m.object is not None:
            if not m.object.metric or not m.object.described_object.name:
                return f"{where}: object.metric and describedObject required"
            err = check_target(where, m.object.target,
                               {TARGET_VALUE, TARGET_AVERAGE_VALUE})
        elif m.external is not None:
            if not m.external.metric:
                return f"{where}: external.metric name is required"
            err = check_target(where, m.external.target,
                               {TARGET_VALUE, TARGET_AVERAGE_VALUE})
        else:
            return f"{where}: one of resource/pods/object/external required"
        if err:
            return err
    return None


# -- ResourceBinding: FederatedResourceQuota enforcement --------------------


def calculate_rb_usage(rb: ResourceBinding) -> Dict[str, int]:
    """helper.CalculateResourceUsage: scheduled replicas x per-replica
    request, in milli units.  Multi-component bindings count each
    component's replicas per scheduled set."""
    total = sum(tc.replicas for tc in rb.spec.clusters)
    usage: Dict[str, int] = {}
    if rb.spec.components:
        for comp in rb.spec.components:
            req = comp.replica_requirements
            if req is None:
                continue
            for name, qty in req.resource_request.items():
                usage[name] = usage.get(name, 0) + total * comp.replicas * qty.milli
        return usage
    req = rb.spec.replica_requirements
    if req is None:
        return usage
    for name, qty in req.resource_request.items():
        usage[name] = usage.get(name, 0) + total * qty.milli
    return usage


class QuotaEnforcer:
    """The FederatedQuotaEnforcement gate (validating.go:111-160).

    Denies a ResourceBinding write whose usage DELTA would push any
    namespace FederatedResourceQuota past spec.overall, and bumps
    status.overall_used on allowed writes.  Runs inside the store write
    lock, so check-and-bump is atomic with the persist.
    """

    def __init__(self, store, gates: Optional[FeatureGates] = None) -> None:
        self.store = store
        self.gates = gates or GATES

    def __call__(self, op, rb: ResourceBinding, old) -> Optional[str]:
        if not self.gates.enabled("FederatedQuotaEnforcement"):
            return None
        if op == OP_CREATE and not rb.spec.clusters:
            return None  # not yet scheduled
        new_usage = calculate_rb_usage(rb)
        old_usage = calculate_rb_usage(old) if old is not None else {}
        delta = {
            n: new_usage.get(n, 0) - old_usage.get(n, 0)
            for n in set(new_usage) | set(old_usage)
        }
        delta = {n: d for n, d in delta.items() if d != 0}
        if not delta:
            return None
        frqs = self.store.list(FederatedResourceQuota.KIND, rb.metadata.namespace)
        to_bump = []
        for frq in frqs:
            if not frq.spec.overall:
                continue
            if frq.spec.static_assignments:
                # static-split quotas are accounted from member-reported
                # ResourceQuota usage (extras.py aggregation path), which
                # would overwrite any bump made here — enforcement covers
                # overall-only quotas, same split as the reference
                continue
            relevant = {n: d for n, d in delta.items() if n in frq.spec.overall}
            if not relevant:
                continue
            for n, d in relevant.items():
                used = frq.status.overall_used.get(n, Quantity(0)).milli
                limit = frq.spec.overall[n].milli
                if used + d > limit:
                    return (
                        f"exceeds FederatedResourceQuota {frq.metadata.name}: "
                        f"{n} used {used}m + delta {d}m > limit {limit}m"
                    )
            to_bump.append((frq, relevant))
        for frq, relevant in to_bump:
            def bump(q, rel=relevant):
                for n, d in rel.items():
                    cur = q.status.overall_used.get(n, Quantity(0))
                    q.status.overall_used[n] = Quantity(cur.milli + d)
            self.store.mutate(
                FederatedResourceQuota.KIND, frq.metadata.namespace,
                frq.metadata.name, bump,
            )
        return None


def install_default_webhooks(
    registry: AdmissionRegistry, store, gates: Optional[FeatureGates] = None,
    default_toleration_seconds: Optional[int] = 300,
) -> None:
    defaulter = DefaultPropagationPolicy(default_toleration_seconds)
    for kind in (PropagationPolicy.KIND, ClusterPropagationPolicy.KIND):
        registry.register_mutating(kind, defaulter)
        registry.register_validating(kind, validate_propagation_policy)
    for kind in (OverridePolicy.KIND, ClusterOverridePolicy.KIND):
        registry.register_validating(kind, validate_override_policy)
    registry.register_validating(FederatedResourceQuota.KIND, validate_frq)
    registry.register_validating(ResourceBinding.KIND, QuotaEnforcer(store, gates))
    registry.register_validating(ResourceInterpreterWebhook.KIND,
                                 validate_interpreter_webhook)
    from karmada_tpu.models.autoscaling import FederatedHPA

    registry.register_validating(FederatedHPA.KIND, validate_federated_hpa)
