from karmada_tpu.webhook.admission import (
    OP_CREATE,
    OP_DELETE,
    OP_UPDATE,
    AdmissionDenied,
    AdmissionRegistry,
)
from karmada_tpu.webhook.builtin import install_default_webhooks

__all__ = [
    "OP_CREATE",
    "OP_DELETE",
    "OP_UPDATE",
    "AdmissionDenied",
    "AdmissionRegistry",
    "install_default_webhooks",
]
