"""Admission framework: mutate/validate every store write.

The reference runs a dedicated karmada-webhook binary serving mutating +
validating admission for each policy CRD (cmd/webhook/app/webhook.go:186-232,
pkg/webhook/).  Here admission is an in-process chain the ObjectStore invokes
synchronously inside its write path — the same semantics (reject before
persist, mutate before validate) without the HTTPS hop.

Plugins are plain callables:

    mutator(op, obj, old)  -> None        (modify obj in place)
    validator(op, obj, old) -> Optional[str]  (non-None message == denial)

registered per kind.  `AdmissionDenied` raised from a write carries the
first denial message.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

OP_CREATE = "CREATE"
OP_UPDATE = "UPDATE"
OP_DELETE = "DELETE"

Mutator = Callable[[str, object, Optional[object]], None]
Validator = Callable[[str, object, Optional[object]], Optional[str]]


class AdmissionDenied(Exception):
    """A validating webhook rejected the write (admission.Denied)."""


class AdmissionRegistry:
    def __init__(self) -> None:
        self._mutators: Dict[str, List[Mutator]] = {}
        self._validators: Dict[str, List[Validator]] = {}

    def register_mutating(self, kind: str, fn: Mutator) -> None:
        self._mutators.setdefault(kind, []).append(fn)

    def register_validating(self, kind: str, fn: Validator) -> None:
        self._validators.setdefault(kind, []).append(fn)

    def admit(self, op: str, obj, old=None) -> None:
        """Mutators first (in registration order), then validators.

        Raises AdmissionDenied on the first validator returning a message.
        Runs inside the store's write lock: plugins may read the store
        (re-entrant lock) but must keep writes to non-hooked kinds to avoid
        unbounded recursion.
        """
        kind = obj.KIND
        for m in self._mutators.get(kind, []):
            m(op, obj, old)
        for v in self._validators.get(kind, []):
            msg = v(op, obj, old)
            if msg:
                raise AdmissionDenied(f"{kind} {obj.metadata.name}: {msg}")
