"""karmada-agent: the PULL-mode member runtime.

Reference: cmd/agent/app/agent.go:140-145 — in pull mode the member
cluster is unreachable from the control plane; an agent INSIDE the member
watches the karmada control plane instead and runs, locally:
clusterStatus, execution (apply Works), and workStatus (reflect status)
controllers, plus certificate rotation for its own credentials.

This module composes the framework's controllers scoped to exactly one
member (each controller acts only on clusters in its `members` dict, so a
per-member instance is the agent): the control-plane push controllers
skip Pull clusters entirely (they could not reach them), and the agent's
scoped instances drive the same Work/status machinery from the member's
side.  The data flow is identical either way — SURVEY §2.9: push vs pull
only inverts who drives the member-cluster writes.

The agent owns TWO liveness loops of its own, like the reference binary:
* its scoped ClusterStatusController renews the cluster Lease every
  collection round (cluster_status_controller.go:399 initLeaseController
  — the lease is the AGENT's heartbeat; controllers/lease.py's monitor
  degrades the cluster to Ready=Unknown when it goes stale), and
* a cert-rotation loop scoped to its OWN ClusterCredential
  (cert_rotation_controller.go:89 runs inside the agent, not the
  control-plane manager).
"""

from __future__ import annotations

import time
from typing import Optional

from karmada_tpu.controllers.certificates import CertRotationController
from karmada_tpu.controllers.execution import ExecutionController
from karmada_tpu.controllers.status import (
    ClusterStatusController,
    WorkStatusController,
)
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


class KarmadaAgent:
    """One agent per pull-mode member cluster."""

    def __init__(
        self,
        control_store: ObjectStore,
        member: FakeMemberCluster,
        runtime: Runtime,
        interpreter: Optional[ResourceInterpreter] = None,
        recorder=None,
        clock=None,
    ) -> None:
        self.member = member
        scoped = {member.name: member}
        # the same controller implementations the push plane runs, scoped
        # to this one member — agent.go registers the identical set.  The
        # agent is its own binary in the reference with its own controller
        # flag, so the control plane's --controllers list must not govern
        # these registrations.
        with runtime.ungoverned():
            self.execution = ExecutionController(
                control_store, runtime, scoped, interpreter, recorder=recorder
            )
            self.work_status = WorkStatusController(
                control_store, runtime, scoped, interpreter
            )
            self.cluster_status = ClusterStatusController(
                control_store, runtime, scoped, recorder=recorder
            )
            # the agent rotates ITS OWN credential (the reference runs the
            # rotation controller inside the agent binary)
            self.cert_rotation = CertRotationController(
                control_store, runtime, cluster=member.name,
                clock=clock if clock is not None else time.time,
            )
            # endpointslice collection runs INSIDE the member for pull mode
            # (agent.go registers endpointsliceCollect; the control plane
            # cannot watch an unreachable member's slices)
            from karmada_tpu.controllers.mcs import (
                EndpointSliceCollectController,
            )

            self.eps_collect = EndpointSliceCollectController(
                control_store, runtime, scoped,
            )
        self._control_store = control_store
        self._runtime = runtime

    @property
    def cluster_name(self) -> str:
        return self.member.name

    def stop(self) -> None:
        """Full teardown on unregister: workers, periodics, and control-
        plane bus subscriptions all unwind (a long-lived plane repeatedly
        joining/unjoining pull members must not accumulate dead wiring)."""
        self._runtime.unregister(self.execution.worker)
        self._runtime.unregister(self.work_status.worker)
        self._runtime.unregister_periodic(self.cluster_status.collect_all)
        self._runtime.unregister_periodic(self.cert_rotation.run_once)
        self.eps_collect.detach(self._runtime)
        self._control_store.bus.unsubscribe(self.execution._on_event)  # noqa: SLF001
        self._control_store.bus.unsubscribe(self.execution._on_cluster_event)  # noqa: SLF001
        self.execution.members.pop(self.member.name, None)
        self.work_status.members.pop(self.member.name, None)
        self.cluster_status.members.pop(self.member.name, None)
