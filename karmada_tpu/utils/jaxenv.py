"""JAX backend bring-up helpers.

The TPU tunnel in this environment can hang or fail at backend *init*
(importing jax is always fast).  Two traps, both observed in round 1:

- ``xla_bridge.backends()`` initialises EVERY registered PJRT factory even
  under ``JAX_PLATFORMS=cpu``, so a tunnel-backed accelerator plugin can
  hang ``jax.devices()`` indefinitely -> drop non-CPU factories.
- a sitecustomize may import jax before callers run, freezing
  ``jax_platforms`` from the outer environment -> ``config.update`` after
  import.

This is the single shared implementation used by tests/conftest.py,
__graft_entry__.dryrun_multichip, and bench.force_cpu_fallback — private
jax API manipulation lives in exactly one place.
"""

from __future__ import annotations

import os
import warnings


def force_cpu(n_devices: int | None = None) -> bool:
    """Pin jax to the host CPU platform, optionally with ``n_devices``
    virtual devices.  Must run before first backend init.

    Returns True if the pin was applied before any backend initialised;
    False (with a warning) if a backend already exists, in which case the
    pin may not take effect.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        kept = [
            f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        kept.append(f"--xla_force_host_platform_device_count={n_devices}")
        os.environ["XLA_FLAGS"] = " ".join(kept)
    try:
        from jax._src import xla_bridge as _xb

        initialized = bool(getattr(_xb, "_backends", None))
        for _name in list(getattr(_xb, "_backend_factories", {})):
            if _name not in ("cpu", "interpreter"):
                _xb._backend_factories.pop(_name, None)
        import jax

        jax.config.update("jax_platforms", "cpu")
        if initialized:
            # a backend that already IS the requested state (CPU platform
            # with at least the requested device count — e.g. the test
            # conftest pinned an 8-device mesh and a caller re-pins for 2)
            # needs no warning: the pin is in effect, just not ours
            devices = jax.devices()
            if devices and devices[0].platform == "cpu" and (
                    n_devices is None or len(devices) >= n_devices):
                return True
            warnings.warn(
                "jax backend already initialized before force_cpu(); the CPU "
                "pin (and any virtual device count) may not take effect",
                stacklevel=2,
            )
            return False
        return True
    # vet: ignore[exception-hygiene] best effort against jax internals; False is the safe answer
    except Exception:  # pragma: no cover - best effort against jax internals
        return False
