"""Out-of-process device-backend health probe + serve-path backend policy.

The accelerator behind this environment's tunnel fails in two modes
(observed across rounds): a fast UNAVAILABLE crash at backend init, and an
uninterruptible in-process hang inside ``jax.devices()``.  Probing in a
SUBPROCESS with a timeout bounds both — importing jax is always fast, only
backend *init* misbehaves.

``resolve_backend`` is the operational policy for long-lived processes
(``karmadactl serve --backend device``): a scheduler asked for the device
backend must degrade to the fastest *working* backend — the native C++
pipeline (~13x faster than XLA:CPU batched on the bench workload) — rather
than silently running the device program on the host CPU.  The batched
scheduler replaces a serial loop (reference:
pkg/scheduler/core/generic_scheduler.go:71-116) and must never be slower
than it, whatever hardware actually answered.
"""

from __future__ import annotations

import subprocess
import sys
import time

# jit one tiny matmul: proves the backend not only initialises but also
# compiles + executes (a half-dead tunnel can pass init and hang dispatch)
_PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "jax.jit(lambda a: a @ a)(jnp.ones((128, 128), jnp.bfloat16))"
    ".block_until_ready();"
    "print('PLATFORM=' + d[0].platform)"
)

# platforms worth running the batched XLA program on; XLA:CPU executes it
# correctly but ~13x slower than the native serial pipeline, so it is never
# the right *production* fallback (tests opt into it explicitly)
ACCELERATOR_PLATFORMS = ("tpu", "gpu", "cuda", "rocm")


def probe_backend(timeout_s: float = 330.0) -> dict:
    """Probe default-backend health out-of-process.

    Returns ``{"ok": bool, "platform": str|None, "attempts": [...]}`` —
    ``ok`` means the subprocess initialised a backend, compiled and ran a
    jit within the budget; ``platform`` is whatever answered (may be
    ``cpu`` when no accelerator is attached).
    """
    diag = {"ok": False, "platform": None, "attempts": []}
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True, text=True, timeout=timeout_s,
        )
        elapsed = round(time.perf_counter() - t0, 1)
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                diag.update(ok=True, platform=line.split("=", 1)[1])
                diag["attempts"].append({"ok": True, "s": elapsed})
                return diag
        diag["attempts"].append({
            "ok": False, "s": elapsed, "rc": r.returncode,
            "err": (r.stderr or r.stdout)[-400:],
        })
    except subprocess.TimeoutExpired:
        diag["attempts"].append({
            "ok": False, "s": round(time.perf_counter() - t0, 1),
            "err": f"probe timed out after {timeout_s}s (backend init hang)",
        })
    return diag


def resolve_backend(requested: str, probe_timeout_s: float = 240.0,
                    probe=None) -> tuple[str, dict]:
    """Pick the backend a long-lived scheduler should actually run.

    - ``requested != "device"``: returned unchanged, no probe spent.
    - ``requested == "device"``: probe the backend out-of-process.  Only a
      live *accelerator* keeps the device backend; a dead/hung probe — or a
      probe that answered with the host CPU — degrades to ``native`` (the
      compiled C++ pipeline) when the toolchain is available, else
      ``serial``.

    Returns ``(effective_backend, diag)``; ``diag["degraded"]`` explains a
    reroute.  ``probe`` is injectable for tests.
    """
    if requested != "device":
        return requested, {"probed": False}
    diag = dict((probe or probe_backend)(timeout_s=probe_timeout_s))
    platform = str(diag.get("platform") or "").lower()
    if diag.get("ok") and any(p in platform for p in ACCELERATOR_PLATFORMS):
        return "device", diag
    from karmada_tpu import native

    if diag.get("ok"):
        # XLA works but only on the host CPU: the native C++ pipeline is
        # ~13x faster than the batched XLA program there — but the XLA
        # program still beats the pure-Python serial loop (~4x), so
        # without the native toolchain the device backend stays the best
        # working choice
        if not native.available():
            return "device", diag
        fallback = "native"
        why = f"device probe answered platform={platform!r} (no accelerator)"
    else:
        # the backend is dead or hung: the device backend is unusable at
        # any speed; take the fastest engine that doesn't need it
        fallback = "native" if native.available() else "serial"
        why = "device probe failed"
    diag["degraded"] = (
        f"{why}; the XLA program on host CPU is slower than the {fallback} "
        f"backend — rerouting to backend={fallback}")
    return fallback, diag
