"""Out-of-process device-backend health probe + serve-path backend policy.

The accelerator behind this environment's tunnel fails in two modes
(observed across rounds): a fast UNAVAILABLE crash at backend init, and an
uninterruptible in-process hang inside ``jax.devices()``.  Probing in a
SUBPROCESS with a timeout bounds both — importing jax is always fast, only
backend *init* misbehaves.

``resolve_backend`` is the operational policy for long-lived processes
(``karmadactl serve --backend device``): a scheduler asked for the device
backend must degrade to the fastest *working* backend — the native C++
pipeline (~13x faster than XLA:CPU batched on the bench workload) — rather
than silently running the device program on the host CPU.  The batched
scheduler replaces a serial loop (reference:
pkg/scheduler/core/generic_scheduler.go:71-116) and must never be slower
than it, whatever hardware actually answered.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

from karmada_tpu.utils.metrics import REGISTRY

# -- probe history (the "chip never answered" condition, made visible) -------
# The watcher log was the ONLY place 77 consecutive probe timeouts ever
# appeared; these export the same trajectory from the serve path: last
# outcome + a consecutive-failure counter in /metrics, and a structured
# snapshot in /debug/state (utils/httpserve pulls last_probe()).
PROBE_ATTEMPTS = REGISTRY.counter(
    "karmada_device_probe_attempts_total",
    "Device-backend health probe attempts by outcome",
    ("ok",),
)
PROBE_LAST_OK = REGISTRY.gauge(
    "karmada_device_probe_last_ok",
    "1 when the most recent device probe answered, else 0",
)
PROBE_LAST_ELAPSED = REGISTRY.gauge(
    "karmada_device_probe_last_elapsed_seconds",
    "Wall time of the most recent device probe attempt",
)
PROBE_CONSECUTIVE_FAILURES = REGISTRY.gauge(
    "karmada_device_probe_consecutive_failures",
    "Probe failures since the last success (the chip-never-answered "
    "trajectory)",
)

_LAST_LOCK = threading.Lock()
# guarded-by: _LAST_LOCK
_LAST: dict = {"probed": False, "ok": None, "platform": None,
               "devices": None, "elapsed_s": None,
               "consecutive_failures": 0, "at_unix": None, "error": None}


def record_probe(diag: dict) -> None:
    """Fold one probe_backend() result into the exported history."""
    attempts = diag.get("attempts") or []
    last = attempts[-1] if attempts else {}
    ok = bool(diag.get("ok"))
    with _LAST_LOCK:
        _LAST.update(
            probed=True, ok=ok,
            platform=diag.get("platform"),
            devices=diag.get("device_count"),
            elapsed_s=last.get("s"),
            at_unix=round(time.time(), 3),
            error=None if ok else str(last.get("err", ""))[:200],
        )
        _LAST["consecutive_failures"] = (
            0 if ok else _LAST["consecutive_failures"] + 1)
        streak = _LAST["consecutive_failures"]
    PROBE_ATTEMPTS.inc(ok=str(ok).lower())
    PROBE_LAST_OK.set(1.0 if ok else 0.0)
    if last.get("s") is not None:
        PROBE_LAST_ELAPSED.set(float(last["s"]))
    PROBE_CONSECUTIVE_FAILURES.set(streak)


def last_probe() -> dict:
    """Snapshot of the most recent probe outcome (for /debug/state)."""
    with _LAST_LOCK:
        return dict(_LAST)

# jit one tiny matmul: proves the backend not only initialises but also
# compiles + executes (a half-dead tunnel can pass init and hang dispatch).
# NDEV makes the probe topology-aware: the mesh-sharded solve path
# (ops/meshing) and the watcher/bench payloads report how many chips
# actually answered, not just that one did.  MEMSTATS carries each
# device's memory_stats() (post-jit, so HBM in-use reflects a live
# executable) — device-memory visibility across chip windows for the
# telemetry plane; null per device on backends that report none
# (XLA:CPU).
_PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp, json;"
    "d = jax.devices();"
    "jax.jit(lambda a: a @ a)(jnp.ones((128, 128), jnp.bfloat16))"
    ".block_until_ready();"
    "print('PLATFORM=' + d[0].platform);"
    "print('NDEV=' + str(len(d)));"
    "ms = [];\n"
    "for dev in d:\n"
    "    try:\n"
    "        s = dev.memory_stats()\n"
    "    except Exception:\n"
    "        s = None\n"
    "    ms.append({'device': f'{dev.platform}:{dev.id}',\n"
    "               'memory_stats': ({k: int(v) for k, v in s.items()}\n"
    "                                if s else None)})\n"
    "print('MEMSTATS=' + json.dumps(ms))"
)

# platforms worth running the batched XLA program on; XLA:CPU executes it
# correctly but ~13x slower than the native serial pipeline, so it is never
# the right *production* fallback (tests opt into it explicitly)
ACCELERATOR_PLATFORMS = ("tpu", "gpu", "cuda", "rocm")


def probe_backend(timeout_s: float = 330.0) -> dict:
    """Probe default-backend health out-of-process.

    Returns ``{"ok": bool, "platform": str|None, "device_count": int|None,
    "attempts": [...]}`` — ``ok`` means the subprocess initialised a
    backend, compiled and ran a jit within the budget; ``platform`` is
    whatever answered (may be ``cpu`` when no accelerator is attached);
    ``device_count`` is how many devices it exposed (the mesh-sharded
    solve's scale axis).
    """
    diag = {"ok": False, "platform": None, "device_count": None,
            "memory_stats": None, "attempts": []}
    t0 = time.perf_counter()
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True, text=True, timeout=timeout_s,
        )
        elapsed = round(time.perf_counter() - t0, 1)
        for line in r.stdout.splitlines():
            if line.startswith("NDEV="):
                try:
                    diag["device_count"] = int(line.split("=", 1)[1])
                except ValueError:
                    pass
            elif line.startswith("MEMSTATS="):
                import json as _json

                try:
                    diag["memory_stats"] = _json.loads(line.split("=", 1)[1])
                except ValueError:
                    pass
        for line in r.stdout.splitlines():
            if line.startswith("PLATFORM="):
                diag.update(ok=True, platform=line.split("=", 1)[1])
                diag["attempts"].append({"ok": True, "s": elapsed})
                record_probe(diag)
                return diag
        diag["attempts"].append({
            "ok": False, "s": elapsed, "rc": r.returncode,
            "err": (r.stderr or r.stdout)[-400:],
        })
    except subprocess.TimeoutExpired:
        diag["attempts"].append({
            "ok": False, "s": round(time.perf_counter() - t0, 1),
            "err": f"probe timed out after {timeout_s}s (backend init hang)",
        })
    record_probe(diag)
    return diag


def resolve_backend(requested: str, probe_timeout_s: float = 240.0,
                    probe=None) -> tuple[str, dict]:
    """Pick the backend a long-lived scheduler should actually run.

    - ``requested != "device"``: returned unchanged, no probe spent.
    - ``requested == "device"``: probe the backend out-of-process.  Only a
      live *accelerator* keeps the device backend; a dead/hung probe — or a
      probe that answered with the host CPU — degrades to ``native`` (the
      compiled C++ pipeline) when the toolchain is available, else
      ``serial``.

    Returns ``(effective_backend, diag)``; ``diag["degraded"]`` explains a
    reroute.  ``probe`` is injectable for tests.
    """
    if requested != "device":
        return requested, {"probed": False}
    diag = dict((probe or probe_backend)(timeout_s=probe_timeout_s))
    if probe is not None:
        # probe_backend records its own history; an injected probe's
        # outcome must reach the exported trajectory the same way
        record_probe(diag)
    platform = str(diag.get("platform") or "").lower()
    if diag.get("ok") and any(p in platform for p in ACCELERATOR_PLATFORMS):
        return "device", diag
    from karmada_tpu import native

    if diag.get("ok"):
        # XLA works but only on the host CPU: the native C++ pipeline is
        # ~13x faster than the batched XLA program there — but the XLA
        # program still beats the pure-Python serial loop (~4x), so
        # without the native toolchain the device backend stays the best
        # working choice
        if not native.available():
            return "device", diag
        fallback = "native"
        why = f"device probe answered platform={platform!r} (no accelerator)"
    else:
        # the backend is dead or hung: the device backend is unusable at
        # any speed; take the fastest engine that doesn't need it
        fallback = "native" if native.available() else "serial"
        why = "device probe failed"
    diag["degraded"] = (
        f"{why}; the XLA program on host CPU is slower than the {fallback} "
        f"backend — rerouting to backend={fallback}")
    return fallback, diag
