"""Prometheus-style metrics primitives (counter / gauge / histogram).

The reference instruments every component with prometheus client_golang
(pkg/scheduler/metrics/metrics.go:60-142, pkg/metrics/cluster.go:57-132,
pkg/util/metrics/); this module is the framework's equivalent: a small
threadsafe registry with the same metric shapes (labeled counters,
gauges, exponential-bucket histograms) and a text exposition dump.

No external dependency: the scrape surface is `Registry.dump()` (the
Prometheus text format) so an HTTP handler or the bench can expose it.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * (factor ** i) for i in range(count)]


def quantile_from_buckets(bounds: Sequence[float], cum_counts: Sequence[int],
                          total: int, q: float) -> float:
    """Bucket-resolution quantile estimate from CUMULATIVE bucket counts
    (the shape Histogram keeps internally and Registry.snapshot()
    exposes).  Shared by Histogram.quantile, the SLO evaluator's
    windowed bucket-delta math (obs/slo), and the `karmadactl top`
    dashboard — one estimator, one bias (the returned value is the upper
    bound of the bucket the rank lands in; +inf past the last bound)."""
    if total <= 0:
        return math.nan
    rank = q * total
    for bound, c in zip(bounds, cum_counts):
        if c >= rank:
            return bound
    return math.inf


def _escape_label(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline would otherwise break the exposition line."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """# HELP line escaping (backslash and newline per the text format)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]) -> None:
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != {sorted(self.label_names)}"
            )
        return tuple(labels[n] for n in self.label_names)

    @staticmethod
    def _fmt_labels(names: Sequence[str], values: Sequence[str],
                    extra: Optional[Tuple[str, str]] = None) -> str:
        pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
        if extra is not None:
            pairs.append(f'{extra[0]}="{_escape_label(extra[1])}"')
        return "{" + ",".join(pairs) + "}" if pairs else ""


class _ScalarMetric(_Metric):
    """Shared one-value-per-label-set storage (Counter / Gauge): the
    render and snapshot shapes must never drift between the two."""

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}  # guarded-by: _lock

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _render(self) -> List[str]:
        with self._lock:
            return [
                f"{self.name}{self._fmt_labels(self.label_names, k)} {v}"
                for k, v in sorted(self._values.items())
            ]

    def _snap(self) -> List[dict]:
        with self._lock:
            return [{"labels": list(k), "value": v}
                    for k, v in sorted(self._values.items())]


class Counter(_ScalarMetric):
    TYPE = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def total(self) -> float:
        """Sum across every label combination (delta accounting for the
        chaos safety auditor, which cannot enumerate label values that
        only exist after faults fire)."""
        with self._lock:
            return sum(self._values.values())


class Gauge(_ScalarMetric):
    TYPE = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def add(self, amount: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Histogram(_Metric):
    TYPE = "histogram"

    def __init__(self, name, help_, label_names=(), buckets: Optional[List[float]] = None):
        super().__init__(name, help_, label_names)
        # exposition edge cases hardened while wiring GET /metrics:
        # duplicate bucket bounds would double-count an observation into
        # two identical `le` lines, and a caller-supplied +Inf bound would
        # collide with the synthetic +Inf line _render always emits —
        # dedupe and keep finite bounds only (int bounds coerce to float
        # so `le` renders uniformly, e.g. le="1.0")
        self.buckets = sorted({
            float(b) for b in (buckets or exponential_buckets(0.001, 2, 15))
            if math.isfinite(b)
        })
        self._counts: Dict[Tuple[str, ...], List[int]] = {}  # guarded-by: _lock
        self._sums: Dict[Tuple[str, ...], float] = {}  # guarded-by: _lock
        self._totals: Dict[Tuple[str, ...], int] = {}  # guarded-by: _lock

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(self._key(labels), 0)

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def quantile(self, q: float, **labels: str) -> float:
        """Bucket-resolution quantile estimate (for dumps/tests)."""
        key = self._key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            counts = list(self._counts.get(key, []))
        return quantile_from_buckets(self.buckets, counts, total, q)

    def _snap(self) -> List[dict]:
        with self._lock:
            return [{"labels": list(k),
                     "count": self._totals[k],
                     "sum": self._sums[k],
                     "buckets": list(self._counts.get(k, []))}
                    for k in sorted(self._totals)]

    def _render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            for key in sorted(self._totals):
                for i, ub in enumerate(self.buckets):
                    out.append(
                        f"{self.name}_bucket"
                        f"{self._fmt_labels(self.label_names, key, ('le', repr(ub)))}"
                        f" {self._counts[key][i]}"
                    )
                out.append(
                    f"{self.name}_bucket"
                    f"{self._fmt_labels(self.label_names, key, ('le', '+Inf'))}"
                    f" {self._totals[key]}"
                )
                out.append(
                    f"{self.name}_sum{self._fmt_labels(self.label_names, key)}"
                    f" {self._sums[key]}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_labels(self.label_names, key)}"
                    f" {self._totals[key]}"
                )
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self.register(Counter(name, help_, label_names))  # type: ignore[return-value]

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self.register(Gauge(name, help_, label_names))  # type: ignore[return-value]

    def histogram(self, name, help_="", label_names=(), buckets=None) -> Histogram:
        return self.register(Histogram(name, help_, label_names, buckets))  # type: ignore[return-value]

    def dump(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}".rstrip())
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            lines.extend(m._render())  # noqa: SLF001
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, dict]:
        """Structured point-in-time view of every family — the telemetry
        plane's sampling surface (obs/timeseries), read under the same
        locks `dump()` renders under, with NO text-format round trip:

            {name: {"type": counter|gauge|histogram,
                    "help": str,
                    "labels": [label names...],
                    # counters/gauges:
                    "samples": [{"labels": [values...], "value": float}],
                    # histograms instead:
                    "bounds": [finite upper bounds...],
                    "samples": [{"labels": [...], "count": int,
                                 "sum": float,
                                 "buckets": [cumulative counts...]}]}}

        Histogram bucket counts are CUMULATIVE (the internal shape), so
        windowed deltas between two snapshots stay valid bucket arrays
        and feed `quantile_from_buckets` directly.  `dump()` stays the
        only text exposition; the two are regression-tested for
        consistency (tests/test_telemetry.py)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: Dict[str, dict] = {}
        for m in metrics:
            fam: Dict[str, object] = {
                "type": m.TYPE,
                "help": m.help,
                "labels": list(m.label_names),
                "samples": m._snap(),  # noqa: SLF001 — registry owner
            }
            if isinstance(m, Histogram):
                fam["bounds"] = list(m.buckets)
            out[m.name] = fam
        return out


# the default registry every component instruments into (the reference's
# controller-runtime metrics.Registry equivalent)
REGISTRY = Registry()
