"""Armed runtime lock instrumentation — the dynamic half of the
concurrency vet (static half: analysis/lock_order.py).

``VetLock`` is a drop-in proxy over ``threading.Lock``/``RLock`` that
shares the one arming flag with analysis/guards (``serve
--check-invariants`` / KARMADA_CHECK_INVARIANTS=1).  Disarmed, every
acquire/release is one list read plus delegation to the wrapped stdlib
lock — cheap enough to live on the production serve paths (bench gates
it at <1% of a mean scheduling cycle).  Armed, it records:

  * per-thread OWNERSHIP — ``require_held()`` raises
    guards.InvariantViolation when `guarded-by:`-annotated state is
    touched off-lock (the runtime teeth behind the static annotation);
  * ACQUISITION ORDER — first-seen lock-pair edges; observing B-then-A
    after A-then-B is a runtime order inversion, counted in
    ``karmada_lock_order_inversions_total{pair}`` (the dynamic
    complement of the static lock-order cycle report);
  * HOLD TIME — ``karmada_lock_hold_seconds{lock}`` observed at release,
    and a ``LockWatchdog`` that trips
    ``karmada_lock_watchdog_trips_total{lock}`` once per over-threshold
    hold (a wedged thread surfaces as a counter, not a silent hang).

``OwnerThread`` is the zero-lock variant for planes that are
single-threaded BY CONTRACT (scheduler/incremental): first toucher wins,
any other thread raises.  ``state_payload()`` feeds the ``locks`` block
of ``/debug/state``.

Bookkeeping uses a PLAIN ``threading.Lock`` registry lock and plain
thread-locals: the detector must never instrument itself (a VetLock
inside the edge table would recurse).  Known limitation: arming or
disarming while locks are held strands per-thread stack entries —
toggle only from quiescent code (tests arm before spawning threads).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from karmada_tpu.analysis import guards
from karmada_tpu.utils.metrics import REGISTRY

#: injectable clock (tests stall the watchdog deterministically)
_CLOCK = [time.monotonic]


def set_clock(fn=None) -> None:
    """Swap the module clock (None restores time.monotonic)."""
    _CLOCK[0] = fn if fn is not None else time.monotonic


_HOLD = REGISTRY.histogram(
    "karmada_lock_hold_seconds",
    "Lock hold time observed at release while the runtime race "
    "detector is armed (utils/locks.VetLock)",
    ("lock",))
_INVERSIONS = REGISTRY.counter(
    "karmada_lock_order_inversions_total",
    "Runtime lock-acquisition order inversions: the armed detector saw "
    "pair A-then-B and later B-then-A (pair label is the sorted lock "
    "names joined with '|')",
    ("pair",))
_TRIPS = REGISTRY.counter(
    "karmada_lock_watchdog_trips_total",
    "Deadlock-watchdog trips: a lock held longer than the watchdog "
    "threshold (once per over-threshold hold)",
    ("lock",))

# detector bookkeeping — PLAIN lock, never a VetLock (no self-tracing)
_REG_LOCK = threading.Lock()
_ALL: "weakref.WeakSet[VetLock]" = weakref.WeakSet()  # guarded-by: _REG_LOCK
_EDGES: Dict[Tuple[str, str], int] = {}  # guarded-by: _REG_LOCK
_INV_DETAILS: deque = deque(maxlen=32)  # guarded-by: _REG_LOCK
_OWNERS: "weakref.WeakSet[OwnerThread]" = weakref.WeakSet()  # guarded-by: _REG_LOCK

_TLS = threading.local()


def _held_stack() -> list:
    stack = getattr(_TLS, "held", None)
    if stack is None:
        stack = _TLS.held = []
    return stack


def _note_edge(first: str, then: str, thread_name: str) -> None:
    """Record first-seen order edge (first held when `then` acquired);
    count an inversion when the reverse edge was seen earlier."""
    inv = None
    with _REG_LOCK:
        if (then, first) in _EDGES and (first, then) not in _EDGES:
            pair = "|".join(sorted((first, then)))
            _INVERSIONS.inc(pair=pair)
            inv = {
                "pair": pair, "held": first, "acquired": then,
                "thread": thread_name,
            }
            _INV_DETAILS.append(inv)
        _EDGES[(first, then)] = _EDGES.get((first, then), 0) + 1
    if inv is not None:
        # incident trigger AFTER _REG_LOCK releases: the bundle capture
        # reads state_payload() (which takes _REG_LOCK) — firing under
        # it would deadlock.  Lazy import: utils must not pull obs at
        # module load.
        from karmada_tpu.obs import incidents as obs_incidents

        obs_incidents.trigger(
            obs_incidents.TRIGGER_LOCK_INVERSION,
            f"lock order inversion: {inv['acquired']} acquired while "
            f"{inv['held']} held", detail=inv)


class VetLock:
    """Drop-in lock proxy: ``with lock:`` / acquire / release, plus armed
    ownership + order + hold-time recording.  Not reentrant unless
    constructed with reentrant=True (then wraps an RLock)."""

    def __init__(self, name: str, reentrant: bool = False) -> None:
        self.name = name
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._owner: Optional[int] = None       # thread ident while held
        self._owner_name: str = ""
        self._acquired_at: Optional[float] = None
        self._trip_mark: Optional[float] = None  # watchdog: once per hold
        with _REG_LOCK:
            _ALL.add(self)

    # -- the lock protocol --------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok and guards._ARMED[0]:  # noqa: SLF001 — the one arming flag
            self._on_acquire()
        return ok

    def release(self) -> None:
        if guards._ARMED[0]:  # noqa: SLF001
            self._on_release()
        self._lock.release()

    def __enter__(self) -> "VetLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") \
            else self._owner is not None

    # -- armed bookkeeping --------------------------------------------

    def _on_acquire(self) -> None:
        now = _CLOCK[0]()
        me = threading.current_thread()
        stack = _held_stack()
        if not any(entry[0] is self for entry in stack):
            for other, _t0 in stack:
                if other is not self:
                    _note_edge(other.name, self.name, me.name)
            # outermost acquire starts the hold clock
            self._acquired_at = now
            self._trip_mark = None
        stack.append((self, now))
        self._owner = me.ident
        self._owner_name = me.name

    def _on_release(self) -> None:
        now = _CLOCK[0]()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is self:
                del stack[i]
                break
        else:
            # released by a thread that never recorded the acquire
            # (cross-thread release, or armed mid-hold): clear and move on
            self._owner = None
            self._acquired_at = None
            return
        if not any(entry[0] is self for entry in stack):
            t0 = self._acquired_at
            self._owner = None
            self._acquired_at = None
            if t0 is not None:
                _HOLD.observe(now - t0, lock=self.name)

    # -- the enforcement surface --------------------------------------

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def require_held(self, what: str = "") -> None:
        """Armed: raise unless the CURRENT thread holds this lock — the
        runtime teeth for `guarded-by:` state.  Disarmed: one list read."""
        if not guards._ARMED[0]:  # noqa: SLF001
            return
        if self._owner != threading.get_ident():
            raise guards.InvariantViolation(
                f"{what or 'guarded state'} touched without holding "
                f"`{self.name}` (owner: "
                f"{self._owner_name if self._owner is not None else 'nobody'}"
                f", this thread: {threading.current_thread().name})")


def make_lock(name: str) -> VetLock:
    """Module-global VetLock constructor the static lock-order pass
    recognizes by name."""
    return VetLock(name)


def make_rlock(name: str) -> VetLock:
    return VetLock(name, reentrant=True)


class OwnerThread:
    """Single-thread-ownership assertion for planes that are
    single-threaded by contract: the first thread to call check() owns
    the plane; any other thread raises (armed only).  reset() hands
    ownership to the next toucher (tests, plane rebuilds)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._ident: Optional[int] = None
        self._thread_name: str = ""
        with _REG_LOCK:
            _OWNERS.add(self)

    def check(self, what: str = "") -> None:
        if not guards._ARMED[0]:  # noqa: SLF001 — one list read disarmed
            return
        me = threading.current_thread()
        if self._ident is None:
            self._ident = me.ident
            self._thread_name = me.name
            return
        if me.ident != self._ident:
            raise guards.InvariantViolation(
                f"`{self.name}` is single-threaded by contract (owned by "
                f"thread {self._thread_name}); {what or 'entry'} called "
                f"from thread {me.name}")

    def reset(self) -> None:
        self._ident = None
        self._thread_name = ""


class LockWatchdog:
    """Periodic scan for over-threshold holds.  check() is the
    deterministic single-scan entry (tests drive it with an injected
    clock); start() runs it on a daemon thread for serve processes."""

    def __init__(self, threshold_s: float = 5.0,
                 poll_s: float = 1.0) -> None:
        self.threshold_s = threshold_s
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check(self) -> List[dict]:
        """One scan: trip (once per hold) every armed VetLock held
        longer than threshold_s; returns the trip records."""
        if not guards.armed():
            return []
        now = _CLOCK[0]()
        trips: List[dict] = []
        with _REG_LOCK:
            locks = list(_ALL)
        for lock in locks:
            t0 = lock._acquired_at  # noqa: SLF001 — racy read is fine:
            # a stale value costs one late/early trip, never a crash
            if t0 is None or now - t0 < self.threshold_s:
                continue
            if lock._trip_mark == t0:  # noqa: SLF001
                continue  # already tripped for this hold
            lock._trip_mark = t0  # noqa: SLF001
            _TRIPS.inc(lock=lock.name)
            trips.append({"lock": lock.name, "held_s": now - t0,
                          "owner": lock._owner_name})  # noqa: SLF001
        if trips:
            # _REG_LOCK is NOT held here (released after the _ALL copy);
            # the capture re-takes it for the locks block
            from karmada_tpu.obs import incidents as obs_incidents

            obs_incidents.trigger(
                obs_incidents.TRIGGER_LOCK_WATCHDOG,
                f"{len(trips)} lock(s) held over {self.threshold_s:g}s",
                detail={"threshold_s": self.threshold_s, "trips": trips})
        return trips

    def start(self) -> "LockWatchdog":
        if self._thread is None:
            t = threading.Thread(target=self._run, daemon=True,
                                 name="lock-watchdog")
            self._thread = t
            t.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # vet: ignore[exception-hygiene] watchdog must outlive any scan hiccup; trips are its only output
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


_WATCHDOG: List[Optional[LockWatchdog]] = [None]


def start_watchdog(threshold_s: float = 5.0,
                   poll_s: float = 1.0) -> LockWatchdog:
    """The serve-process singleton (serve --check-invariants)."""
    if _WATCHDOG[0] is None:
        _WATCHDOG[0] = LockWatchdog(threshold_s, poll_s).start()
    return _WATCHDOG[0]


def stop_watchdog() -> None:
    if _WATCHDOG[0] is not None:
        _WATCHDOG[0].stop()
        _WATCHDOG[0] = None


def state_payload() -> dict:
    """The `locks` block of /debug/state (utils/httpserve)."""
    with _REG_LOCK:
        locks = sorted(_ALL, key=lambda lk: lk.name)[:64]
        edges = len(_EDGES)
        details = list(_INV_DETAILS)
        owners = sorted(_OWNERS, key=lambda o: o.name)[:32]
    now = _CLOCK[0]()
    rows = []
    for lk in locks:
        t0 = lk._acquired_at  # noqa: SLF001 — racy snapshot by design
        rows.append({
            "name": lk.name,
            "kind": "rlock" if lk.reentrant else "lock",
            "owner": lk._owner_name if t0 is not None else None,  # noqa: SLF001
            "held_for_s": (now - t0) if t0 is not None else None,
        })
    wd = _WATCHDOG[0]
    return {
        "armed": guards.armed(),
        "locks": rows,
        "owner_threads": [
            {"name": o.name,
             "owner": o._thread_name or None}  # noqa: SLF001
            for o in owners],
        "order_edges": edges,
        "inversions": {
            "total": _INVERSIONS.total(),
            "recent": details,
        },
        "watchdog": {
            "running": wd is not None,
            "threshold_s": wd.threshold_s if wd is not None else None,
            "trips_total": _TRIPS.total(),
        },
    }


def reset_for_tests() -> None:
    """Clear the order-edge table and inversion details (metric counters
    are cumulative; tests assert deltas)."""
    with _REG_LOCK:
        _EDGES.clear()
        _INV_DETAILS.clear()
