"""Shared well-known labels/annotations (reference pkg/util/constants.go)."""

# set on a resource template to make the apply engine keep each member
# cluster's own spec.replicas (member-side HPAs in control): constants.go:62
RETAIN_REPLICAS_LABEL = "resourcetemplate.karmada.io/retain-replicas"
RETAIN_REPLICAS_VALUE = "true"
