"""Kubernetes-style resource quantities with exact integer milli-unit math.

The reference's capacity arithmetic (pkg/estimator/client/general.go:294-334)
operates on `resource.Quantity`: `Value()` (ceiling to whole units) for most
resources and `MilliValue()` for CPU. To keep the TPU solver bit-compatible
we normalise every quantity to an exact integer count of *milli-units* at
parse time; all downstream tensors are integer typed.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

# Binary suffixes (Ki, Mi, ...) and decimal suffixes (k, M, ...) per the
# Kubernetes resource.Quantity grammar.
_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {"n": -3, "u": -2, "m": -1, "": 0, "k": 1, "M": 2, "G": 3, "T": 4, "P": 5, "E": 6}

_QTY_RE = re.compile(r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)$")


@dataclass(frozen=True, order=True)
class Quantity:
    """An exact resource amount stored as integer milli-units."""

    milli: int

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_milli(m: int) -> "Quantity":
        return Quantity(int(m))

    @staticmethod
    def from_units(v: int) -> "Quantity":
        return Quantity(int(v) * 1000)

    @staticmethod
    def parse(s: "str | int | float | Quantity") -> "Quantity":
        return parse_quantity(s)

    # -- accessors (match k8s resource.Quantity semantics) -----------------
    def value(self) -> int:
        """Whole units, rounded up (k8s Quantity.Value())."""
        return -((-self.milli) // 1000)

    def milli_value(self) -> int:
        return self.milli

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli + other.milli)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli - other.milli)

    def __neg__(self) -> "Quantity":
        return Quantity(-self.milli)

    def is_zero(self) -> bool:
        return self.milli == 0

    def __str__(self) -> str:
        if self.milli % 1000 == 0:
            return str(self.milli // 1000)
        return f"{self.milli}m"

    def to_json(self) -> str:
        return str(self)


def parse_quantity(s: "str | int | float | Quantity") -> Quantity:
    """Parse a Kubernetes quantity string ("100m", "2Gi", "1.5", 3) exactly."""
    if isinstance(s, Quantity):
        return s
    if isinstance(s, int):
        return Quantity.from_units(s)
    if isinstance(s, float):
        if s != s or s in (float("inf"), float("-inf")):
            raise ValueError(f"invalid quantity: {s!r}")
        # floats only appear from hand-written configs; route via repr for exactness
        s = repr(s)
    m = _QTY_RE.match(s.strip())
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    num, suffix = m.group(1), m.group(2)
    # milli-units per unit of suffix, as an exact rational scale_num/scale_den
    if suffix in _BIN:
        scale_num, scale_den = 1000 * _BIN[suffix], 1
    elif suffix in _DEC:
        e = 3 * _DEC[suffix] + 3
        scale_num, scale_den = (10**e, 1) if e >= 0 else (1, 10**-e)
    else:
        raise ValueError(f"invalid quantity suffix: {s!r}")

    if "e" in num.lower():
        mantissa, _, exp = num.lower().partition("e")
        exp_i = int(exp)
    else:
        mantissa, exp_i = num, 0

    neg = mantissa.startswith("-")
    mantissa = mantissa.lstrip("+-")
    if "." in mantissa:
        int_part, frac = mantissa.split(".", 1)
    else:
        int_part, frac = mantissa, ""
    digits = (int_part + frac) or "0"
    # milli = digits * 10^(exp_i - len(frac)) * scale_num / scale_den, exact
    power = exp_i - len(frac)
    n = int(digits) * scale_num
    d = scale_den
    if power >= 0:
        n *= 10**power
    else:
        d *= 10**-power
    if n % d == 0:
        n //= d
    else:
        # inexact at milli granularity: k8s rounds away from zero (up for
        # positive quantities) to the smallest representable unit
        n = -((-n) // d)
    if neg:
        n = -n
    return Quantity(n)


# Canonical resource names (mirror corev1.ResourceName usage in the reference)
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"


def resource_request_value(name: str, q: Quantity) -> int:
    """The integer the division math uses: MilliValue for cpu, Value otherwise.

    Mirrors pkg/estimator/client/general.go:296-325.
    """
    return q.milli_value() if name == RESOURCE_CPU else q.value()
