"""Event recorder: the framework's record.EventRecorder equivalent.

The reference emits Kubernetes Events with reasons enumerated in
pkg/events/events.go; controllers here record structured events into a
bounded in-memory journal (duplicate (object, reason, message) events
coalesce with a count, like the apiserver does), queryable by object.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, List, Optional

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

# pkg/events/events.go reasons used by this framework's controllers
REASON_SCHEDULE_BINDING_SUCCEED = "ScheduleBindingSucceed"
REASON_SCHEDULE_BINDING_FAILED = "ScheduleBindingFailed"
REASON_SYNC_WORKLOAD_SUCCEED = "SyncSucceed"
REASON_SYNC_WORKLOAD_FAILED = "SyncFailed"
REASON_WORK_DISPATCHING = "WorkDispatching"
REASON_TAINT_CLUSTER_SUCCEED = "TaintClusterSucceed"
REASON_EVICT_WORKLOAD_FROM_CLUSTER = "EvictWorkloadFromCluster"
REASON_APPLY_POLICY_SUCCEED = "ApplyPolicySucceed"
REASON_REFLECT_STATUS_FAILED = "ReflectStatusFailed"
REASON_CLUSTER_NOT_READY = "ClusterNotReady"
REASON_CLUSTER_READY = "ClusterReady"


@dataclass
class ObjectRef:
    kind: str = ""
    namespace: str = ""
    name: str = ""


@dataclass
class RecordedEvent:
    ref: ObjectRef
    type: str = TYPE_NORMAL
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0


class EventRecorder:
    """Bounded, coalescing event journal."""

    def __init__(self, capacity: int = 4096,
                 now: Callable[[], float] = time.time) -> None:
        self.capacity = capacity
        self.now = now
        self._events: "OrderedDict[tuple, RecordedEvent]" = OrderedDict()
        self._lock = threading.Lock()

    def event(self, obj, type_: str, reason: str, message: str) -> None:
        """Record one event for a typed store object (or an ObjectRef)."""
        if isinstance(obj, ObjectRef):
            ref = obj
        else:
            ref = ObjectRef(kind=obj.KIND, namespace=obj.namespace, name=obj.name)
        key = (ref.kind, ref.namespace, ref.name, type_, reason, message)
        ts = self.now()
        with self._lock:
            ev = self._events.get(key)
            if ev is not None:
                ev.count += 1
                ev.last_timestamp = ts
                self._events.move_to_end(key)
                return
            self._events[key] = RecordedEvent(
                ref=ref, type=type_, reason=reason, message=message,
                first_timestamp=ts, last_timestamp=ts,
            )
            while len(self._events) > self.capacity:
                self._events.popitem(last=False)

    def list(self, kind: Optional[str] = None, namespace: Optional[str] = None,
             name: Optional[str] = None) -> List[RecordedEvent]:
        with self._lock:
            return [
                ev for ev in self._events.values()
                if (kind is None or ev.ref.kind == kind)
                and (namespace is None or ev.ref.namespace == namespace)
                and (name is None or ev.ref.name == name)
            ]
