"""Event recorder: the framework's record.EventRecorder equivalent.

Grown into the lifecycle ledger (karmada_tpu/obs/events.py): a bounded,
coalescing, thread-safe journal with a per-object timeline index, where
every event carries {type, reason, message, origin, cycle_id, trace_id,
decision_id}.  This module re-exports the whole surface so the classic
``from karmada_tpu.utils import events as ev`` import sites keep
working; see obs/events for the ledger itself, /debug/events for the
HTTP surface, and docs/OBSERVABILITY.md for the reason catalog.

A bare ``EventRecorder()`` binds the PROCESS ledger — every controller
shares one unified per-binding timeline; explicit capacity/now yields a
private ledger (test isolation, the pre-ledger semantics).
"""

from karmada_tpu.obs.events import (  # noqa: F401 — the public surface
    EVENTS_DROPPED,
    EVENTS_TOTAL,
    REASON_APPLY_POLICY_SUCCEED,
    REASON_BACKEND_DEGRADED,
    REASON_BACKEND_REARMED,
    REASON_BATCH_FORMED,
    REASON_BINDING_DISPLACED,
    REASON_BINDING_ENQUEUED,
    REASON_BINDING_SHED,
    REASON_CHAOS_FAULT_INJECTED,
    REASON_CLUSTER_NOT_READY,
    REASON_CLUSTER_READY,
    REASON_CLUSTER_STATUS_UNKNOWN,
    REASON_CYCLE_FAULT,
    REASON_EVICT_WORKLOAD_FROM_CLUSTER,
    REASON_EVICTION_BUDGET_DENIED,
    REASON_EVICTION_DEFERRED,
    REASON_EVICTION_PENDING,
    REASON_EVICTION_TASK_DRAINED,
    REASON_HPA_FAST_PATH,
    REASON_OVERLOAD_ENTERED,
    REASON_OVERLOAD_EXITED,
    REASON_REBALANCE_EVICTED,
    REASON_REFLECT_STATUS_FAILED,
    REASON_SCHEDULE_BINDING_FAILED,
    REASON_SCHEDULE_BINDING_SUCCEED,
    REASON_SYNC_WORKLOAD_FAILED,
    REASON_SYNC_WORKLOAD_SUCCEED,
    REASON_TAINT_CLUSTER_SUCCEED,
    REASON_UNTAINT_CLUSTER_SUCCEED,
    REASON_WORK_DISPATCHING,
    SCHEDULER_REF,
    TYPE_NORMAL,
    TYPE_WARNING,
    EventLedger,
    EventRecorder,
    LedgerEvent,
    ObjectRef,
    arm,
    armed,
    configure,
    disarm,
    emit,
    emit_key,
    ledger,
    set_clock,
    state_payload,
    timeline_payload,
)

#: compat alias — callers that type-annotated the old dataclass
RecordedEvent = LedgerEvent
