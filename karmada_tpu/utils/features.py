"""Feature-gate registry (reference pkg/features/features.go:34-157).

Same registry semantics as k8s featuregate: every gate has a default, can
be flipped at runtime (`--feature-gates=Name=true,...` style strings are
accepted by `set_from_string`), and callers ask `enabled(name)`.
"""

from __future__ import annotations

import threading
from typing import Dict

# gate name -> default (mirrors features.go defaults in the reference)
DEFAULT_GATES: Dict[str, bool] = {
    "Failover": True,
    "GracefulEviction": True,
    "PropagateDeps": True,
    "CustomizedClusterResourceModeling": True,
    "PolicyPreemption": True,
    "MultiClusterService": False,
    "ResourceQuotaEstimate": False,
    "StatefulFailoverInjection": False,
    "PriorityBasedScheduling": True,
    "FederatedQuotaEnforcement": False,
    "MultiplePodTemplatesScheduling": True,
    "ControllerPriorityQueue": False,
}


class FeatureGates:
    def __init__(self, overrides: Dict[str, bool] | None = None) -> None:
        self._gates = dict(DEFAULT_GATES)
        self._lock = threading.Lock()
        if overrides:
            for k, v in overrides.items():
                self.set(k, v)

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name not in self._gates:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._gates[name]

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            if name not in self._gates:
                raise KeyError(f"unknown feature gate {name!r}")
            self._gates[name] = bool(value)

    def set_from_string(self, spec: str) -> None:
        """Parse 'A=true,B=false' (the --feature-gates flag format)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, val = part.partition("=")
            self.set(name.strip(), val.strip().lower() in ("true", "1", "yes"))

    def snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._gates)


# process-wide default instance (components accept an injected one for tests)
GATES = FeatureGates()
