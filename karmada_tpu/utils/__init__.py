from karmada_tpu.utils.quantity import Quantity, parse_quantity  # noqa: F401
