"""Leader election over a store-backed Lease.

Reference: every karmada binary runs controller-runtime leader election
(a coordination.k8s.io Lease in karmada-system) so only one replica of the
controller-manager/scheduler acts while standbys wait (SURVEY §5
checkpoint/resume).  The framework's equivalent: a typed Lease object in
the ObjectStore, acquired/renewed with optimistic concurrency — the
store's resourceVersion conflict check IS the election's atomicity.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from karmada_tpu.models.meta import ObjectMeta, TypedObject
from karmada_tpu.store.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    ObjectStore,
)

LEASE_NAMESPACE = "karmada-system"


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0


@dataclass
class Lease(TypedObject):
    KIND = "Lease"
    API_VERSION = "coordination.k8s.io/v1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)


class LeaderElector:
    """Campaign for one named lease; call `tick()` periodically (it both
    renews held leadership and tries takeover of expired leases)."""

    def __init__(
        self,
        store: ObjectStore,
        lease_name: str,
        identity: str,
        lease_duration_s: float = 15.0,
        clock: Callable[[], float] = time.time,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        self.store = store
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False

    def is_leader(self) -> bool:
        return self._leading

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            self._leading = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            self._leading = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def tick(self) -> bool:
        """One election round; returns current leadership."""
        now = self.clock()
        lease = self.store.try_get(Lease.KIND, LEASE_NAMESPACE, self.lease_name)
        if lease is None:
            lease = Lease(metadata=ObjectMeta(
                name=self.lease_name, namespace=LEASE_NAMESPACE))
            lease.spec = LeaseSpec(
                holder_identity=self.identity,
                lease_duration_seconds=self.lease_duration_s,
                acquire_time=now, renew_time=now,
            )
            try:
                self.store.create(lease)
                self._set_leading(True)
                return True
            except AlreadyExistsError:
                lease = self.store.try_get(
                    Lease.KIND, LEASE_NAMESPACE, self.lease_name)
                if lease is None:
                    return self._leading

        held_by_me = lease.spec.holder_identity == self.identity
        expired = now - lease.spec.renew_time > lease.spec.lease_duration_seconds
        if not held_by_me and not expired:
            self._set_leading(False)
            return False
        # held and recently renewed: skip the store write (controller-runtime
        # renews on ~duration/3, not every probe — a 0.5s periodic would
        # otherwise fsync a WAL record and fan a Lease event out per tick)
        if held_by_me and now - lease.spec.renew_time < self.lease_duration_s / 3:
            self._set_leading(True)
            return True

        # renew (held) or take over (expired) via optimistic concurrency:
        # a racing standby loses on the resourceVersion conflict
        lease.spec.holder_identity = self.identity
        lease.spec.renew_time = now
        lease.spec.lease_duration_seconds = self.lease_duration_s
        if not held_by_me:
            lease.spec.acquire_time = now
        try:
            self.store.update(lease)
            self._set_leading(True)
            return True
        except (ConflictError, NotFoundError):
            self._set_leading(False)
            return False

    def release(self) -> None:
        """Graceful handoff: expire the lease immediately so standbys take
        over without waiting out the duration."""
        if not self._leading:
            return

        def expire(obj: Lease) -> None:
            if obj.spec.holder_identity == self.identity:
                obj.spec.renew_time = 0.0
        try:
            self.store.mutate(Lease.KIND, LEASE_NAMESPACE, self.lease_name, expire)
        except NotFoundError:
            pass
        self._set_leading(False)
