"""Observability HTTP endpoint for long-running processes.

Reference: every binary exposes Prometheus metrics plus an opt-in pprof
server (pkg/sharedcli/profileflag/profileflag.go:58-70). Here one small
ThreadingHTTPServer serves:

    /metrics   Prometheus text exposition (utils/metrics.REGISTRY)
    /healthz   liveness ("ok")
    /readyz    readiness: the supplied probe callback (e.g. store reachable)
    /debug/state   JSON snapshot: object counts per kind, the device-probe
                   history (utils/deviceprobe), the active solver mesh
                   (ops/meshing), trace-recorder stats
    /debug/traces        recent flight-recorder ring (JSON, full spans)
    /debug/traces/slow   the always-retained slowest-cycles shelf (JSON)
    /debug/traces/{id}   one trace as a text waterfall
                         (?format=json for the raw trace)
    /debug/explain       recent explain-plane decision summaries + the
                         always-retained unschedulable shelf (JSON)
    /debug/explain/{namespace}/{name}
                         one binding's full Decision (verdict table)
    /debug/load          live load-generator state (karmada_tpu/loadgen,
                         armed by `serve --loadgen SCENARIO`): scenario
                         progress, admission/shed counts, queue depths
                         and oldest-resident ages; {"enabled": false}
                         when no driver is active
    /debug/resident      resident-state plane (karmada_tpu/resident,
                         armed by `serve --resident`): generation, vocab
                         sizes, row-cache hit rate, delta depth, audit
                         outcomes (?recent=N adds per-cycle records);
                         {"enabled": false} when rebuild-per-cycle
    /debug/rebalance     rebalance plane (karmada_tpu/rebalance, serve
                         --rebalance): last detect scores per cluster,
                         eviction/conservation totals, pacing budget;
                         render with `karmadactl rebalance --endpoint`

    /debug/facade        facade plane (karmada_tpu/facade, armed by
                         `serve --facade[=ADDR]`): call/batch totals,
                         the coalesce ratio, in-flight depth, what-if
                         query tallies, the bound wire address;
                         {"enabled": false} when disarmed
    /whatif              capacity-planning queries against the armed
                         facade plane (?query=placement|cluster-loss|
                         headroom&replicas=N&cpu=Q&memory=Q&divided=
                         &cluster=&limit=): a hypothetical solve on a
                         copy-on-write fork of live state — never
                         mutates a placement; what `karmadactl whatif`
                         polls
    /debug/chaos         chaos fault-injection plane (karmada_tpu/chaos,
                         armed by `serve --chaos SPEC`): armed rules with
                         fire counts, per-site totals, the recent fire
                         log; {"enabled": false} when disarmed
    /debug/timeseries    telemetry plane ring (obs/timeseries, armed by
                         `serve --telemetry`): per-series point lists
                         over the retained window, counters with
                         reset-aware window deltas; ?n=N limits samples,
                         ?prefix=karmada_scheduler filters families,
                         ?points=0 keeps only the window aggregates
                         (delta/last — what karmadactl top polls);
                         {"enabled": false} when disarmed
    /debug/slo           SLO error budgets (obs/slo): per-objective
                         multi-window burn rates, budget remaining, the
                         regression-watchdog verdict; {"enabled": false}
                         when disarmed
    /debug/incidents     incident plane (obs/incidents): flight-ring
                         stats, capture/suppression totals by trigger,
                         the bounded bundle index; {"enabled": false}
                         when the store is disarmed (the flight ring
                         itself is armed by default)
    /debug/incidents/{id}
                         one self-contained forensic bundle: the flight
                         ring, MetricRing samples + SLO verdict,
                         implicated-binding timelines, the locks block,
                         and the trigger's own detail (e.g. the audit
                         divergence diff)
    /debug/profile?seconds=N
                         on-demand jax.profiler capture (obs/devprof):
                         opens a bounded trace window, writes
                         TensorBoard-loadable artifacts under the serve
                         dir, answers the artifact inventory; one
                         capture at a time (HTTP 409 while busy)
    /debug/events        lifecycle ledger (obs/events, armed by
                         default): counters, per-reason tallies, the
                         recent event ring; ?n=N bounds the ring slice,
                         ?since=SEQ returns only events with activity
                         after the cursor (last_seq — coalesced repeats
                         included; the `karmadactl events --watch`
                         cursor)
    /debug/events/{ns}/{name}
                         one binding's gap-free event timeline plus a
                         status summary from the live store (clusters,
                         Scheduled condition, eviction tasks) — what
                         `karmadactl describe ns/name --endpoint`
                         renders kube-style

The trace endpoints read the process-wide tracer (karmada_tpu.obs.TRACER,
armed by `karmadactl serve --trace-buffer N`) unless an explicit recorder
is injected; with tracing disabled they answer {"enabled": false} rather
than 404 so a dashboard can poll unconditionally.  The explain endpoints
read the process-wide decision ring (obs/decisions, armed by `serve
--explain`) the same way.  Unknown trace/decision ids answer a JSON 404
body ({"error": ...}), and a handler exception answers a JSON 500 —
never a closed connection.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional


class ObservabilityServer:
    def __init__(
        self,
        store=None,
        registry=None,
        ready_probe: Optional[Callable[[], bool]] = None,
        recorder=None,
        decisions=None,
        # /debug/profile artifact root (serve passes <plane dir>/profiles);
        # None lazily falls back to a tmp dir on the first capture
        profile_dir: Optional[str] = None,
    ) -> None:
        from karmada_tpu.utils.metrics import REGISTRY

        self.store = store
        self.registry = registry if registry is not None else REGISTRY
        self.ready_probe = ready_probe
        self._recorder = recorder
        self._decisions = decisions
        self.profile_dir = profile_dir
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _trace_recorder(self):
        if self._recorder is not None:
            return self._recorder
        from karmada_tpu import obs

        return obs.TRACER.recorder  # None while tracing is disabled

    def _decision_recorder(self):
        if self._decisions is not None:
            return self._decisions
        from karmada_tpu.obs import decisions

        return decisions.recorder()  # None while the explain plane is off

    def _state(self) -> dict:
        from karmada_tpu import resident
        from karmada_tpu.ops import aotcache, meshing
        from karmada_tpu.utils import deviceprobe, locks

        from karmada_tpu.obs import events as obs_events

        counts = self.store.counts_by_kind() if self.store is not None else {}
        rec = self._trace_recorder()
        dec = self._decision_recorder()
        return {"objects_by_kind": counts,
                # the lifecycle ledger's counters (obs/events): recorded/
                # coalesced/evicted totals + retained window size
                "events": obs_events.ledger().counters(),
                "total": sum(counts.values()),
                "device_probe": deviceprobe.last_probe(),
                # the AOT executable plane (ops/aotcache): persistent
                # compile-cache dir + key, hit/miss counters, and the
                # per-(shape x variant) warm-start ledger —
                # {"armed": false} when serve ran --aot-cache off
                "aot": aotcache.state_payload(),
                # the active solver mesh (ops/meshing): shape, device
                # count, platform — {"enabled": false} on the
                # single-device fallback; never initialises a backend
                "mesh": meshing.mesh_info(),
                # the resident-state plane (karmada_tpu/resident):
                # generation, vocab sizes, row-cache hit rate, last audit
                # — {"enabled": false} when running rebuild-per-cycle
                "resident": resident.state_payload(),
                # the shortlist plane (ops/shortlist): dispatch/fallback
                # counters + the last shortlisted chunk's geometry.
                # Read through sys.modules so a host-backend plane that
                # never armed the two-tier solve pays no jax import
                "shortlist": self._shortlist_state(),
                # the runtime race detector (utils/locks): armed flag,
                # per-VetLock owner/held-for, single-thread ownership
                # contracts, order-edge + inversion counts, watchdog —
                # the first page to pull when a serve process wedges
                "locks": locks.state_payload(),
                "traces": rec.stats() if rec is not None else None,
                "explain": dec.stats() if dec is not None else None}

    @staticmethod
    def _shortlist_state() -> dict:
        import sys as _sys

        mod = _sys.modules.get("karmada_tpu.ops.shortlist")
        if mod is None:
            return {"active": False}
        payload = mod.state_payload()
        # "active" means the tier actually ran, not merely that some
        # other plane imported the module — an operator debugging why
        # shortlisting isn't firing must not read an armed-looking block
        # with zero dispatches
        return {"active": payload["dispatches"] > 0, **payload}

    def _traces_payload(self, which: str) -> dict:
        from karmada_tpu.obs import export

        rec = self._trace_recorder()
        if rec is None:
            return {"enabled": False, "traces": []}
        traces = rec.slowest() if which == "slow" else rec.recent()
        return {
            "enabled": True,
            "dropped": rec.dropped,
            "summaries": [export.summarize(t) for t in traces],
            "traces": traces,
        }

    @staticmethod
    def _query_params(query: str) -> dict:
        """k=v pairs of a raw query string (no repeats expected on the
        debug surface; the last value wins)."""
        out = {}
        for part in (query or "").split("&"):
            if "=" in part:
                k, v = part.split("=", 1)
                out[k] = v
        return out

    @staticmethod
    def _json_error(message: str, code: int):
        """A well-formed JSON error body: unknown ids and handler faults
        must never surface as an unhandled exception / empty response."""
        return (json.dumps({"error": message}).encode(),
                "application/json", code)

    def _one_trace(self, trace_id: str, as_json: bool):
        """(body, ctype, code) for /debug/traces/{id}."""
        from karmada_tpu.obs import export

        rec = self._trace_recorder()
        tr = rec.get(trace_id) if rec is not None else None
        if tr is None:
            return self._json_error(f"trace {trace_id!r} not found", 404)
        if as_json:
            return export.to_json(tr).encode(), "application/json", 200
        return (export.render_waterfall(tr).encode() + b"\n",
                "text/plain", 200)

    @staticmethod
    def _decision_summary(d: dict) -> dict:
        return {"key": d["key"], "outcome": d["outcome"],
                "reason": d.get("reason"), "message": d.get("message"),
                "trace_id": d.get("trace_id"), "ts": d.get("ts"),
                "backend": d.get("backend"), "event_id": d.get("event_id")}

    def _explain_payload(self) -> dict:
        rec = self._decision_recorder()
        if rec is None:
            return {"enabled": False, "decisions": [], "unschedulable": []}
        return {
            "enabled": True,
            "stats": rec.stats(),
            "dropped": rec.dropped,
            "decisions": [self._decision_summary(d) for d in rec.recent()],
            "unschedulable": [self._decision_summary(d)
                              for d in rec.unschedulable()],
        }

    def _one_timeline(self, key: str):
        """(body, ctype, code) for /debug/events/{namespace}/{name}: the
        binding's ordered event timeline + a live status summary (the
        `karmadactl describe --endpoint` payload)."""
        from karmada_tpu.obs import events as obs_events

        if "/" not in key:
            return self._json_error(
                f"expected namespace/name, got {key!r}", 404)
        ns, name = key.split("/", 1)
        payload = obs_events.timeline_payload(ns, name)
        payload["binding"] = self._binding_summary(ns, name)
        # the explain cross-reference: the latest Decision's identity so
        # the describe renderer can show the verdict one fetch away
        dec = self._decision_recorder()
        d = dec.get(f"{ns}/{name}") if dec is not None else None
        if d is not None:
            payload["decision"] = {
                "id": d.get("id"), "outcome": d.get("outcome"),
                "reason": d.get("reason"), "message": d.get("message"),
                "event_id": d.get("event_id")}
        return json.dumps(payload).encode(), "application/json", 200

    def _binding_summary(self, ns: str, name: str):
        """A kube-describe-style status block from the live store (None
        when the server carries no store or the binding is gone)."""
        if self.store is None:
            return None
        rb = self.store.try_get("ResourceBinding", ns, name)
        if rb is None:
            return None
        cond = next((c for c in rb.status.conditions
                     if c.type == "Scheduled"), None)
        return {
            "exists": True,
            "generation": rb.metadata.generation,
            "observed_generation": rb.status.scheduler_observed_generation,
            "replicas": rb.spec.replicas,
            "clusters": [{"name": t.name, "replicas": t.replicas}
                         for t in rb.spec.clusters],
            "eviction_tasks": [{"from_cluster": t.from_cluster,
                                "reason": t.reason, "producer": t.producer}
                               for t in rb.spec.graceful_eviction_tasks],
            "scheduled_condition": (None if cond is None else {
                "status": cond.status, "reason": cond.reason,
                "message": cond.message}),
        }

    def _one_decision(self, key: str):
        """(body, ctype, code) for /debug/explain/{namespace}/{name}."""
        rec = self._decision_recorder()
        if rec is None:
            return self._json_error(
                "explain plane is disabled (serve --explain to arm it)", 404)
        d = rec.get(key)
        if d is None:
            return self._json_error(f"no decision recorded for {key!r}", 404)
        return json.dumps(d).encode(), "application/json", 200

    def _route(self, path: str, query: str):
        """(body, ctype, code) for one GET."""
        if path == "/metrics":
            return (self.registry.dump().encode(),
                    "text/plain; version=0.0.4", 200)
        if path == "/healthz":
            return b"ok", "text/plain", 200
        if path == "/readyz":
            ok = self.ready_probe() if self.ready_probe else True
            return (b"ok" if ok else b"not ready", "text/plain",
                    200 if ok else 503)
        if path == "/debug/state":
            return json.dumps(self._state()).encode(), "application/json", 200
        if path in ("/debug/traces", "/debug/traces/slow"):
            which = "slow" if path.endswith("/slow") else "recent"
            return (json.dumps(self._traces_payload(which)).encode(),
                    "application/json", 200)
        if path.startswith("/debug/traces/"):
            trace_id = path[len("/debug/traces/"):]
            return self._one_trace(trace_id, "format=json" in (query or ""))
        if path == "/debug/load":
            from karmada_tpu.loadgen import driver as loadgen_driver

            return (json.dumps(loadgen_driver.load_state()).encode(),
                    "application/json", 200)
        if path == "/debug/resident":
            from karmada_tpu import resident

            recent = 0
            for part in (query or "").split("&"):
                if part.startswith("recent="):
                    try:
                        recent = max(0, int(part[len("recent="):]))
                    except ValueError:
                        pass
            return (json.dumps(resident.state_payload(recent)).encode(),
                    "application/json", 200)
        if path == "/debug/chaos":
            from karmada_tpu import chaos

            return (json.dumps(chaos.state_payload()).encode(),
                    "application/json", 200)
        if path == "/debug/rebalance":
            from karmada_tpu import rebalance

            return (json.dumps(rebalance.state_payload()).encode(),
                    "application/json", 200)
        if path == "/debug/facade":
            from karmada_tpu import facade

            return (json.dumps(facade.state_payload()).encode(),
                    "application/json", 200)
        if path == "/whatif":
            from karmada_tpu import facade

            payload = facade.whatif_payload(self._query_params(query))
            code = 200 if "error" not in payload else (
                503 if not payload.get("enabled", True) else 400)
            return json.dumps(payload).encode(), "application/json", code
        if path == "/debug/timeseries":
            from karmada_tpu.obs import timeseries

            params = self._query_params(query)
            n = None
            try:
                if params.get("n"):
                    n = max(0, int(params["n"]))
            except ValueError:
                pass
            return (json.dumps(timeseries.state_payload(
                        n=n, prefix=params.get("prefix") or None,
                        include_points=params.get("points") != "0")).encode(),
                    "application/json", 200)
        if path == "/debug/slo":
            from karmada_tpu.obs import slo

            return (json.dumps(slo.state_payload()).encode(),
                    "application/json", 200)
        if path == "/debug/incidents":
            from karmada_tpu.obs import incidents

            return (json.dumps(incidents.state_payload(),
                               default=str).encode(),
                    "application/json", 200)
        if path.startswith("/debug/incidents/"):
            from karmada_tpu.obs import incidents

            iid = path[len("/debug/incidents/"):]
            bundle = incidents.bundle_payload(iid)
            if bundle is None:
                return self._json_error(
                    f"no incident bundle {iid!r} (incident plane "
                    "disarmed, id unknown, or bundle evicted)", 404)
            return (json.dumps(bundle, default=str).encode(),
                    "application/json", 200)
        if path == "/debug/profile":
            from karmada_tpu.obs import devprof

            params = self._query_params(query)
            try:
                seconds = float(params.get("seconds", "1"))
            except ValueError:
                return self._json_error(
                    f"seconds must be a number, got "
                    f"{params.get('seconds')!r}", 400)
            out_dir = self.profile_dir
            if out_dir is None:
                import tempfile

                out_dir = self.profile_dir = tempfile.mkdtemp(
                    prefix="karmada-profile-")
            rec = devprof.capture_profile(seconds, out_dir)
            code = 200 if rec.get("ok") else (
                409 if rec.get("busy") else 500)
            return json.dumps(rec).encode(), "application/json", code
        if path == "/debug/events":
            from karmada_tpu.obs import events as obs_events

            params = self._query_params(query)
            n, since = 64, None
            try:
                if params.get("n"):
                    n = max(0, int(params["n"]))
                if params.get("since"):
                    since = int(params["since"])
            except ValueError:
                pass
            return (json.dumps(obs_events.state_payload(
                        n=n, since=since)).encode(),
                    "application/json", 200)
        if path.startswith("/debug/events/"):
            return self._one_timeline(path[len("/debug/events/"):])
        if path == "/debug/explain":
            return (json.dumps(self._explain_payload()).encode(),
                    "application/json", 200)
        if path.startswith("/debug/explain/"):
            key = path[len("/debug/explain/"):]
            return self._one_decision(key)
        if path.startswith("/debug/"):
            return self._json_error(f"no such debug endpoint {path!r}", 404)
        return b"not found", "text/plain", 404

    def start(self, port: int = 0, host: str = "127.0.0.1") -> str:
        import http.server
        import urllib.parse

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server convention
                parsed = urllib.parse.urlsplit(self.path)
                try:
                    body, ctype, code = outer._route(parsed.path,
                                                     parsed.query)
                # vet: ignore[exception-hygiene] answered as a JSON 500 body
                except Exception as e:  # noqa: BLE001 — JSON 500, never a
                    # closed connection with no body
                    body, ctype, code = outer._json_error(repr(e), 500)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        h, p = self._httpd.server_address
        return f"http://{h}:{p}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
