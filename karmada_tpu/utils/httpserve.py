"""Observability HTTP endpoint for long-running processes.

Reference: every binary exposes Prometheus metrics plus an opt-in pprof
server (pkg/sharedcli/profileflag/profileflag.go:58-70). Here one small
ThreadingHTTPServer serves:

    /metrics   Prometheus text exposition (utils/metrics.REGISTRY)
    /healthz   liveness ("ok")
    /readyz    readiness: the supplied probe callback (e.g. store reachable)
    /debug/state   JSON object-count snapshot per kind (the pprof analog:
                   what is this plane holding right now)
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional


class ObservabilityServer:
    def __init__(
        self,
        store=None,
        registry=None,
        ready_probe: Optional[Callable[[], bool]] = None,
    ) -> None:
        from karmada_tpu.utils.metrics import REGISTRY

        self.store = store
        self.registry = registry if registry is not None else REGISTRY
        self.ready_probe = ready_probe
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _state(self) -> dict:
        counts = self.store.counts_by_kind() if self.store is not None else {}
        return {"objects_by_kind": counts,
                "total": sum(counts.values())}

    def start(self, port: int = 0, host: str = "127.0.0.1") -> str:
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server convention
                if self.path == "/metrics":
                    body = outer.registry.dump().encode()
                    ctype = "text/plain; version=0.0.4"
                    code = 200
                elif self.path == "/healthz":
                    body, ctype, code = b"ok", "text/plain", 200
                elif self.path == "/readyz":
                    ok = outer.ready_probe() if outer.ready_probe else True
                    body = b"ok" if ok else b"not ready"
                    ctype, code = "text/plain", (200 if ok else 503)
                elif self.path == "/debug/state":
                    body = json.dumps(outer._state()).encode()
                    ctype, code = "application/json", 200
                else:
                    body, ctype, code = b"not found", "text/plain", 404
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        h, p = self._httpd.server_address
        return f"http://{h}:{p}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
