"""Observability HTTP endpoint for long-running processes.

Reference: every binary exposes Prometheus metrics plus an opt-in pprof
server (pkg/sharedcli/profileflag/profileflag.go:58-70). Here one small
ThreadingHTTPServer serves:

    /metrics   Prometheus text exposition (utils/metrics.REGISTRY)
    /healthz   liveness ("ok")
    /readyz    readiness: the supplied probe callback (e.g. store reachable)
    /debug/state   JSON snapshot: object counts per kind, the device-probe
                   history (utils/deviceprobe), the active solver mesh
                   (ops/meshing), trace-recorder stats
    /debug/traces        recent flight-recorder ring (JSON, full spans)
    /debug/traces/slow   the always-retained slowest-cycles shelf (JSON)
    /debug/traces/{id}   one trace as a text waterfall
                         (?format=json for the raw trace)

The trace endpoints read the process-wide tracer (karmada_tpu.obs.TRACER,
armed by `karmadactl serve --trace-buffer N`) unless an explicit recorder
is injected; with tracing disabled they answer {"enabled": false} rather
than 404 so a dashboard can poll unconditionally.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional


class ObservabilityServer:
    def __init__(
        self,
        store=None,
        registry=None,
        ready_probe: Optional[Callable[[], bool]] = None,
        recorder=None,
    ) -> None:
        from karmada_tpu.utils.metrics import REGISTRY

        self.store = store
        self.registry = registry if registry is not None else REGISTRY
        self.ready_probe = ready_probe
        self._recorder = recorder
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _trace_recorder(self):
        if self._recorder is not None:
            return self._recorder
        from karmada_tpu import obs

        return obs.TRACER.recorder  # None while tracing is disabled

    def _state(self) -> dict:
        from karmada_tpu.ops import meshing
        from karmada_tpu.utils import deviceprobe

        counts = self.store.counts_by_kind() if self.store is not None else {}
        rec = self._trace_recorder()
        return {"objects_by_kind": counts,
                "total": sum(counts.values()),
                "device_probe": deviceprobe.last_probe(),
                # the active solver mesh (ops/meshing): shape, device
                # count, platform — {"enabled": false} on the
                # single-device fallback; never initialises a backend
                "mesh": meshing.mesh_info(),
                "traces": rec.stats() if rec is not None else None}

    def _traces_payload(self, which: str) -> dict:
        from karmada_tpu.obs import export

        rec = self._trace_recorder()
        if rec is None:
            return {"enabled": False, "traces": []}
        traces = rec.slowest() if which == "slow" else rec.recent()
        return {
            "enabled": True,
            "dropped": rec.dropped,
            "summaries": [export.summarize(t) for t in traces],
            "traces": traces,
        }

    def _one_trace(self, trace_id: str, as_json: bool):
        """(body, ctype, code) for /debug/traces/{id}."""
        from karmada_tpu.obs import export

        rec = self._trace_recorder()
        tr = rec.get(trace_id) if rec is not None else None
        if tr is None:
            return (f"trace {trace_id!r} not found".encode(),
                    "text/plain", 404)
        if as_json:
            return export.to_json(tr).encode(), "application/json", 200
        return (export.render_waterfall(tr).encode() + b"\n",
                "text/plain", 200)

    def start(self, port: int = 0, host: str = "127.0.0.1") -> str:
        import http.server
        import urllib.parse

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server convention
                parsed = urllib.parse.urlsplit(self.path)
                path = parsed.path
                if path == "/metrics":
                    body = outer.registry.dump().encode()
                    ctype = "text/plain; version=0.0.4"
                    code = 200
                elif path == "/healthz":
                    body, ctype, code = b"ok", "text/plain", 200
                elif path == "/readyz":
                    ok = outer.ready_probe() if outer.ready_probe else True
                    body = b"ok" if ok else b"not ready"
                    ctype, code = "text/plain", (200 if ok else 503)
                elif path == "/debug/state":
                    body = json.dumps(outer._state()).encode()
                    ctype, code = "application/json", 200
                elif path in ("/debug/traces", "/debug/traces/slow"):
                    which = "slow" if path.endswith("/slow") else "recent"
                    body = json.dumps(outer._traces_payload(which)).encode()
                    ctype, code = "application/json", 200
                elif path.startswith("/debug/traces/"):
                    trace_id = path[len("/debug/traces/"):]
                    as_json = "format=json" in (parsed.query or "")
                    body, ctype, code = outer._one_trace(trace_id, as_json)
                else:
                    body, ctype, code = b"not found", "text/plain", 404
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        h, p = self._httpd.server_address
        return f"http://{h}:{p}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
