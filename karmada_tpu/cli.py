"""karmadactl — the framework's CLI (reference pkg/karmadactl/, 30
subcommands over the control plane).

Operates on a PERSISTED control plane directory (store/persistence.py):
every invocation loads the plane, applies the command, pumps the
controllers to quiescence, and exits — state carries across invocations
through the snapshot+WAL, the same way karmadactl talks to a long-lived
apiserver.

Member clusters are capacity simulators; `join` records the simulated
capacity on the Cluster object so later invocations rehydrate the same
fleet (the kind-cluster analog of hack/local-up-karmada.sh).

    python -m karmada_tpu.cli --dir ./plane init
    python -m karmada_tpu.cli --dir ./plane join m1 --cpu 64 --memory-gi 256
    python -m karmada_tpu.cli --dir ./plane apply -f deployment.yaml
    python -m karmada_tpu.cli --dir ./plane get ResourceBinding -n default
    python -m karmada_tpu.cli --dir ./plane get Deployment --cluster m1
    python -m karmada_tpu.cli --dir ./plane top clusters
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

SIM_CAPACITY_ANNOTATION = "karmada.io/simulated-capacity"

VERSION = "karmada-tpu v0.3"


def _load_plane(directory: str, backend: str = "serial"):
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.cluster import Cluster

    cp = ControlPlane(backend=backend, persist_dir=directory)
    # rehydrate simulated members from their recorded capacity
    for cluster in cp.store.list(Cluster.KIND):
        raw = cluster.metadata.annotations.get(SIM_CAPACITY_ANNOTATION)
        if not raw or cluster.metadata.name in cp.members:
            continue
        cap = json.loads(raw)
        cp.add_member(
            cluster.metadata.name,
            cpu_milli=cap.get("cpu_milli", 64_000),
            memory_gi=cap.get("memory_gi", 256),
            pods=cap.get("pods", 110),
            sync_mode=cluster.spec.sync_mode,
        )
    if cp.members:
        cp.tick()  # re-sync member-facing state (RBAC, works) post-rehydrate
    return cp


def _finish(cp) -> None:
    cp.tick()
    cp.checkpoint()


def cmd_init(args) -> int:
    cp = _load_plane(args.dir)
    _finish(cp)
    print(f"control plane initialized at {args.dir}")
    return 0


def cmd_join(args) -> int:
    from karmada_tpu.models.cluster import Cluster

    cp = _load_plane(args.dir)
    if args.name in cp.members:
        print(f"cluster {args.name} already joined", file=sys.stderr)
        return 1
    cp.add_member(
        args.name, cpu_milli=args.cpu * 1000, memory_gi=args.memory_gi,
        pods=args.pods, region=args.region, zone=args.zone,
        provider=args.provider, sync_mode=args.sync_mode,
    )

    def record(c: Cluster) -> None:
        c.metadata.annotations[SIM_CAPACITY_ANNOTATION] = json.dumps({
            "cpu_milli": args.cpu * 1000, "memory_gi": args.memory_gi,
            "pods": args.pods,
        })
    cp.store.mutate(Cluster.KIND, "", args.name, record)
    _finish(cp)
    print(f"cluster {args.name} joined ({args.sync_mode} mode)")
    return 0


def cmd_unjoin(args) -> int:
    cp = _load_plane(args.dir)
    if args.name not in cp.members:
        print(f"unknown cluster {args.name}", file=sys.stderr)
        return 1
    cp.unjoin(args.name)
    _finish(cp)
    print(f"cluster {args.name} unjoined")
    return 0


def _print_table(rows, headers) -> None:
    widths = [max(len(str(r[i])) for r in rows + [headers]) for i in range(len(headers))]
    for r in [headers] + rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))


def cmd_get(args) -> int:
    cp = _load_plane(args.dir)
    if args.cluster:
        handle = cp.proxy(args.cluster)
        objs = (
            [handle.get(args.kind, args.namespace, args.name)]
            if args.name else handle.list(args.kind, args.namespace or None)
        )
        objs = [o for o in objs if o is not None]
    elif args.name:
        o = cp.store.try_get(args.kind, args.namespace, args.name)
        objs = [o] if o is not None else []
    else:
        objs = cp.store.list(args.kind, args.namespace or None)
    if args.output == "json":
        for o in objs:
            manifest = o.to_manifest() if hasattr(o, "to_manifest") else o.__dict__
            print(json.dumps(manifest, default=str))
        return 0
    rows = [[o.namespace or "-", o.name, type(o).__name__] for o in objs]
    _print_table(rows, ["NAMESPACE", "NAME", "TYPE"])
    return 0


def cmd_apply(args) -> int:
    import yaml

    cp = _load_plane(args.dir)
    with open(args.filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    for manifest in docs:
        cp.apply(manifest)
        print(f"{manifest.get('kind')}/{manifest['metadata']['name']} applied")
    _finish(cp)
    return 0


def cmd_promote(args) -> int:
    """Adopt a member-cluster resource into the federation
    (pkg/karmadactl/promote)."""
    from karmada_tpu.interpreter.interpreter import prune_for_propagation

    cp = _load_plane(args.dir)
    handle = cp.proxy(args.cluster)
    obj = handle.get(args.kind, args.namespace, args.name)
    if obj is None:
        print(f"{args.kind}/{args.name} not found in {args.cluster}", file=sys.stderr)
        return 1
    cp.apply(prune_for_propagation(obj.to_manifest()))
    _finish(cp)
    print(f"{args.kind}/{args.name} promoted from {args.cluster}")
    return 0


def cmd_cordon(args, uncordon: bool = False) -> int:
    """cordon/uncordon: the NoSchedule taint (pkg/karmadactl/cordon)."""
    from karmada_tpu.models.cluster import Cluster, Taint

    cp = _load_plane(args.dir)
    key = "cluster.karmada.io/cordoned"

    def update(c: Cluster) -> None:
        c.spec.taints = [t for t in c.spec.taints if t.key != key]
        if not uncordon:
            c.spec.taints.append(Taint(key=key, effect="NoSchedule"))
    try:
        cp.store.mutate(Cluster.KIND, "", args.name, update)
    except KeyError:
        print(f"unknown cluster {args.name}", file=sys.stderr)
        return 1
    _finish(cp)
    print(f"cluster {args.name} {'uncordoned' if uncordon else 'cordoned'}")
    return 0


def cmd_top(args) -> int:
    from karmada_tpu.models.cluster import Cluster

    cp = _load_plane(args.dir)
    rows = []
    for c in cp.store.list(Cluster.KIND):
        s = c.status.resource_summary
        if s is None:
            rows.append([c.name, "-", "-", "-", c.ready])
            continue
        cpu_alloc = s.allocatable.get("cpu")
        cpu_used = s.allocated.get("cpu")
        pct = (
            f"{100 * cpu_used.milli // max(cpu_alloc.milli, 1)}%"
            if cpu_alloc and cpu_used else "-"
        )
        rows.append([
            c.name,
            f"{cpu_used.milli}m/{cpu_alloc.milli}m" if cpu_alloc else "-",
            pct,
            s.allocatable.get("pods", "-"),
            c.ready,
        ])
    _print_table(rows, ["CLUSTER", "CPU(used/alloc)", "CPU%", "PODS", "READY"])
    return 0


def cmd_interpret(args) -> int:
    """Dry-run interpreter customizations against a manifest
    (pkg/karmadactl/interpret)."""
    import yaml

    from karmada_tpu.interpreter.interpreter import ResourceInterpreter

    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    interp = ResourceInterpreter()
    if args.customization:
        from karmada_tpu.interpreter.declarative import make_hooks
        from karmada_tpu.interpreter.interpreter import Customization

        with open(args.customization) as f:
            cust = yaml.safe_load(f)
        hooks = make_hooks(cust.get("customizations", {}))
        interp.register(Customization(
            api_version=manifest.get("apiVersion", ""),
            kind=manifest.get("kind", ""),
            hooks=hooks,
        ))
    op = args.operation
    if op == "InterpretReplica":
        replicas, req = interp.get_replicas(manifest)
        print(json.dumps({"replicas": replicas, "requirements": (
            {k: str(v) for k, v in req.resource_request.items()} if req else None
        )}))
    elif op == "InterpretHealth":
        print(json.dumps({"health": interp.interpret_health(manifest)}))
    elif op == "ReviseReplica":
        print(json.dumps(interp.revise_replica(manifest, args.replicas)))
    elif op == "InterpretStatus":
        print(json.dumps(interp.reflect_status(manifest)))
    else:
        print(f"unsupported operation {op}", file=sys.stderr)
        return 1
    return 0


def cmd_tick(args) -> int:
    cp = _load_plane(args.dir, backend=args.backend)
    n = cp.tick()
    cp.checkpoint()
    print(f"{n} reconciles")
    return 0


def cmd_serve(args) -> int:
    """Run the control plane long-lived: every controller on its own
    thread, periodic hooks on a timer (the karmada-controller-manager /
    scheduler / webhook processes rolled into one, Runtime.serve)."""
    import time as _time

    cp = _load_plane(args.dir, backend=args.backend)
    if args.feature_gates:
        cp.gates.set_from_string(args.feature_gates)
    cp.runtime._periodic_interval_s = args.sync_period  # noqa: SLF001
    cp.runtime.serve()
    print(f"serving control plane from {args.dir} "
          f"(backend={args.backend}, {len(cp.members)} members); ctrl-c to stop")
    try:
        next_checkpoint = _time.time() + args.checkpoint_period
        while True:
            _time.sleep(0.5)
            if _time.time() >= next_checkpoint:
                cp.checkpoint()
                next_checkpoint = _time.time() + args.checkpoint_period
    except KeyboardInterrupt:
        pass
    finally:
        cp.runtime.stop()
        cp.checkpoint()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="karmadactl", description=__doc__)
    p.add_argument("--dir", required=True, help="control plane directory")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("init")
    sub.add_parser("version")

    j = sub.add_parser("join")
    j.add_argument("name")
    j.add_argument("--cpu", type=int, default=64, help="cores")
    j.add_argument("--memory-gi", type=int, default=256)
    j.add_argument("--pods", type=int, default=110)
    j.add_argument("--region", default="")
    j.add_argument("--zone", default="")
    j.add_argument("--provider", default="")
    j.add_argument("--sync-mode", choices=["Push", "Pull"], default="Push")

    u = sub.add_parser("unjoin")
    u.add_argument("name")

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-n", "--namespace", default="")
    g.add_argument("--cluster", default="", help="read through the cluster proxy")
    g.add_argument("-o", "--output", choices=["table", "json"], default="table")

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)

    pr = sub.add_parser("promote")
    pr.add_argument("kind")
    pr.add_argument("name")
    pr.add_argument("-n", "--namespace", default="")
    pr.add_argument("--cluster", required=True)

    for cname in ("cordon", "uncordon"):
        c = sub.add_parser(cname)
        c.add_argument("name")

    t = sub.add_parser("top")
    t.add_argument("what", choices=["clusters"])

    i = sub.add_parser("interpret")
    i.add_argument("-f", "--filename", required=True)
    i.add_argument("--operation", default="InterpretReplica")
    i.add_argument("--customization", default="")
    i.add_argument("--replicas", type=int, default=1)

    tk = sub.add_parser("tick")
    tk.add_argument("--backend", default="serial")

    sv = sub.add_parser("serve")
    sv.add_argument("--backend", choices=["serial", "device"], default="device")
    sv.add_argument("--feature-gates", default="",
                    help="A=true,B=false (pkg/features registry names)")
    sv.add_argument("--sync-period", type=float, default=0.5,
                    help="periodic resync interval seconds")
    sv.add_argument("--checkpoint-period", type=float, default=30.0,
                    help="WAL compaction interval seconds")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        print(VERSION)
        return 0
    return {
        "init": cmd_init,
        "join": cmd_join,
        "unjoin": cmd_unjoin,
        "get": cmd_get,
        "apply": cmd_apply,
        "promote": cmd_promote,
        "cordon": cmd_cordon,
        "uncordon": lambda a: cmd_cordon(a, uncordon=True),
        "top": cmd_top,
        "interpret": cmd_interpret,
        "tick": cmd_tick,
        "serve": cmd_serve,
    }[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
