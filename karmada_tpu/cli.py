"""karmadactl — the framework's CLI (reference pkg/karmadactl/, 30
subcommands over the control plane).

Operates on a PERSISTED control plane directory (store/persistence.py):
every invocation loads the plane, applies the command, pumps the
controllers to quiescence, and exits — state carries across invocations
through the snapshot+WAL, the same way karmadactl talks to a long-lived
apiserver.

Member clusters are capacity simulators; `join` records the simulated
capacity on the Cluster object so later invocations rehydrate the same
fleet (the kind-cluster analog of hack/local-up-karmada.sh).

    python -m karmada_tpu.cli --dir ./plane init
    python -m karmada_tpu.cli --dir ./plane join m1 --cpu 64 --memory-gi 256
    python -m karmada_tpu.cli --dir ./plane apply -f deployment.yaml
    python -m karmada_tpu.cli --dir ./plane get ResourceBinding -n default
    python -m karmada_tpu.cli --dir ./plane get Deployment --cluster m1
    python -m karmada_tpu.cli --dir ./plane top clusters
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

SIM_CAPACITY_ANNOTATION = "karmada.io/simulated-capacity"

VERSION = "karmada-tpu v0.4"


def _load_plane(directory: str, backend: str = "serial", waves: int = 8,
                controllers: Optional[str] = None,
                probe_device: bool = False, probe_timeout: float = 240.0,
                device_cycle_timeout: Optional[float] = None,
                pipeline_chunk: int = 1024,
                mesh: Optional[str] = None,
                explain: float = 0.0,
                batch_window: int = 4096,
                batch_deadline: Optional[float] = None,
                admission_limit: Optional[int] = None,
                resident: bool = False,
                resident_audit: int = 64,
                resident_fused: bool = False,
                device_recover_cycles: Optional[int] = None,
                chaos: Optional[str] = None,
                chaos_seed: int = 0,
                aot_cache: str = "off",
                rebalance: Optional[float] = None,
                shortlist_k: Optional[int] = None):
    """controllers=None rehydrates the persisted --controllers spec; an
    explicit spec is also persisted so later invocations honor it.

    probe_device=True (the long-lived serve path) health-checks the device
    backend out-of-process first and degrades backend="device" to the
    fastest working backend (native C++, else serial) when no accelerator
    answers — the batched scheduler must never run slower than the serial
    loop it replaces (utils/deviceprobe.resolve_backend)."""
    from karmada_tpu.e2e import ControlPlane
    from karmada_tpu.models.cluster import Cluster

    diag: dict = {}
    if probe_device and backend == "device":
        from karmada_tpu.utils.deviceprobe import resolve_backend

        backend, diag = resolve_backend(backend, probe_timeout_s=probe_timeout)
        if backend != "device":
            print(f"WARNING: {diag['degraded']}", file=sys.stderr)
    mesh_shape = None
    if mesh:
        from karmada_tpu.ops.meshing import parse_shape

        mesh_shape = parse_shape(mesh)  # ValueError on malformed BxC
    if aot_cache != "off" and backend == "device":
        # arm the persistent compile cache BEFORE the plane loads (it must
        # precede the first in-process jit — rehydration may already run
        # solves); accelerator artifacts share one dir across hosts, CPU
        # artifacts are host-feature keyed (ops/aotcache).  Device backend
        # only — the host backends never jit, and a probe-degraded plane
        # must not pay an in-process jax import it will never use
        from karmada_tpu.ops import aotcache as aot_mod
        from karmada_tpu.utils.deviceprobe import ACCELERATOR_PLATFORMS

        plat = str(diag.get("platform") or "").lower()
        hint = ("accel"
                if any(p in plat for p in ACCELERATOR_PLATFORMS) else "cpu")
        aot_mod.enable(None if aot_cache in ("", "on") else aot_cache,
                       platform_hint=hint, mesh=mesh_shape)
    cp = ControlPlane(backend=backend, persist_dir=directory, waves=waves,
                      controllers=controllers, pipeline_chunk=pipeline_chunk,
                      mesh_shape=mesh_shape,
                      device_cycle_timeout_s=device_cycle_timeout,
                      explain=explain,
                      batch_window=batch_window,
                      batch_deadline_s=batch_deadline,
                      admission_limit=admission_limit,
                      resident=resident,
                      resident_audit_interval=resident_audit,
                      resident_fused=resident_fused,
                      device_recover_cycles=device_recover_cycles,
                      chaos=chaos, chaos_seed=chaos_seed,
                      rebalance=rebalance,
                      shortlist_k=shortlist_k)
    if controllers is not None:
        cp.apply({"apiVersion": "v1", "kind": "ConfigMap",
                  "metadata": {"namespace": "karmada-system",
                               "name": "controller-manager"},
                  "data": {"controllers": controllers}})
    # rehydrate feature gates persisted by `addons enable/disable`
    gates_cm = cp.store.try_get("ConfigMap", "karmada-system", "feature-gates")
    if gates_cm is not None:
        for gate, value in gates_cm.manifest.get("data", {}).items():
            try:
                cp.gates.set(gate, bool(value) and value not in ("false", "False"))
            except KeyError:
                pass  # gate from a newer/older version: ignore
    # rehydrate simulated members from their recorded capacity
    for cluster in cp.store.list(Cluster.KIND):
        raw = cluster.metadata.annotations.get(SIM_CAPACITY_ANNOTATION)
        if not raw or cluster.metadata.name in cp.members:
            continue
        cap = json.loads(raw)
        cp.add_member(
            cluster.metadata.name,
            cpu_milli=cap.get("cpu_milli", 64_000),
            memory_gi=cap.get("memory_gi", 256),
            pods=cap.get("pods", 110),
            sync_mode=cluster.spec.sync_mode,
        )
    if cp.members:
        cp.tick()  # re-sync member-facing state (RBAC, works) post-rehydrate
    return cp


def _finish(cp) -> None:
    cp.tick()
    cp.checkpoint()


def cmd_init(args) -> int:
    cp = _load_plane(args.dir)
    _finish(cp)
    print(f"control plane initialized at {args.dir}")
    return 0


def cmd_join(args) -> int:
    from karmada_tpu.models.cluster import Cluster

    cp = _load_plane(args.dir)
    if args.name in cp.members:
        print(f"cluster {args.name} already joined", file=sys.stderr)
        return 1
    cp.add_member(
        args.name, cpu_milli=args.cpu * 1000, memory_gi=args.memory_gi,
        pods=args.pods, region=args.region, zone=args.zone,
        provider=args.provider, sync_mode=args.sync_mode,
    )

    def record(c: Cluster) -> None:
        c.metadata.annotations[SIM_CAPACITY_ANNOTATION] = json.dumps({
            "cpu_milli": args.cpu * 1000, "memory_gi": args.memory_gi,
            "pods": args.pods,
        })
    cp.store.mutate(Cluster.KIND, "", args.name, record)
    _finish(cp)
    print(f"cluster {args.name} joined ({args.sync_mode} mode)")
    return 0


def cmd_unjoin(args) -> int:
    cp = _load_plane(args.dir)
    if args.name not in cp.members:
        print(f"unknown cluster {args.name}", file=sys.stderr)
        return 1
    cp.unjoin(args.name)
    _finish(cp)
    print(f"cluster {args.name} unjoined")
    return 0


def _print_table(rows, headers) -> None:
    from karmada_tpu.printers import render

    print(render(headers, rows))


def cmd_get(args) -> int:
    cp = _load_plane(args.dir)
    if args.kind == "pods":  # kubectl-style lowercase alias
        args.kind = "Pod"
    version = getattr(args, "api_version", "")
    if version:
        # honored on store reads with -o json; anything else must error
        # rather than silently print the wrong schema
        if args.cluster or args.output != "json":
            print("--api-version requires -o json and a control-plane read "
                  "(no --cluster)", file=sys.stderr)
            return 1
        from karmada_tpu.models.conversion import REGISTRY as conv

        if not conv.served(args.kind, version):
            print(f"{args.kind} is not served at {version!r}; served: "
                  f"{conv.served_versions(args.kind)}", file=sys.stderr)
            return 1
    if args.cluster:
        handle = _proxy_handle(cp, args.cluster)
        if handle is None:
            return 1
        if args.kind == "Pod" and not (
                args.name and handle.get("Pod", args.namespace, args.name)):
            # the member's synthesized pod plane (admitted replicas) — what
            # `kubectl get pods` shows.  A name naming a real standalone Pod
            # object falls through to the manifest path below.
            pods = [p for p in handle.pods(args.namespace or None)
                    if not args.name or p["name"] == args.name]
            if args.output == "json":
                for p in pods:
                    print(json.dumps(p))
                return 0
            _print_table(
                [[p["name"], p["namespace"], p["owner"],
                  "True" if p["ready"] else "False"] for p in pods]
                or [["-", "-", "-", "-"]],
                ["NAME", "NAMESPACE", "OWNER", "READY"])
            return 0
        objs = (
            [handle.get(args.kind, args.namespace, args.name)]
            if args.name else handle.list(args.kind, args.namespace or None)
        )
        objs = [o for o in objs if o is not None]
    elif args.name:
        o = cp.store.try_get(args.kind, args.namespace, args.name)
        objs = [o] if o is not None else []
    else:
        objs = cp.store.list(args.kind, args.namespace or None)
    if args.output == "json":
        from karmada_tpu.models.codec import registered_kind, to_manifest_typed

        for o in objs:
            if registered_kind(getattr(o, "KIND", None)) and not hasattr(
                    o, "to_manifest"):
                manifest = to_manifest_typed(o, version=version or None)
            elif hasattr(o, "to_manifest"):
                manifest = o.to_manifest()
            else:
                manifest = o.__dict__
            print(json.dumps(manifest, default=str))
        return 0
    from karmada_tpu.printers import render, table_for

    headers, rows = table_for(args.kind, objs)
    print(render(headers, rows))
    return 0


def cmd_apply(args) -> int:
    import yaml

    cp = _load_plane(args.dir)
    with open(args.filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    bad = 0
    for manifest in docs:
        try:
            cp.apply(manifest)
        except ValueError as e:
            # unserved apiVersion for a registered kind (codec
            # from_manifest_typed): CLI convention is stderr + exit 1,
            # never a raw traceback.  Earlier docs of the same file are
            # already in the store — keep going so _finish still ticks
            # and checkpoints them (kubectl apply semantics)
            print(str(e), file=sys.stderr)
            bad += 1
            continue
        print(f"{manifest.get('kind')}/{manifest['metadata']['name']} applied")
    _finish(cp)
    return 1 if bad else 0


def cmd_create(args) -> int:
    """Like apply, but refuses to overwrite (pkg/karmadactl/create /
    kubectl create semantics)."""
    import yaml

    cp = _load_plane(args.dir)
    with open(args.filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    conflicts = 0
    for manifest in docs:
        kind = manifest.get("kind")
        meta = manifest.get("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        if cp.store.try_get(kind, ns, name) is not None:
            # kubectl create: report the conflict, keep creating the rest
            print(f"{kind}/{name} already exists", file=sys.stderr)
            conflicts += 1
            continue
        try:
            cp.apply(manifest)
        except ValueError as e:
            # unserved apiVersion: stderr + nonzero, like the conflicts
            print(str(e), file=sys.stderr)
            conflicts += 1
            continue
        print(f"{kind}/{name} created")
    _finish(cp)
    return 1 if conflicts else 0


def cmd_edit(args) -> int:
    """Open the object in $EDITOR and apply the result
    (pkg/karmadactl/edit / kubectl edit semantics).  Identity fields
    (kind/name/namespace) must survive the edit."""
    import os
    import subprocess
    import tempfile

    cp = _load_plane(args.dir)
    obj = cp.store.try_get(args.kind, args.namespace, args.name)
    if obj is None:
        print(f"{args.kind}/{args.name} not found", file=sys.stderr)
        return 1
    if not hasattr(obj, "manifest"):
        print(f"{args.kind} is a typed API object; edit it with apply/patch",
              file=sys.stderr)
        return 1
    manifest = obj.to_manifest()
    editor = os.environ.get("EDITOR", "vi")
    with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False) as f:
        json.dump(manifest, f, indent=2, default=str)
        path = f.name
    try:
        rc = subprocess.call(f"{editor} {path}", shell=True)
        if rc != 0:
            print(f"editor exited {rc}; edit cancelled", file=sys.stderr)
            return 1
        with open(path) as f:
            try:
                edited = json.load(f)
            except json.JSONDecodeError as e:
                print(f"edited object is not valid JSON: {e}", file=sys.stderr)
                return 1
    finally:
        os.unlink(path)
    if edited == manifest:
        print("no changes")
        return 0
    emeta = edited.get("metadata", {})
    if (edited.get("kind") != args.kind or emeta.get("name") != args.name
            or emeta.get("namespace", "") != (args.namespace or "")):
        print("cannot change kind/name/namespace in an edit", file=sys.stderr)
        return 1
    try:
        cp.apply(edited)
    except ValueError as e:
        # e.g. the edit rewrote apiVersion to an unserved version
        print(str(e), file=sys.stderr)
        return 1
    _finish(cp)
    print(f"{args.kind}/{args.name} edited")
    return 0


def _proxy_handle(cp, cluster: str):
    try:
        return cp.proxy(cluster)
    # vet: ignore[exception-hygiene] proxy error printed to stderr, exit 1
    except Exception as e:  # noqa: BLE001 — ProxyDenied / unknown cluster
        print(f"cluster proxy error: {e}", file=sys.stderr)
        return None


def _err_text(e: Exception) -> str:
    """str(KeyError) reprs its argument (stray quotes); unwrap it."""
    return e.args[0] if isinstance(e, KeyError) and e.args else str(e)


def _stream_pod_logs(args, tail, header: str = "") -> int:
    cp = _load_plane(args.dir)
    handle = _proxy_handle(cp, args.cluster)
    if handle is None:
        return 1
    try:
        lines = handle.logs(args.namespace or "default", args.pod, tail=tail)
    # vet: ignore[exception-hygiene] error printed to stderr, exit 1
    except Exception as e:  # noqa: BLE001 — pod not found
        print(_err_text(e), file=sys.stderr)
        return 1
    if header:
        print(header)
    for line in lines:
        print(line)
    return 0


def cmd_logs(args) -> int:
    """Stream a member pod's log through the cluster proxy
    (pkg/karmadactl/logs)."""
    return _stream_pod_logs(args, args.tail)


def cmd_exec(args) -> int:
    """Run a command in a member pod through the cluster proxy
    (pkg/karmadactl/exec)."""
    cp = _load_plane(args.dir)
    handle = _proxy_handle(cp, args.cluster)
    if handle is None:
        return 1
    try:
        rc, out = handle.exec(args.namespace or "default", args.pod,
                              args.cmd)
    # vet: ignore[exception-hygiene] error printed to stderr, exit 1
    except Exception as e:  # noqa: BLE001 — pod not found
        print(_err_text(e), file=sys.stderr)
        return 1
    if out:
        print(out)
    return rc


def cmd_attach(args) -> int:
    """Attach to a member pod's output stream (pkg/karmadactl/attach).
    The simulator has no interactive session; attach shows the live tail."""
    return _stream_pod_logs(
        args, tail=10,
        header=f"attached to {args.pod} in {args.cluster} (simulated stream)")


def cmd_promote(args) -> int:
    """Adopt a member-cluster resource into the federation
    (pkg/karmadactl/promote)."""
    from karmada_tpu.interpreter.interpreter import prune_for_propagation

    cp = _load_plane(args.dir)
    handle = cp.proxy(args.cluster)
    obj = handle.get(args.kind, args.namespace, args.name)
    if obj is None:
        print(f"{args.kind}/{args.name} not found in {args.cluster}", file=sys.stderr)
        return 1
    cp.apply(prune_for_propagation(obj.to_manifest()))
    _finish(cp)
    print(f"{args.kind}/{args.name} promoted from {args.cluster}")
    return 0


def cmd_cordon(args, uncordon: bool = False) -> int:
    """cordon/uncordon: the NoSchedule taint (pkg/karmadactl/cordon)."""
    from karmada_tpu.models.cluster import Cluster, Taint

    cp = _load_plane(args.dir)
    key = "cluster.karmada.io/cordoned"

    def update(c: Cluster) -> None:
        c.spec.taints = [t for t in c.spec.taints if t.key != key]
        if not uncordon:
            c.spec.taints.append(Taint(key=key, effect="NoSchedule"))
    try:
        cp.store.mutate(Cluster.KIND, "", args.name, update)
    except KeyError:
        print(f"unknown cluster {args.name}", file=sys.stderr)
        return 1
    _finish(cp)
    print(f"cluster {args.name} {'uncordoned' if uncordon else 'cordoned'}")
    return 0


def _node_rows(node_metrics) -> list:
    rows = []
    for n in node_metrics:
        alloc = n.get("allocatable", {})
        usage = n.get("usage", {})
        cpu_alloc = max(alloc.get("cpu", 0), 1)
        rows.append([
            n.get("cluster", "-"), n.get("name", "-"),
            f"{usage.get('cpu', 0)}m",
            f"{100 * usage.get('cpu', 0) // cpu_alloc}%",
            f"{alloc.get('pods', 0)}",
        ])
    return rows


def cmd_top(args) -> int:
    if getattr(args, "endpoint", ""):
        # live telemetry dashboard over /debug/timeseries + /debug/slo
        # (obs/timeseries.render_top): queue depths, the cycle budget
        # breakdown, h2d counter, shed/eviction rates, SLO burn — the
        # plane-level `top`, no --dir needed
        import urllib.error
        import urllib.request

        from karmada_tpu.obs import timeseries as ts_mod

        base = args.endpoint.rstrip("/")
        try:
            # aggregate mode (?points=0): the dashboard needs window
            # deltas and last values, not the whole ring's point lists
            with urllib.request.urlopen(base + "/debug/timeseries?points=0",
                                        timeout=10) as r:
                ts = json.loads(r.read().decode())
            with urllib.request.urlopen(base + "/debug/slo",
                                        timeout=10) as r:
                slo = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            print(f"server error ({e.code}): {e.read().decode()[:200]}",
                  file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
            return 1
        print(ts_mod.render_top(ts, slo))
        return 0
    from karmada_tpu.models.cluster import Cluster

    cp = _load_plane(args.dir)
    if args.what == "nodes":
        # merged NodeMetrics across members (pkg/karmadactl/top nodes via
        # the metrics adapter's resource provider)
        rows = _node_rows(cp.metrics_provider.node_metrics())
        _print_table(rows or [["-"] * 5],
                     ["CLUSTER", "NODE", "CPU", "CPU%", "PODS"])
        return 0
    if args.what == "pods":
        # merged PodMetrics across clusters (pkg/karmadactl/top pods via
        # the metrics adapter fan-out)
        rows = []
        for pm in cp.metrics_provider.pod_metrics(
                "Deployment", args.namespace or "default", args.name or ""):
            usage = pm.get("usage", {})
            rows.append([
                pm.get("cluster", "-"), pm.get("name", "-"),
                f"{usage.get('cpu', 0)}m",
                f"{usage.get('memory', 0) // 1000 // (1 << 20)}Mi",
            ])
        _print_table(rows or [["-", "-", "-", "-"]],
                     ["CLUSTER", "POD", "CPU", "MEMORY"])
        return 0
    rows = []
    for c in cp.store.list(Cluster.KIND):
        s = c.status.resource_summary
        if s is None:
            rows.append([c.name, "-", "-", "-", c.ready])
            continue
        cpu_alloc = s.allocatable.get("cpu")
        cpu_used = s.allocated.get("cpu")
        pct = (
            f"{100 * cpu_used.milli // max(cpu_alloc.milli, 1)}%"
            if cpu_alloc and cpu_used else "-"
        )
        rows.append([
            c.name,
            f"{cpu_used.milli}m/{cpu_alloc.milli}m" if cpu_alloc else "-",
            pct,
            s.allocatable.get("pods", "-"),
            c.ready,
        ])
    _print_table(rows, ["CLUSTER", "CPU(used/alloc)", "CPU%", "PODS", "READY"])
    return 0


def cmd_interpret(args) -> int:
    """Dry-run interpreter customizations against a manifest
    (pkg/karmadactl/interpret)."""
    import yaml

    from karmada_tpu.interpreter.interpreter import ResourceInterpreter

    with open(args.filename) as f:
        manifest = yaml.safe_load(f)
    interp = ResourceInterpreter()
    if args.customization:
        from karmada_tpu.interpreter.declarative import make_hooks
        from karmada_tpu.interpreter.interpreter import Customization

        with open(args.customization) as f:
            cust = yaml.safe_load(f)
        hooks = make_hooks(cust.get("customizations", {}))
        interp.register(Customization(
            api_version=manifest.get("apiVersion", ""),
            kind=manifest.get("kind", ""),
            hooks=hooks,
        ))
    op = args.operation
    if op == "InterpretReplica":
        replicas, req = interp.get_replicas(manifest)
        print(json.dumps({"replicas": replicas, "requirements": (
            {k: str(v) for k, v in req.resource_request.items()} if req else None
        )}))
    elif op == "InterpretHealth":
        print(json.dumps({"health": interp.interpret_health(manifest)}))
    elif op == "ReviseReplica":
        print(json.dumps(interp.revise_replica(manifest, args.replicas)))
    elif op == "InterpretStatus":
        print(json.dumps(interp.reflect_status(manifest)))
    else:
        print(f"unsupported operation {op}", file=sys.stderr)
        return 1
    return 0


def _fetch_json(base: str, path: str):
    """GET one JSON payload from an observability endpoint; raises
    SystemExit-style (None, errcode) tuples are avoided — returns the
    payload or prints the error and returns None."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(base.rstrip("/") + path, timeout=10) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            msg = json.loads(e.read().decode()).get("error", str(e))
        # vet: ignore[exception-hygiene] fallback to the raw error text
        except Exception:  # noqa: BLE001 — non-JSON error body
            msg = str(e)
        print(f"server error ({e.code}): {msg}", file=sys.stderr)
        return None
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return None


def _event_age(evd: dict, now: float) -> str:
    age = max(0.0, now - float(evd.get("last_timestamp") or 0.0))
    if age < 120:
        return f"{age:.0f}s"
    if age < 7200:
        return f"{age / 60:.0f}m"
    return f"{age / 3600:.1f}h"


def _event_rows(events, now: float, with_object: bool = True):
    rows = []
    for evd in events:
        obj = (f"{evd.get('kind')}/"
               + ("/".join(p for p in (evd.get("namespace"),
                                       evd.get("name")) if p)))
        link = []
        if evd.get("cycle_id") is not None:
            link.append(f"cycle={evd['cycle_id']}")
        if evd.get("trace_id"):
            link.append(f"trace={evd['trace_id']}")
        if evd.get("decision_id") is not None:
            link.append(f"decision={evd['decision_id']}")
        row = [
            _event_age(evd, now),
            evd.get("type", ""),
            evd.get("reason", ""),
        ]
        if with_object:
            row.append(obj)
        row += [
            str(evd.get("count", 1)),
            (evd.get("message") or "")[:72],
            ",".join(link) or "-",
        ]
        rows.append(row)
    return rows


def _render_describe(payload: dict) -> None:
    """Kube-style `karmadactl describe ns/binding --endpoint` rendering:
    status summary + the event timeline + the last explain verdict."""
    import time as _time

    print(f"NAME: {payload.get('key')}  ({payload.get('kind')})")
    binding = payload.get("binding")
    if binding:
        cond = binding.get("scheduled_condition") or {}
        where = ", ".join(f"{c['name']}({c['replicas']})"
                          for c in binding.get("clusters", []))
        print(f"STATUS: Scheduled={cond.get('status', 'Unknown')}"
              + (f" ({cond.get('reason')})" if cond.get("reason") else "")
              + (f" — {cond.get('message')}" if cond.get("message") else ""))
        print(f"REPLICAS: {binding.get('replicas')}  "
              f"CLUSTERS: {where or '-'}  "
              f"GENERATION: {binding.get('observed_generation')}"
              f"/{binding.get('generation')}")
        for t in binding.get("eviction_tasks", []):
            print(f"EVICTING: {t['from_cluster']} "
                  f"(reason={t['reason']}, producer={t['producer']})")
    else:
        print("STATUS: binding not present in the live store")
    decision = payload.get("decision")
    if decision:
        print(f"LAST VERDICT: {decision.get('outcome')}"
              + (f" ({decision.get('reason')})"
                 if decision.get("reason") else "")
              + f" — {decision.get('message')}")
        print("  (full verdict table: `karmadactl explain "
              f"{payload.get('key')} --endpoint URL`)")
    events = payload.get("events") or []
    print(f"\nEvents ({len(events)}):")
    rows = _event_rows(events, _time.time(), with_object=False)
    _print_table(rows or [["-"] * 6],
                 ["AGE", "TYPE", "REASON", "COUNT", "MESSAGE", "LINKS"])


def cmd_events(args) -> int:
    """The lifecycle ledger's front door (obs/events, /debug/events):

      karmadactl events --endpoint URL            recent-event table
      karmadactl events ns/name --endpoint URL    one binding's timeline
      karmadactl events --endpoint URL --watch    follow new events
    """
    import time as _time

    base = args.endpoint
    if args.target:
        if "/" not in args.target:
            print("expected namespace/name (e.g. default/app-deployment)",
                  file=sys.stderr)
            return 1
        payload = _fetch_json(base, f"/debug/events/{args.target}")
        if payload is None:
            return 1
        _render_describe(payload)
        return 0
    since = None
    first = True
    while True:
        # ctrl-c must exit cleanly wherever it lands — mid-fetch (the
        # 10s urlopen is most of each cycle against a slow endpoint) as
        # much as mid-sleep
        try:
            path = f"/debug/events?n={args.limit}"
            if since is not None:
                path += f"&since={since}"
            payload = _fetch_json(base, path)
            if payload is None:
                return 1
            events = payload.get("recent") or []
            if first:
                stats = payload.get("stats") or {}
                print(f"ledger: {stats.get('recorded')} recorded, "
                      f"{stats.get('coalesced')} coalesced, "
                      f"{stats.get('evicted')} evicted, "
                      f"{stats.get('retained')} retained over "
                      f"{stats.get('objects')} object(s)")
            rows = _event_rows(events, _time.time())
            if rows or first:
                _print_table(rows or [["-"] * 7],
                             ["AGE", "TYPE", "REASON", "OBJECT", "COUNT",
                              "MESSAGE", "LINKS"])
            first = False
            for evd in events:
                # the ACTIVITY cursor (not the event id): a coalesced
                # repeat bumps last_seq, so a shed storm collapsing onto
                # one tail entry keeps surfacing; the server pages
                # OLDEST-first past the cursor, so a burst wider than
                # --limit drains over successive polls instead of being
                # skipped
                since = max(since or 0, int(evd.get("last_seq") or 0))
            if not args.watch:
                return 0
            if len(events) < args.limit:
                _time.sleep(max(args.interval, 0.2))
        except KeyboardInterrupt:
            return 0


def cmd_describe(args) -> int:
    """Detailed single-object view incl. recorded events
    (pkg/karmadactl/describe).  With --endpoint, `karmadactl describe
    ns/binding --endpoint URL` renders a live serve plane's kube-style
    view instead: status + the lifecycle-ledger timeline
    (/debug/events/{ns}/{name}) + the last explain verdict."""
    if getattr(args, "endpoint", ""):
        if (args.kind or "").lower() in ("incident", "incidents"):
            # `karmadactl describe incident inc-0001-... --endpoint URL`:
            # dump the one forensic bundle (the incidents-command twin)
            if not args.name:
                print("describe incident expects an incident ID "
                      "(see `karmadactl incidents --endpoint URL`)",
                      file=sys.stderr)
                return 1
            bundle = _fetch_json(args.endpoint,
                                 f"/debug/incidents/{args.name}")
            if bundle is None:
                return 1
            print(json.dumps(bundle, indent=2, default=str))
            return 0
        target = args.kind if "/" in (args.kind or "") else (
            f"{args.namespace}/{args.name}"
            if args.name and args.namespace else "")
        ns, _, nm = target.partition("/")
        if not ns or not nm:
            print("describe --endpoint expects namespace/name "
                  "(e.g. karmadactl describe default/app-deployment "
                  "--endpoint URL)", file=sys.stderr)
            return 1
        payload = _fetch_json(args.endpoint, f"/debug/events/{target}")
        if payload is None:
            return 1
        _render_describe(payload)
        return 0
    if not args.name:
        print("usage: karmadactl describe KIND NAME [-n NS] | "
              "karmadactl describe NS/NAME --endpoint URL",
              file=sys.stderr)
        return 1
    cp = _load_plane(args.dir)
    if args.cluster:
        try:
            obj = cp.proxy(args.cluster).get(args.kind, args.namespace, args.name)
        # vet: ignore[exception-hygiene] proxy error printed to stderr, exit 1
        except Exception as e:  # noqa: BLE001 — ProxyDenied / unknown cluster
            print(f"cluster proxy error: {e}", file=sys.stderr)
            return 1
    else:
        obj = cp.store.try_get(args.kind, args.namespace, args.name)
    if obj is None:
        print(f"{args.kind}/{args.name} not found", file=sys.stderr)
        return 1
    manifest = obj.to_manifest() if hasattr(obj, "to_manifest") else obj.__dict__
    print(json.dumps(manifest, default=lambda o: getattr(o, "__dict__", str(o)),
                     indent=2))
    events = cp.events(kind=args.kind, namespace=args.namespace or None,
                       name=args.name)
    if events:
        print("\nEvents:")
        for e in events[-12:]:
            print(f"  {e.type}\t{e.reason}\t{e.message}")
    return 0


def cmd_delete(args) -> int:
    cp = _load_plane(args.dir)
    try:
        cp.delete(args.kind, args.namespace, args.name)
    except KeyError:
        print(f"{args.kind}/{args.name} not found", file=sys.stderr)
        return 1
    _finish(cp)
    print(f"{args.kind}/{args.name} deleted")
    return 0


def _parse_kv_edits(pairs):
    """kubectl-style edits: `k=v` sets, `k-` removes."""
    sets, removes = {}, []
    for p in pairs:
        if p.endswith("-"):
            removes.append(p[:-1])
        elif "=" in p:
            k, v = p.split("=", 1)
            sets[k] = v
        else:
            raise ValueError(f"expected key=value or key-, got {p!r}")
    return sets, removes


def cmd_meta_edit(args, field: str) -> int:
    """label / annotate (pkg/karmadactl/label, annotate)."""
    cp = _load_plane(args.dir)
    try:
        sets, removes = _parse_kv_edits(args.pairs)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1

    def update(obj) -> None:
        target = getattr(obj.metadata, field)
        target.update(sets)
        for k in removes:
            target.pop(k, None)
    try:
        cp.store.mutate(args.kind, args.namespace, args.name, update)
    except KeyError:
        print(f"{args.kind}/{args.name} not found", file=sys.stderr)
        return 1
    _finish(cp)
    print(f"{args.kind}/{args.name} {field} updated")
    return 0


def cmd_taint(args) -> int:
    """Add/remove cluster taints: `key=value:Effect` adds, `key-` removes
    (pkg/karmadactl/taint)."""
    from karmada_tpu.models.cluster import Cluster, Taint

    cp = _load_plane(args.dir)
    adds, removes = [], []
    for spec in args.taints:
        if spec.endswith("-"):
            removes.append(spec[:-1])
            continue
        if ":" not in spec:
            print(f"expected key[=value]:Effect or key-, got {spec!r}",
                  file=sys.stderr)
            return 1
        kv, effect = spec.rsplit(":", 1)
        key, _, value = kv.partition("=")
        adds.append(Taint(key=key, value=value, effect=effect))

    def update(c: Cluster) -> None:
        keep = [t for t in c.spec.taints
                if t.key not in removes and t.key not in {a.key for a in adds}]
        c.spec.taints = keep + adds
    try:
        cp.store.mutate(Cluster.KIND, "", args.name, update)
    except KeyError:
        print(f"unknown cluster {args.name}", file=sys.stderr)
        return 1
    _finish(cp)
    print(f"cluster {args.name} tainted")
    return 0


def _model_registry():
    """kind -> dataclass for every registered API type."""
    from karmada_tpu.models.codec import model_registry

    return model_registry()


def _format_versions(storage: str, served) -> str:
    """One VERSIONS rendering for local and --server api-resources: every
    served version, the storage version starred."""
    return ",".join(v + ("*" if v == storage else "") for v in served)


def cmd_api_resources(args) -> int:
    """List every registered API kind with its served versions
    (pkg/karmadactl/apiresources; the VERSIONS column marks the storage
    version with *)."""
    from karmada_tpu.models.conversion import REGISTRY as conv

    rows = []
    for kind, cls in sorted(_model_registry().items()):
        rows.append([kind, cls.__module__.rsplit(".", 1)[-1],
                     cls.__name__,
                     _format_versions(cls.API_VERSION,
                                      conv.served_versions(kind))])
    _print_table(rows, ["KIND", "GROUP", "TYPE", "VERSIONS"])
    return 0


def _render_decision(d: dict) -> None:
    """Render one explain-plane Decision: the kube-scheduler-style
    one-liner plus the per-cluster verdict table."""
    from karmada_tpu.obs.decisions import REASON_LABEL

    print(f"BINDING: {d['key']}")
    print(f"OUTCOME: {d['outcome']}"
          + (f" (dominant reason: {d['reason']})" if d.get("reason") else ""))
    print(f"MESSAGE: {d.get('message', '')}")
    if d.get("trace_id"):
        print(f"TRACE:   {d['trace_id']}  (karmadactl trace --endpoint "
              f"URL {d['trace_id']})")
    print(f"BACKEND: {d.get('backend', '?')}")
    rows = []
    for c in d.get("clusters", []):
        reasons = ", ".join(REASON_LABEL.get(r, r) for r in c.get("reasons", []))
        rows.append([
            c["name"],
            str(c.get("replicas", 0)),
            "ok" if not c.get("verdict") else f"0x{c['verdict']:x}",
            reasons or "-",
            "-" if c.get("score") is None else str(c["score"]),
            "-" if c.get("avail") is None else str(c["avail"]),
            "-" if c.get("static_weight") is None else str(c["static_weight"]),
            "-" if c.get("plugin_score") is None else str(c["plugin_score"]),
        ])
    if rows:
        _print_table(rows, ["CLUSTER", "REPLICAS", "VERDICT", "REASONS",
                            "SCORE", "AVAIL", "STATIC_W", "PLUGIN"])
    if d.get("clusters_omitted"):
        print(f"({d['clusters_omitted']} more rejected cluster(s) omitted; "
              "reason_counts cover the whole fleet)")
    if d.get("reason_counts"):
        counts = ", ".join(f"{r}={n}" for r, n in
                           sorted(d["reason_counts"].items()))
        print(f"REJECTIONS: {counts}")


def _explain_remote(args) -> int:
    """`karmadactl explain <namespace>/<binding> --endpoint URL`: fetch a
    placement decision from a serve process's explain plane
    (`serve --explain --metrics-port ...`) and render it; with no binding
    argument, list the recent decisions + the unschedulable shelf."""
    import urllib.error
    import urllib.request

    base = args.endpoint.rstrip("/")
    path = ("/debug/explain" if not args.kind
            else f"/debug/explain/{args.kind}")
    try:
        with urllib.request.urlopen(base + path, timeout=10) as r:
            payload = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            msg = json.loads(e.read().decode()).get("error", str(e))
        # vet: ignore[exception-hygiene] fallback to the raw error text
        except Exception:  # noqa: BLE001 — non-JSON error body
            msg = str(e)
        print(f"server error ({e.code}): {msg}", file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    if args.kind:
        _render_decision(payload)
        return 0
    if not payload.get("enabled", False):
        print("explain plane is disabled on the server "
              "(serve --explain to arm it)", file=sys.stderr)
        return 1
    rows = [
        [d["key"], d["outcome"], d.get("reason") or "-",
         (d.get("message") or "")[:60]]
        for d in payload.get("unschedulable", []) + payload.get("decisions", [])
    ]
    _print_table(rows or [["-"] * 4],
                 ["BINDING", "OUTCOME", "REASON", "MESSAGE"])
    return 0


def cmd_explain(args) -> int:
    """Two modes (pkg/karmadactl/explain + the explain plane):

    * `karmadactl explain <Kind>` — field documentation from the
      dataclass tree, as before;
    * `karmadactl explain <namespace>/<binding> --endpoint URL` — the
      per-binding placement verdict from a serve process's explain plane
      (why it landed where it did / why it is unschedulable).
    """
    import dataclasses
    import typing

    if getattr(args, "endpoint", "") or (args.kind and "/" in args.kind):
        if not getattr(args, "endpoint", ""):
            print("explaining a binding decision needs --endpoint URL "
                  "(the serve process's observability endpoint)",
                  file=sys.stderr)
            return 1
        return _explain_remote(args)
    if not args.kind:
        print("usage: karmadactl explain <Kind> | "
              "karmadactl explain <namespace>/<binding> --endpoint URL",
              file=sys.stderr)
        return 1

    registry = _model_registry()
    cls = registry.get(args.kind)
    if cls is None:
        print(f"unknown kind {args.kind}; see `karmadactl api-resources`",
              file=sys.stderr)
        return 1

    def walk(c, indent: int, seen) -> None:
        if c in seen or indent > 3 * 2:
            return
        seen = seen | {c}
        try:
            hints = typing.get_type_hints(c)
        # vet: ignore[exception-hygiene] unresolvable hints degrade to declared field types
        except Exception:  # noqa: BLE001 — unresolvable forward refs
            hints = {}
        for f in dataclasses.fields(c):
            t = hints.get(f.name, f.type)
            name = getattr(t, "__name__", None) or str(t)
            print(" " * indent + f"{f.name}\t<{name}>")
            origin = typing.get_origin(t)
            sub = typing.get_args(t) if origin else (t,)
            for s in sub:
                if dataclasses.is_dataclass(s):
                    walk(s, indent + 2, seen)
    print(f"KIND: {args.kind}")
    walk(cls, 0, frozenset())
    return 0


def cmd_token(args) -> int:
    """Create/list bootstrap tokens for pull-mode registration
    (pkg/karmadactl/token, kubeadm-style). Tokens live in the
    karmada-system/bootstrap-tokens ConfigMap."""
    import secrets

    cp = _load_plane(args.dir)
    ns, name = "karmada-system", "bootstrap-tokens"
    holder = cp.store.try_get("ConfigMap", ns, name)
    if args.action == "create":
        token = secrets.token_hex(8)
        if holder is None:
            cp.apply({"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"namespace": ns, "name": name},
                      "data": {token: "valid"}})
        else:
            def add(obj) -> None:
                obj.manifest.setdefault("data", {})[token] = "valid"
            cp.store.mutate("ConfigMap", ns, name, add)
        _finish(cp)
        print(token)
        return 0
    tokens = (holder.manifest.get("data", {}) if holder is not None else {})
    _print_table([[t, v] for t, v in tokens.items()] or [["-", "-"]],
                 ["TOKEN", "STATUS"])
    return 0


def cmd_register(args) -> int:
    """Pull-mode registration: token-gated agent bootstrap
    (pkg/karmadactl/register — the kubeadm-join analog)."""
    cp = _load_plane(args.dir)
    holder = cp.store.try_get("ConfigMap", "karmada-system", "bootstrap-tokens")
    tokens = holder.manifest.get("data", {}) if holder is not None else {}
    if tokens.get(args.token) != "valid":
        print("invalid or expired bootstrap token", file=sys.stderr)
        return 1
    if args.name in cp.members:
        print(f"cluster {args.name} already registered", file=sys.stderr)
        return 1
    from karmada_tpu.models.cluster import Cluster

    cp.add_member(args.name, cpu_milli=args.cpu * 1000,
                  memory_gi=args.memory_gi, pods=args.pods,
                  region=args.region, sync_mode="Pull")

    def record(c: Cluster) -> None:
        c.metadata.annotations[SIM_CAPACITY_ANNOTATION] = json.dumps({
            "cpu_milli": args.cpu * 1000, "memory_gi": args.memory_gi,
            "pods": args.pods,
        })
    cp.store.mutate(Cluster.KIND, "", args.name, record)
    _finish(cp)
    print(f"cluster {args.name} registered (Pull mode, CSR approved)")
    return 0


def cmd_unregister(args) -> int:
    """Pull-mode teardown (pkg/karmadactl/unregister)."""
    return cmd_unjoin(args)


def cmd_addons(args) -> int:
    """Enable/disable optional subsystems via their feature gates
    (pkg/karmadactl/addons: estimator/descheduler/search/metrics-adapter).
    Gate choices map onto the pkg/features registry names."""
    gate_by_addon = {
        "resource-quota-estimate": "ResourceQuotaEstimate",
        "multicluster-service": "MultiClusterService",
        "quota-enforcement": "FederatedQuotaEnforcement",
        "stateful-failover": "StatefulFailoverInjection",
        "priority-queue": "ControllerPriorityQueue",
    }
    cp = _load_plane(args.dir)
    gate = gate_by_addon[args.addon]
    cp.gates.set(gate, args.action == "enable")
    # persist the choice; _load_plane rehydrates it on every later invocation
    cp.apply({"apiVersion": "v1", "kind": "ConfigMap",
              "metadata": {"namespace": "karmada-system", "name": "feature-gates"},
              "data": dict(cp.gates.snapshot())})
    _finish(cp)
    print(f"addon {args.addon}: {gate}={args.action == 'enable'}")
    return 0


def _deep_merge(dst: dict, src: dict) -> dict:
    for k, v in src.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict):
            if isinstance(dst.get(k), dict):
                _deep_merge(dst[k], v)
            else:
                # fresh subtree: recurse into an empty dict so nulls are
                # stripped on create too (RFC 7386 semantics)
                dst[k] = _deep_merge({}, v)
        else:
            dst[k] = v
    return dst


def cmd_patch(args) -> int:
    """Strategic-merge-style patch of a template object
    (pkg/karmadactl/patch): `-p '{"spec": {"replicas": 5}}'`; null deletes
    a key."""
    cp = _load_plane(args.dir)
    try:
        patch = json.loads(args.patch)
    except json.JSONDecodeError as e:
        print(f"invalid patch JSON: {e}", file=sys.stderr)
        return 1
    if not isinstance(patch, dict):
        print("patch must be a JSON object", file=sys.stderr)
        return 1

    if any(k in patch for k in ("kind", "apiVersion")):
        print("cannot patch kind/apiVersion", file=sys.stderr)
        return 1
    meta_patch = patch.get("metadata", {})
    if any(k in meta_patch for k in ("name", "namespace", "uid")):
        print("cannot patch metadata identity fields", file=sys.stderr)
        return 1

    def update(obj) -> None:
        if not hasattr(obj, "manifest"):
            raise TypeError(
                f"{args.kind} is a typed API object; edit it with apply"
            )
        _deep_merge(obj.manifest, patch)
        # to_manifest() re-syncs metadata from ObjectMeta, so label/
        # annotation patches must land there too or they silently revert
        for field, target in (("labels", obj.metadata.labels),
                              ("annotations", obj.metadata.annotations)):
            if field in meta_patch:
                _deep_merge(target, meta_patch[field] or {})
    try:
        cp.store.mutate(args.kind, args.namespace, args.name, update)
    except KeyError:
        print(f"{args.kind}/{args.name} not found", file=sys.stderr)
        return 1
    except TypeError as e:
        print(str(e), file=sys.stderr)
        return 1
    _finish(cp)
    print(f"{args.kind}/{args.name} patched")
    return 0


def cmd_completion(args) -> int:
    """Emit a bash completion function over the live subcommand set
    (pkg/karmadactl/completion)."""
    cmds = " ".join(sorted([*COMMANDS, "version"]))
    print(f"""_karmadactl_completions() {{
  COMPREPLY=($(compgen -W "{cmds}" -- "${{COMP_WORDS[COMP_CWORD]}}"))
}}
complete -F _karmadactl_completions karmadactl""")
    return 0


def cmd_options(args) -> int:
    """List global flags (pkg/karmadactl/options)."""
    print("--dir   control plane directory (required)")
    return 0


def cmd_deinit(args) -> int:
    """Tear down the persisted control plane (pkg/karmadactl/deinit)."""
    import shutil

    if not args.force:
        print("refusing to delete without --force", file=sys.stderr)
        return 1
    shutil.rmtree(args.dir, ignore_errors=True)
    print(f"control plane at {args.dir} removed")
    return 0


def cmd_tick(args) -> int:
    try:
        cp = _load_plane(args.dir, backend=args.backend, waves=args.waves,
                         controllers=args.controllers)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    n = cp.tick()
    cp.checkpoint()
    print(f"{n} reconciles")
    return 0


def cmd_serve(args) -> int:
    """Run the control plane long-lived: every controller on its own
    thread, periodic hooks on a timer (the karmada-controller-manager /
    scheduler / webhook processes rolled into one, Runtime.serve)."""
    import os
    import time as _time

    if args.check_invariants:
        # arm BEFORE the plane loads: rehydration may already run solves
        from karmada_tpu.analysis import guards
        from karmada_tpu.utils import locks as locks_mod

        guards.arm()
        # the lock watchdog rides the same arming flag: over-threshold
        # holds trip karmada_lock_watchdog_trips_total and show in the
        # /debug/state locks block instead of wedging silently
        locks_mod.start_watchdog()
        print("runtime invariant guards armed "
              "(solver entry + d2h boundaries; analysis/guards) + "
              "lock race detector / deadlock watchdog (utils/locks)")
    explain_rate = 0.0
    if args.explain:
        try:
            explain_rate = float(args.explain)
        except ValueError:
            print(f"--explain rate must be a number in (0, 1], "
                  f"got {args.explain!r}", file=sys.stderr)
            return 1
        if not 0.0 < explain_rate <= 1.0:
            print(f"--explain rate must be in (0, 1], got {explain_rate}",
                  file=sys.stderr)
            return 1
    shortlist_k = None
    if args.shortlist:
        try:
            shortlist_k = int(args.shortlist)
        except ValueError:
            print(f"--shortlist k must be an integer, got "
                  f"{args.shortlist!r}", file=sys.stderr)
            return 1
        if shortlist_k <= 0:
            print(f"--shortlist k must be positive, got {shortlist_k}",
                  file=sys.stderr)
            return 1
    rebalance_interval = None
    if args.rebalance is not None:
        try:
            rebalance_interval = float(args.rebalance)
        except ValueError:
            print(f"--rebalance interval must be a number of seconds, "
                  f"got {args.rebalance!r}", file=sys.stderr)
            return 1
        if rebalance_interval <= 0:
            print(f"--rebalance interval must be positive, got "
                  f"{rebalance_interval}", file=sys.stderr)
            return 1
    loadgen_scenario = None
    if args.loadgen:
        from karmada_tpu.loadgen import get_scenario

        try:
            loadgen_scenario = get_scenario(args.loadgen)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
    facade_addr = None
    if args.facade:
        # validate BEFORE the plane loads: a typo'd address must fail
        # the command, not die after controllers are already running
        host, _, port_s = args.facade.rpartition(":")
        try:
            facade_addr = (host or "127.0.0.1", int(port_s))
        except ValueError:
            print(f"--facade ADDR must be HOST:PORT (or :PORT), got "
                  f"{args.facade!r}", file=sys.stderr)
            return 1
    if args.chaos:
        # validate the fault spec BEFORE the plane loads: a typo'd chaos
        # spec must fail the command, never silently arm nothing
        from karmada_tpu import chaos as chaos_mod

        try:
            chaos_mod.parse_spec(args.chaos, seed=args.chaos_seed)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
    try:
        cp = _load_plane(args.dir, backend=args.backend, waves=args.waves,
                         controllers=args.controllers,
                         probe_device=not args.no_probe,
                         probe_timeout=args.probe_timeout,
                         device_cycle_timeout=(
                             args.device_cycle_timeout
                             if args.device_cycle_timeout > 0 else None),
                         pipeline_chunk=args.pipeline_chunk,
                         mesh=args.mesh, explain=explain_rate,
                         batch_window=args.batch_window,
                         batch_deadline=(args.batch_deadline
                                         if args.batch_deadline > 0
                                         else None),
                         admission_limit=(args.admission_limit
                                          if args.admission_limit > 0
                                          else None),
                         resident=args.resident,
                         resident_audit=args.resident_audit,
                         resident_fused=args.resident_fused,
                         device_recover_cycles=(
                             args.device_recover_cycles
                             if args.device_recover_cycles > 0 else None),
                         chaos=args.chaos or None,
                         chaos_seed=args.chaos_seed,
                         aot_cache=args.aot_cache,
                         rebalance=rebalance_interval,
                         shortlist_k=shortlist_k)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    if args.aot_cache != "off" and cp.scheduler.backend == "device":
        # AOT warm-start (ops/aotcache): pre-compile every pow2 batch shape
        # x jit variant this configuration can dispatch on a background
        # thread, so the first real cycle of each shape deserializes from
        # the persistent cache instead of paying the XLA compile.  Device
        # backend only — the host backends never build solver executables.
        from karmada_tpu.models.cluster import Cluster as _Cluster
        from karmada_tpu.ops import aotcache as aot_mod

        sched = cp.scheduler
        warm_shapes = aot_mod.warm_shapes(sched.batch_window,
                                          sched.pipeline_chunk)
        warm_variants = aot_mod.variants_for(
            sched.explain, sched.batch_window > sched.pipeline_chunk,
            fused=getattr(sched, "resident_fused", False),
            shortlist=bool(getattr(sched, "shortlist_k", None)))
        resident_cap = None
        if getattr(sched, "resident_fused", False):
            # the fused gather's jit signature includes the slot-store
            # capacity, and at boot the resident plane has adopted
            # nothing yet (_resident_slot_cap would fall to the 64
            # floor): derive the adoption-time cap from the persisted
            # store's binding count so the warmed executables match the
            # geometry the first real cycles will gather at
            from karmada_tpu.models.work import ResourceBinding as _RB
            from karmada_tpu.ops.tensors import _next_pow2 as _np2

            n_rb = len(cp.store.list(_RB.KIND))
            resident_cap = _np2(max(n_rb, 64), 64)
        aot_mod.start_background_warmup(
            lambda: list(cp.store.list(_Cluster.KIND)), sched._general,
            shapes=warm_shapes, variants=warm_variants, waves=sched.waves,
            keep_sel=sched.enable_empty_workload_propagation,
            resident_cap=resident_cap,
            shortlist_k=getattr(sched, "shortlist_k", None))
        aot_state = aot_mod.state_payload()
        if aot_state["armed"]:
            print(f"AOT executable plane armed: persistent compile cache "
                  f"at {aot_state['cache_dir']} (key {aot_state['key']}); "
                  f"background warm-start over {len(warm_shapes)} pow2 "
                  f"shape(s) x {len(warm_variants)} jit variant(s) — "
                  "progress in /debug/state aot section")
        else:
            print("WARNING: persistent compile cache unavailable on this "
                  "jax; background warm-start still pre-compiles "
                  f"{len(warm_shapes)} shape(s) x {len(warm_variants)} "
                  "variant(s) for THIS process, but restarts will re-pay "
                  "the compiles", file=sys.stderr)
    if args.chaos:
        print(f"CHAOS PLANE ARMED (seed {args.chaos_seed}): {args.chaos} — "
              "deterministic faults will fire at the named seams; state "
              "at /debug/chaos")
    if rebalance_interval is not None:
        print(f"rebalance plane armed: drain-and-re-place cycle every "
              f"{rebalance_interval:g}s (graceful evictions under the "
              "shared pacing budget, re-placed with origin=rebalance); "
              "state at /debug/rebalance, render with "
              "`karmadactl rebalance --endpoint URL`")
    if args.resident:
        if cp.scheduler.backend == "device":
            fused_note = (" + FUSED device gather (binding rows never "
                          "re-upload)" if args.resident_fused else "")
            print("resident-state plane armed: cluster tensors stay "
                  "device-resident between cycles, advanced by watch "
                  f"deltas (parity audit every {args.resident_audit} "
                  f"cycle(s)){fused_note}; state at /debug/resident, "
                  "render with `karmadactl resident --endpoint URL`")
        else:
            print(f"WARNING: --resident needs the device backend (running "
                  f"backend={cp.scheduler.backend}); the resident plane "
                  "is not armed", file=sys.stderr)
    elif args.resident_fused:
        print("WARNING: --resident-fused requires --resident; the fused "
              "gather path is not armed", file=sys.stderr)
    if shortlist_k is not None:
        if cp.scheduler.shortlist_k:
            fused_sl = (" via the device slot store (sub-batch gathers "
                        "straight into the candidate union)"
                        if args.resident_fused and args.resident else "")
            print(f"shortlist plane armed (k={shortlist_k}): chunks at/"
                  f"above {cp.scheduler.shortlist_min_cells} dense cells "
                  "run the two-tier solve (tier-1 candidate kernel -> "
                  f"dense solver over the candidate union){fused_sl}; "
                  "super-k_max rows are truncated out and re-solved "
                  "per-binding at full width; fallbacks are counted in "
                  "karmada_shortlist_fallbacks_total (row-granular in "
                  "karmada_shortlist_fallback_rows_total); state in "
                  "/debug/state shortlist section")
        else:
            print(f"WARNING: --shortlist needs the device backend "
                  f"(running backend={cp.scheduler.backend}); the "
                  "shortlist plane is not armed", file=sys.stderr)
    if explain_rate > 0:
        if args.metrics_port >= 0:
            pct = f"{explain_rate:.0%}" if explain_rate < 1 else "every"
            print(f"explain plane armed ({pct} cycle(s) sampled): "
                  "per-binding placement verdicts at /debug/explain; "
                  "render with `karmadactl explain NAMESPACE/BINDING "
                  "--endpoint URL`")
        else:
            print("WARNING: --explain is armed but --metrics-port is "
                  "disabled, so /debug/explain is unreachable; add "
                  "--metrics-port PORT to read the decisions",
                  file=sys.stderr)
    if args.telemetry:
        try:
            ring_cap = int(args.telemetry)
        except ValueError:
            print(f"--telemetry ring capacity must be an integer, got "
                  f"{args.telemetry!r}", file=sys.stderr)
            return 1
        from karmada_tpu.obs import slo as slo_mod
        from karmada_tpu.obs import timeseries as ts_mod

        ts_mod.configure(capacity=ring_cap,
                         min_interval_s=max(args.telemetry_interval, 0.0))
        ev = slo_mod.configure(objectives=slo_mod.default_objectives(
            schedule_deadline_s=args.slo_deadline))
        watchdog_note = (
            f"regression watchdog armed (baseline "
            f"{ev.watchdog.baseline_bps:g} bindings/s, floor "
            f"{ev.watchdog.floor_bps:g})" if ev.watchdog is not None
            else "regression watchdog off (no committed baseline "
                 "envelope found)")
        print(f"telemetry plane armed: {ring_cap}-sample metric ring on "
              f"the scheduler cycle clock (min interval "
              f"{args.telemetry_interval:g}s), SLO burn rates at "
              f"/debug/slo (schedule/dwell p99 bound "
              f"{args.slo_deadline:g}s); {watchdog_note}; render with "
              "`karmadactl top --endpoint URL`")
        if args.metrics_port < 0:
            print("WARNING: --telemetry is armed but --metrics-port is "
                  "disabled, so /debug/timeseries and /debug/slo are "
                  "unreachable (the karmada_slo_* gauges still update)",
                  file=sys.stderr)
    if not args.no_incidents:
        # incident plane (obs/incidents), armed by default: every
        # detector's trigger captures a rate-limited forensic bundle
        # under the plane dir
        from karmada_tpu.obs import incidents as incidents_mod

        incidents_mod.configure(
            os.path.join(args.dir, "incidents"),
            cooldown_s=max(args.incident_cooldown, 0.0))
        print("incident plane armed: trigger-driven forensic bundles "
              f"under {os.path.join(args.dir, 'incidents')} "
              f"(cooldown {max(args.incident_cooldown, 0.0):g}s per "
              "trigger); index at /debug/incidents, render with "
              "`karmadactl incidents --endpoint URL`")
    if args.feature_gates:
        cp.gates.set_from_string(args.feature_gates)
    cp.runtime._periodic_interval_s = args.sync_period  # noqa: SLF001
    if args.trace_buffer > 0:
        # arm the flight recorder before any controller thread runs so the
        # very first scheduling cycle is captured (karmada_tpu/obs)
        from karmada_tpu import obs as obs_mod

        obs_mod.TRACER.configure(capacity=args.trace_buffer)
        if args.metrics_port >= 0:
            print(f"flight recorder on: last {args.trace_buffer} traces at "
                  "/debug/traces (+ /debug/traces/slow, /debug/traces/ID); "
                  "fetch with `karmadactl trace --endpoint URL`")
        else:
            print("WARNING: --trace-buffer is armed but --metrics-port is "
                  "disabled, so /debug/traces is unreachable; add "
                  "--metrics-port PORT to read the recorder",
                  file=sys.stderr)
    # bind the observability endpoint BEFORE starting controller threads:
    # a port clash must fail fast, not skip the shutdown/checkpoint path
    obs = None
    if args.metrics_port >= 0:
        from karmada_tpu.utils.httpserve import ObservabilityServer

        obs = ObservabilityServer(
            store=cp.store,
            # /debug/profile artifacts land under the plane dir so a
            # capture survives the process (the profileflag contract)
            profile_dir=os.path.join(args.dir, "profiles"))
        url = obs.start(port=args.metrics_port)
        print(f"observability endpoint at {url} "
              "(/metrics /healthz /readyz /debug/state /debug/traces)")
    api = None
    if args.api_port >= 0:
        from karmada_tpu.search.httpapi import QueryPlaneServer

        api = QueryPlaneServer(
            cp.store, cp.members, cp.cluster_proxy,
            search_cache=cp.search_cache,
            metrics_provider=cp.metrics_provider,
            apply_fn=cp.apply, auth=cp.unified_auth)
        api_url = api.start(port=args.api_port)
        print(f"query plane at {api_url} "
              "(cluster proxy, search cache, metrics adapter; "
              f"karmadactl --server {api_url})")
    facade_service = None
    if facade_addr is not None:
        # the facade plane (karmada_tpu/facade): scheduler-as-a-service
        # over the wire tier, coalescing concurrent callers into one
        # detached solve per batch — bound before controller threads so
        # a port clash fails fast
        from karmada_tpu import facade as facade_mod

        facade_service = facade_mod.FacadeService(cp.scheduler, cp.store)
        try:
            fh, fp = facade_service.serve(host=facade_addr[0],
                                          port=facade_addr[1])
        except OSError as e:
            print(f"--facade cannot bind {facade_addr[0]}:"
                  f"{facade_addr[1]}: {e}", file=sys.stderr)
            facade_service.close()
            return 1
        facade_mod.set_active(facade_service)
        print(f"facade plane armed at {fh}:{fp} "
              f"(SelectClusters/AssignReplicas/WhatIf, batch window "
              f"{facade_service.batch_window}, deadline "
              f"{facade_service.batch_deadline_s:g}s); counters at "
              "/debug/facade, capacity queries at /whatif "
              "(`karmadactl whatif --endpoint URL`, `karmadactl "
              f"estimate --facade-addr {fh}:{fp}`)")
    cp.runtime.serve()
    loadgen_driver = None
    if loadgen_scenario is not None:
        # real-time synthetic traffic against THIS plane (loadgen/driver):
        # paced injections through the normal store paths, live state at
        # /debug/load, admission/shed accounting in /metrics
        from karmada_tpu.loadgen import LoadDriver

        loadgen_driver = LoadDriver(
            cp, loadgen_scenario, realtime=True,
            realtime_rate=args.loadgen_rate, seed=args.loadgen_seed,
        ).start()
        print(f"load generator running: scenario {loadgen_scenario.name} "
              f"(~{args.loadgen_rate:.0f} arrivals/s, "
              f"{len(loadgen_driver._arrivals)} total); "  # noqa: SLF001
              "live state at /debug/load")
    print(f"serving control plane from {args.dir} "
          f"(backend={cp.scheduler.backend}, {len(cp.members)} members); "
          "ctrl-c to stop")
    try:
        next_checkpoint = _time.time() + args.checkpoint_period
        while True:
            _time.sleep(0.5)
            if _time.time() >= next_checkpoint:
                cp.checkpoint()
                next_checkpoint = _time.time() + args.checkpoint_period
    except KeyboardInterrupt:
        pass
    finally:
        if loadgen_driver is not None:
            loadgen_driver.stop()
        if facade_service is not None:
            from karmada_tpu import facade as facade_mod

            facade_mod.set_active(None)
            facade_service.close()
        if obs is not None:
            obs.stop()
        if api is not None:
            api.stop()
        if not args.no_incidents:
            from karmada_tpu.obs import incidents as incidents_mod

            incidents_mod.disarm()
        cp.runtime.stop()
        cp.checkpoint()
    return 0


def cmd_vet(args) -> int:
    """Static analysis over the control plane's own source
    (karmada_tpu/analysis): trace-safety, dtype-contract, spec-coverage,
    and lock-discipline passes.  Exit 0 only on zero findings; waivers
    (`# vet: ignore[rule] <why>`) never fail the run but are always
    enumerated.  `--format json` emits the machine-readable summary the
    bench/watch tooling ingests."""
    import os

    from karmada_tpu.analysis.vet import run_vet

    paths = args.paths or [os.path.dirname(os.path.abspath(__file__))]
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        report = run_vet(paths, rules=rules)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.format == "github":
        # GitHub Actions annotation lines: findings become inline
        # ::error markers on the PR diff; the summary line goes to
        # stdout unannotated (tools/check.sh + CI share this entry)
        for f in sorted(report.findings,
                        key=lambda f: (f.file, f.line, f.rule)):
            msg = f.message.replace("\n", " ")
            print(f"::error file={f.file},line={f.line},"
                  f"title=vet {f.rule}::{msg}")
        c = report.counts()
        print(f"vet: {c['findings']} finding(s), {c['waivers']} "
              f"waiver(s) across {report.files} file(s)")
    else:
        print(report.to_json() if args.format == "json"
              else report.render_text())
    return 0 if report.clean else 1


def cmd_loadgen(args) -> int:
    """The sustained-traffic harness front door (karmada_tpu/loadgen):

      karmadactl loadgen                      list the scenario catalog
      karmadactl loadgen --endpoint URL       live /debug/load state of a
                                              serve process (started with
                                              serve --loadgen SCENARIO)
      karmadactl loadgen SCENARIO             compressed-time rehearsal
                                              against an ephemeral
                                              scheduler slice; prints the
                                              SOAK payload JSON
    """
    import urllib.error
    import urllib.request

    from karmada_tpu.loadgen import SCENARIOS, report

    if args.endpoint:
        base = args.endpoint.rstrip("/")
        try:
            with urllib.request.urlopen(base + "/debug/load", timeout=10) as r:
                state = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            print(f"server error ({e.code}): {e.read().decode()[:200]}",
                  file=sys.stderr)
            return 1
        except urllib.error.URLError as e:
            print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
            return 1
        print(report.render_load_state(state))
        return 0
    if not args.scenario:
        rows = [[s.name, str(s.n_bindings), f"{s.load_factor:g}x",
                 "yes" if s.slow else "no", s.description]
                for s in sorted(SCENARIOS.values(), key=lambda s: s.name)]
        _print_table(rows, ["SCENARIO", "BINDINGS", "LOAD", "SLOW",
                            "DESCRIPTION"])
        print("\nrun one compressed: `karmadactl loadgen SCENARIO`; "
              "drive a live plane: `serve --loadgen SCENARIO`")
        return 0
    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, ServiceModel, VirtualClock, get_scenario,
    )

    try:
        scenario = get_scenario(args.scenario)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 1
    clock = VirtualClock()
    model = ServiceModel()
    plane = ServeSlice(scenario, clock, model)
    driver = LoadDriver(plane, scenario, clock=clock, model=model,
                        seed=args.seed)
    payload = driver.run()
    print(json.dumps(payload, indent=2 if args.pretty else None))
    return 0


def cmd_rebalance(args) -> int:
    """Render a live serve process's rebalance plane (/debug/rebalance):
    last detect cycle's per-cluster overcommit/divergence scores,
    eviction and conservation totals, and the shared pacing-budget
    state — whether the drain loop is converged at a glance."""
    import urllib.error
    import urllib.request

    from karmada_tpu.rebalance import render_state

    base = args.endpoint.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/debug/rebalance",
                                    timeout=10) as r:
            state = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        print(f"server error ({e.code}): {e.read().decode()[:200]}",
              file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    print(render_state(state))
    return 0


def cmd_whatif(args) -> int:
    """Ask a live serve process's facade plane a capacity-planning
    question (/whatif, karmada_tpu/facade): a hypothetical solve on a
    copy-on-write fork of live state — placements never move.

      karmadactl whatif --endpoint URL --query placement --replicas 500
      karmadactl whatif --endpoint URL --query cluster-loss
      karmadactl whatif --endpoint URL --query headroom --cpu 1000m
    """
    import urllib.error
    import urllib.parse
    import urllib.request

    params = {"query": args.query, "replicas": str(args.replicas),
              "limit": str(args.limit)}
    if args.cpu:
        params["cpu"] = args.cpu
    if args.memory:
        params["memory"] = args.memory
    if args.cluster:
        params["cluster"] = args.cluster
    if args.duplicated:
        params["divided"] = "false"
    base = args.endpoint.rstrip("/")
    url = base + "/whatif?" + urllib.parse.urlencode(params)
    try:
        with urllib.request.urlopen(url, timeout=120) as r:
            payload = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read().decode())
        except json.JSONDecodeError:
            payload = {"error": str(e)}
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    if payload.get("error"):
        print(payload["error"], file=sys.stderr)
        return 1
    if args.format == "json":
        print(json.dumps(payload, indent=2))
        return 0
    print(_render_whatif(payload))
    return 0


def _render_whatif(payload: dict) -> str:
    """Human rendering of one /whatif answer (whatif.py documents the
    per-query result shapes)."""
    res = payload.get("result", {})
    lines = [f"what-if {payload.get('query')} "
             f"(forked from {payload.get('source')} state)"]
    query = payload.get("query")
    if query == "placement":
        lines.append(f"  replicas requested: {res.get('replicas')}")
        lines.append(f"  outcome: {res.get('outcome')}"
                     + (f" — {res['message']}" if res.get("message") else ""))
        for a in res.get("assignments", []):
            lines.append(f"    {a['cluster']:<24} {a['replicas']} replicas")
    elif query == "cluster-loss":
        lines.append(f"  worst single loss: {res.get('worst') or '(none)'}")
        lines.append(f"  {'CLUSTER':<24} {'HOSTED':>8} {'REPLICAS':>9} "
                     f"{'STRANDED':>9} {'REPLICAS':>9}")
        for row in res.get("ranking", []):
            trunc = (f"  (+{row['truncated']} unprobed)"
                     if row.get("truncated") else "")
            lines.append(
                f"  {row['cluster']:<24} {row['bindings']:>8} "
                f"{row['replicas']:>9} {row['stranded_bindings']:>9} "
                f"{row['stranded_replicas']:>9}{trunc}")
    elif query == "headroom":
        lines.append(f"  max replicas that still fully schedule: "
                     f"{res.get('max_replicas')} "
                     f"({res.get('probes')} probe solves)")
        for a in res.get("assignments", []):
            lines.append(f"    {a['cluster']:<24} {a['replicas']} replicas")
    else:
        lines.append(f"  {json.dumps(res)}")
    return "\n".join(lines)


def cmd_estimate(args) -> int:
    """One AssignReplicas call against a served facade plane over the
    wire tier (serve --facade prints the bound address) — the
    external-scheduler integration path, typed errors and all:

      karmadactl estimate --facade-addr 127.0.0.1:PORT --replicas 50 \\
          --cpu 500m --memory 1Gi
    """
    from karmada_tpu.estimator import wire
    from karmada_tpu.estimator.client import EstimatorError
    from karmada_tpu.facade import FacadeClient

    host, _, port_s = args.facade_addr.rpartition(":")
    try:
        addr = (host or "127.0.0.1", int(port_s))
    except ValueError:
        print(f"--facade-addr must be HOST:PORT, got "
              f"{args.facade_addr!r}", file=sys.stderr)
        return 1
    resource_request = {}
    if args.cpu:
        resource_request["cpu"] = args.cpu
    if args.memory:
        resource_request["memory"] = args.memory
    import uuid

    req = wire.AssignReplicasRequest(
        namespace=args.namespace, name=args.name,
        replicas=args.replicas, resource_request=resource_request,
        divided=not args.duplicated,
        cluster_names=[c for c in args.clusters.split(",") if c],
        # caller-side trace id: lands in the serve process's facade
        # flight record, stitching this CLI call to its coalesced batch
        trace_id=f"cli-{uuid.uuid4().hex[:16]}")
    client = FacadeClient(wire.TcpTransport(addr[0], addr[1], timeout=120))
    try:
        resp = client.assign_replicas(req)
    except EstimatorError as e:
        print(f"estimate failed ({e.kind}): {e}", file=sys.stderr)
        return 1
    finally:
        client.close()
    if args.format == "json":
        print(json.dumps(resp.to_json(), indent=2))
        return 0
    print(f"outcome: {resp.outcome}"
          + (f" — {resp.message}" if resp.message else ""))
    for a in resp.assignments:
        print(f"  {a['cluster']:<24} {a['replicas']} replicas")
    print(f"(coalesced batch {resp.batch_id}, {resp.batch_size} caller(s)"
          + (f", trace {resp.trace_id}" if resp.trace_id else "") + ")")
    return 0 if resp.outcome == "scheduled" else 1


def cmd_resident(args) -> int:
    """Render a live serve process's resident-state plane
    (/debug/resident): generation, vocabulary sizes, row-cache hit rate,
    rebuild reasons, and the last parity-audit outcome — whether the
    plane is running resident or rebuild-per-cycle at a glance."""
    import urllib.error
    import urllib.request

    from karmada_tpu.resident import render_state

    base = args.endpoint.rstrip("/")
    url = base + "/debug/resident"
    if args.recent:
        url += f"?recent={args.recent}"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            state = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        print(f"server error ({e.code}): {e.read().decode()[:200]}",
              file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    print(render_state(state))
    return 0


def cmd_incidents(args) -> int:
    """Render a live serve process's incident plane (/debug/incidents):
    flight-ring stats, capture/suppression totals by trigger, and the
    bundle index.  With an ID, dump that one forensic bundle as JSON
    (also available via `karmadactl describe incident ID`)."""
    if args.id:
        bundle = _fetch_json(args.endpoint, f"/debug/incidents/{args.id}")
        if bundle is None:
            return 1
        print(json.dumps(bundle, indent=2, default=str))
        return 0
    state = _fetch_json(args.endpoint, "/debug/incidents")
    if state is None:
        return 1
    if not state.get("enabled"):
        flight = state.get("flight") or {}
        print("incident plane disarmed (serve arms it automatically); "
              f"flight ring: {flight.get('retained', 0)} record(s) "
              f"retained of {flight.get('recorded', 0)} recorded")
        return 0
    flight = state.get("flight") or {}
    print(f"captured {state.get('captured', 0)} incident(s), "
          f"cooldown {state.get('cooldown_s', 0):g}s per trigger; "
          f"flight ring {flight.get('retained', 0)}/"
          f"{flight.get('capacity', 0)} record(s)")
    by_trigger = state.get("by_trigger") or {}
    suppressed = state.get("suppressed") or {}
    if by_trigger or suppressed:
        print("trigger totals:")
        for kind in sorted(set(by_trigger) | set(suppressed)):
            print(f"  {kind:<22} captured {by_trigger.get(kind, 0):<4} "
                  f"suppressed {suppressed.get(kind, 0)}")
    incidents = state.get("incidents") or []
    if not incidents:
        print("no incident bundles captured")
        return 0
    print(f"{'ID':<32} {'TRIGGER':<22} {'CAPTURE':>9}  SUMMARY")
    for e in incidents:
        print(f"{e.get('id', ''):<32} {e.get('trigger', ''):<22} "
              f"{e.get('capture_s', 0.0):>8.3f}s  "
              f"{(e.get('summary') or '')[:60]}")
    return 0


def cmd_profile(args) -> int:
    """Open one on-demand jax.profiler capture window on a live serve
    process (/debug/profile, obs/devprof) and report the artifacts it
    wrote under the plane's profile dir — the TPU-native profileflag:

      karmadactl profile --endpoint http://127.0.0.1:8080 --seconds 2
    """
    import urllib.error
    import urllib.request

    base = args.endpoint.rstrip("/")
    url = f"{base}/debug/profile?seconds={args.seconds:g}"
    # the server holds the window open for the full capture, and
    # jax.profiler.start_trace itself costs seconds-to-tens-of-seconds
    # in a process with a large executable population: the client
    # budget is the window plus generous grace, never less
    timeout = max(30.0, args.seconds + 120.0)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            rec = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            rec = json.loads(e.read().decode())
        except json.JSONDecodeError:
            rec = {"ok": False, "error": str(e)}
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    if not rec.get("ok"):
        print(f"capture failed: {rec.get('error')}", file=sys.stderr)
        return 1
    print(f"captured {rec.get('seconds')}s profiler window "
          f"({rec.get('wall_s')}s wall) -> {rec.get('dir')}")
    for f in rec.get("files", []):
        print(f"  {f['path']}  {f['bytes']} bytes")
    print(f"total {rec.get('total_bytes')} bytes; load with "
          "`tensorboard --logdir` on the directory above")
    return 0


def cmd_trace(args) -> int:
    """Fetch flight-recorder traces from a serve process's observability
    endpoint (`serve --metrics-port ... --trace-buffer N`) and render them:
    a summary table without arguments, a text waterfall for one trace id.
    Rendering happens client-side (karmada_tpu/obs/export) so the server
    ships plain JSON."""
    import urllib.error
    import urllib.request

    from karmada_tpu.obs import export

    base = args.endpoint.rstrip("/")
    path = "/debug/traces/slow" if args.slow else "/debug/traces"
    if args.trace_id:
        path = f"/debug/traces/{args.trace_id}?format=json"
    try:
        with urllib.request.urlopen(base + path, timeout=10) as r:
            payload = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        print(f"server error ({e.code}): {e.read().decode()[:200]}",
              file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach {base}: {e.reason}", file=sys.stderr)
        return 1
    if args.trace_id:
        print(export.render_waterfall(payload))
        return 0
    if not payload.get("enabled", False):
        print("tracing is disabled on the server "
              "(serve --trace-buffer N to arm it)", file=sys.stderr)
        return 1
    rows = [
        [s["trace_id"], s["root"], str(s["spans"]),
         f"{s['duration_ms']:.2f}", str(s["cancelled"]).lower()]
        for s in payload.get("summaries", [])
    ]
    _print_table(rows or [["-"] * 5],
                 ["TRACE", "ROOT", "SPANS", "DURATION_MS", "CANCELLED"])
    if payload.get("dropped"):
        print(f"({payload['dropped']} older traces dropped from the ring)")
    return 0


# -- remote mode (--server): the query plane over HTTP ------------------------
# Reference: karmadactl talks to the aggregated apiserver by URL; here the
# data-path verbs (get / logs / exec / top / apply / delete) target a plane
# served by `karmadactl serve --api-port` (karmada_tpu/search/httpapi.py).


def _http_json(server: str, method: str, path: str, body=None, params=None):
    """One JSON request to the served query plane.  Returns (code, payload)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    url = server.rstrip("/") + path
    if params:
        filtered = {k: v for k, v in params.items() if v not in (None, "")}
        if filtered:
            url += "?" + urllib.parse.urlencode(filtered)
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"null")
        except json.JSONDecodeError:
            payload = {"error": str(e)}
        return e.code, payload
    except urllib.error.URLError as e:
        print(f"cannot reach {server}: {e.reason}", file=sys.stderr)
        raise SystemExit(1)


def _remote_fail(code, payload) -> int:
    msg = payload.get("error", payload) if isinstance(payload, dict) else payload
    print(f"server error ({code}): {msg}", file=sys.stderr)
    return 1


def cmd_get_remote(args) -> int:
    if args.kind == "pods":
        args.kind = "Pod"
    if getattr(args, "api_version", "") and (
            args.cluster or args.output != "json"):
        # the proxy/table branches have no versioned encoding; erroring
        # beats silently printing the wrong schema
        print("--api-version requires -o json and a control-plane read "
              "(no --cluster)", file=sys.stderr)
        return 1
    if args.cluster:
        if args.kind == "Pod":
            code, pods = _http_json(
                args.server, "GET", f"/clusters/{args.cluster}/proxy/pods",
                params={"namespace": args.namespace})
            if code != 200:
                return _remote_fail(code, pods)
            pods = [p for p in pods if not args.name or p["name"] == args.name]
            if args.output == "json":
                for p in pods:
                    print(json.dumps(p))
                return 0
            _print_table(
                [[p["name"], p["namespace"], p["owner"],
                  "True" if p["ready"] else "False"] for p in pods]
                or [["-", "-", "-", "-"]],
                ["NAME", "NAMESPACE", "OWNER", "READY"])
            return 0
        path = (f"/clusters/{args.cluster}/proxy/{args.kind}"
                + (f"/{args.namespace}/{args.name}" if args.name else ""))
        code, out = _http_json(args.server, "GET", path,
                               params={"namespace": args.namespace})
        if code != 200:
            return _remote_fail(code, out)
        manifests = out if isinstance(out, list) else [out]
        if args.output == "json":
            for m in manifests:
                print(json.dumps(m))
            return 0
        from karmada_tpu.models.unstructured import Unstructured
        from karmada_tpu.printers import render, table_for

        objs = [Unstructured.from_manifest(m) for m in manifests]
        headers, rows = table_for(args.kind, objs)
        print(render(headers, rows))
        return 0
    if args.output == "json" or args.name:
        path = (f"/api/{args.kind}/{args.namespace}/{args.name}"
                if args.name else f"/api/{args.kind}")
        params = {"namespace": args.namespace}
        if getattr(args, "api_version", ""):
            params["version"] = args.api_version
        code, out = _http_json(args.server, "GET", path, params=params)
        if code != 200:
            return _remote_fail(code, out)
        for m in (out if isinstance(out, list) else [out]):
            print(json.dumps(m, default=str))
        return 0
    # table view rendered server-side (typed kinds need the live objects)
    code, out = _http_json(args.server, "GET", f"/api-table/{args.kind}",
                           params={"namespace": args.namespace})
    if code != 200:
        return _remote_fail(code, out)
    _print_table(out["rows"] or [["-"] * len(out["headers"])], out["headers"])
    return 0


def cmd_logs_remote(args) -> int:
    code, out = _http_json(
        args.server, "GET",
        f"/clusters/{args.cluster}/proxy/logs/"
        f"{args.namespace or 'default'}/{args.pod}",
        params={"tail": args.tail})
    if code != 200:
        return _remote_fail(code, out)
    for line in out["lines"]:
        print(line)
    return 0


def cmd_exec_remote(args) -> int:
    code, out = _http_json(
        args.server, "POST",
        f"/clusters/{args.cluster}/proxy/exec/"
        f"{args.namespace or 'default'}/{args.pod}",
        body={"command": args.cmd})
    if code != 200:
        return _remote_fail(code, out)
    if out.get("output"):
        print(out["output"])
    return int(out.get("rc", 0))


def cmd_apply_remote(args) -> int:
    """karmadactl --server apply -f: manifests POST to the served plane's
    /api/apply (typed codec + admission run server-side)."""
    import yaml

    with open(args.filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    rc = 0
    for manifest in docs:
        code, out = _http_json(args.server, "POST", "/api/apply",
                               body=manifest)
        if code != 200:
            _remote_fail(code, out)
            rc = 1
            continue
        name = (manifest.get("metadata") or {}).get("name", "?")
        print(f"{manifest.get('kind')}/{name} applied")
    return rc


def cmd_delete_remote(args) -> int:
    path = (f"/api/{args.kind}/{args.namespace}/{args.name}"
            if args.namespace else f"/api/{args.kind}/{args.name}")
    code, out = _http_json(args.server, "DELETE", path)
    if code != 200:
        return _remote_fail(code, out)
    print(f"{args.kind}/{args.name} deleted")
    return 0


def cmd_top_remote(args) -> int:
    if args.what == "nodes":
        code, out = _http_json(args.server, "GET", "/metrics-adapter/nodes")
        if code != 200:
            return _remote_fail(code, out)
        _print_table(_node_rows(out) or [["-"] * 5],
                     ["CLUSTER", "NODE", "CPU", "CPU%", "PODS"])
        return 0
    if args.what == "pods":
        code, out = _http_json(
            args.server, "GET",
            f"/metrics-adapter/pods/Deployment/"
            f"{args.namespace or 'default'}/{args.name or ''}")
        if code != 200:
            return _remote_fail(code, out)
        rows = []
        for pm in out:
            usage = pm.get("usage", {})
            rows.append([
                pm.get("cluster", "-"), pm.get("name", "-"),
                f"{usage.get('cpu', 0)}m",
                f"{usage.get('memory', 0) // 1000 // (1 << 20)}Mi",
            ])
        _print_table(rows or [["-", "-", "-", "-"]],
                     ["CLUSTER", "POD", "CPU", "MEMORY"])
        return 0
    code, out = _http_json(args.server, "GET", "/api-table/Cluster")
    if code != 200:
        return _remote_fail(code, out)
    _print_table(out["rows"] or [["-"] * len(out["headers"])], out["headers"])
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="karmadactl", description=__doc__)
    p.add_argument("--dir", default=None, help="control plane directory")
    p.add_argument("--server", default=None,
                   help="URL of a served query plane (karmadactl serve "
                        "--api-port); get/logs/exec/top run over HTTP "
                        "instead of opening --dir")
    sub = p.add_subparsers(dest="command", required=True)

    sub.add_parser("init")
    sub.add_parser("version")

    j = sub.add_parser("join")
    j.add_argument("name")
    j.add_argument("--cpu", type=int, default=64, help="cores")
    j.add_argument("--memory-gi", type=int, default=256)
    j.add_argument("--pods", type=int, default=110)
    j.add_argument("--region", default="")
    j.add_argument("--zone", default="")
    j.add_argument("--provider", default="")
    j.add_argument("--sync-mode", choices=["Push", "Pull"], default="Push")

    u = sub.add_parser("unjoin")
    u.add_argument("name")

    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("name", nargs="?")
    g.add_argument("-n", "--namespace", default="")
    g.add_argument("--cluster", default="", help="read through the cluster proxy")
    g.add_argument("-o", "--output", choices=["table", "json"], default="table")
    g.add_argument("--api-version", default="",
                   help="with --server -o json: serve the objects at this "
                        "registered API version (multi-version read, e.g. "
                        "work.karmada.io/v1alpha2 for Work)")

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)

    cr = sub.add_parser("create")
    cr.add_argument("-f", "--filename", required=True)

    ed = sub.add_parser("edit")
    ed.add_argument("kind")
    ed.add_argument("name")
    ed.add_argument("-n", "--namespace", default="")

    lg = sub.add_parser("logs")
    lg.add_argument("pod")
    lg.add_argument("--cluster", required=True)
    lg.add_argument("-n", "--namespace", default="default")
    lg.add_argument("--tail", type=int, default=None)

    xc = sub.add_parser("exec")
    xc.add_argument("pod")
    xc.add_argument("--cluster", required=True)
    xc.add_argument("-n", "--namespace", default="default")
    xc.add_argument("cmd", nargs="*",
                    help="command to run (flags go after --)")

    at = sub.add_parser("attach")
    at.add_argument("pod")
    at.add_argument("--cluster", required=True)
    at.add_argument("-n", "--namespace", default="default")

    pr = sub.add_parser("promote")
    pr.add_argument("kind")
    pr.add_argument("name")
    pr.add_argument("-n", "--namespace", default="")
    pr.add_argument("--cluster", required=True)

    for cname in ("cordon", "uncordon"):
        c = sub.add_parser(cname)
        c.add_argument("name")

    t = sub.add_parser("top")
    t.add_argument("what", nargs="?", default="clusters",
                   choices=["clusters", "pods", "nodes"])
    t.add_argument("name", nargs="?", help="workload name (pods)")
    t.add_argument("-n", "--namespace", default="")
    t.add_argument("--endpoint", default="",
                   help="observability endpoint URL of a serve process "
                        "armed with --telemetry: render the live plane "
                        "dashboard (queue depths, cycle budget breakdown, "
                        "h2d counter, shed/eviction rates, SLO burn) from "
                        "/debug/timeseries + /debug/slo instead of the "
                        "cluster table")

    i = sub.add_parser("interpret")
    i.add_argument("-f", "--filename", required=True)
    i.add_argument("--operation", default="InterpretReplica")
    i.add_argument("--customization", default="")
    i.add_argument("--replicas", type=int, default=1)

    d = sub.add_parser("describe")
    d.add_argument("kind",
                   help="an API Kind (local mode), or namespace/binding "
                        "with --endpoint (live timeline view)")
    d.add_argument("name", nargs="?", default="")
    d.add_argument("-n", "--namespace", default="")
    d.add_argument("--cluster", default="")
    d.add_argument("--endpoint", default="",
                   help="observability endpoint URL of a serve process: "
                        "render the kube-style live view (status + "
                        "lifecycle-ledger event timeline + last explain "
                        "verdict) from /debug/events/{ns}/{name}")

    evs = sub.add_parser("events")
    evs.add_argument("target", nargs="?", default="",
                     help="namespace/name: render that binding's event "
                          "timeline (omit to list recent events)")
    evs.add_argument("--endpoint", required=True,
                     help="observability endpoint URL of a live serve "
                          "process (serve --metrics-port PORT)")
    evs.add_argument("--watch", action="store_true",
                     help="follow: poll /debug/events?since=ID and print "
                          "new events until interrupted")
    evs.add_argument("--interval", type=float, default=2.0,
                     help="--watch poll interval seconds")
    evs.add_argument("--limit", type=int, default=64, metavar="N",
                     help="events per fetch (the recent-ring slice)")

    dl = sub.add_parser("delete")
    dl.add_argument("kind")
    dl.add_argument("name")
    dl.add_argument("-n", "--namespace", default="")

    for ename in ("label", "annotate"):
        e = sub.add_parser(ename)
        e.add_argument("kind")
        e.add_argument("name")
        e.add_argument("pairs", nargs="+", help="key=value to set, key- to remove")
        e.add_argument("-n", "--namespace", default="")

    tn = sub.add_parser("taint")
    tn.add_argument("name", help="cluster name")
    tn.add_argument("taints", nargs="+", help="key[=value]:Effect or key-")

    sub.add_parser("api-resources")

    trc = sub.add_parser("trace")
    trc.add_argument("trace_id", nargs="?",
                     help="render this trace's waterfall (omit to list)")
    trc.add_argument("--endpoint", required=True,
                     help="observability endpoint URL of a serve process "
                          "(printed by `serve --metrics-port ... "
                          "--trace-buffer N`)")
    trc.add_argument("--slow", action="store_true",
                     help="list the always-retained slowest cycles instead "
                          "of the recent ring")

    lgen = sub.add_parser("loadgen")
    lgen.add_argument("scenario", nargs="?", default="",
                      help="scenario name to rehearse in compressed time "
                           "(omit to list the catalog)")
    lgen.add_argument("--endpoint", default="",
                      help="observability endpoint URL of a serve process "
                           "running `serve --loadgen`; renders the live "
                           "/debug/load state instead of rehearsing")
    lgen.add_argument("--seed", type=int, default=0,
                      help="deterministic arrival-process seed")
    lgen.add_argument("--pretty", action="store_true",
                      help="indent the SOAK payload JSON")

    vt = sub.add_parser("vet")
    vt.add_argument("paths", nargs="*",
                    help="files/directories to analyze (default: the "
                         "installed karmada_tpu package)")
    vt.add_argument("--format", choices=["text", "json", "github"],
                    default="text",
                    help="json: machine-readable findings/waivers summary "
                         "(rule, file:line, waiver count); github: "
                         "::error file=...,line=... annotation lines for "
                         "Actions; exit code is non-zero on any finding "
                         "either way")
    vt.add_argument("--rules", default="",
                    help="comma-separated finding-rule filter (e.g. "
                         "trace-branch,dtype-contract); all passes still "
                         "run and waivers are always enumerated in full — "
                         "only reported FINDINGS are filtered")

    ex = sub.add_parser("explain")
    ex.add_argument("kind", nargs="?", default="",
                    help="an API Kind (field docs), or namespace/binding "
                         "with --endpoint (placement decision)")
    ex.add_argument("--endpoint", default="",
                    help="observability endpoint URL of a serve process "
                         "armed with --explain; renders the binding's "
                         "placement verdict table (omit the binding "
                         "argument to list recent decisions)")

    to = sub.add_parser("token")
    to.add_argument("action", choices=["create", "list"])

    rg = sub.add_parser("register")
    rg.add_argument("name")
    rg.add_argument("--token", required=True)
    rg.add_argument("--cpu", type=int, default=64)
    rg.add_argument("--memory-gi", type=int, default=256)
    rg.add_argument("--pods", type=int, default=110)
    rg.add_argument("--region", default="")

    ur = sub.add_parser("unregister")
    ur.add_argument("name")

    ad = sub.add_parser("addons")
    ad.add_argument("action", choices=["enable", "disable"])
    ad.add_argument("addon", choices=[
        "resource-quota-estimate", "multicluster-service",
        "quota-enforcement", "stateful-failover", "priority-queue",
    ])

    pt = sub.add_parser("patch")
    pt.add_argument("kind")
    pt.add_argument("name")
    pt.add_argument("-n", "--namespace", default="")
    pt.add_argument("-p", "--patch", required=True, help="JSON merge patch")

    sub.add_parser("completion")
    sub.add_parser("options")

    di = sub.add_parser("deinit")
    di.add_argument("--force", action="store_true")

    tk = sub.add_parser("tick")
    tk.add_argument("--backend", default="serial")
    tk.add_argument("--waves", type=int, default=8)
    tk.add_argument("--controllers", default=None,
                    help="enable/disable list (see serve --controllers)")

    sv = sub.add_parser("serve")
    sv.add_argument("--backend", choices=["serial", "native", "device"],
                    default="device")
    sv.add_argument("--feature-gates", default="",
                    help="A=true,B=false (pkg/features registry names)")
    sv.add_argument("--controllers", default=None,
                    help="enable/disable list: '*' all, '-name' disables, "
                         "a bare allowlist runs only those (reference "
                         "--controllers flag); persisted on the plane, "
                         "omit to keep the last choice")
    sv.add_argument("--sync-period", type=float, default=0.5,
                    help="periodic resync interval seconds")
    sv.add_argument("--checkpoint-period", type=float, default=30.0,
                    help="WAL compaction interval seconds")
    sv.add_argument("--waves", type=int, default=8,
                    help="capacity-contention waves per solver chunk "
                         "(batch size = strict one-at-a-time semantics)")
    sv.add_argument("--pipeline-chunk", type=int, default=1024,
                    help="pipelined chunk executor chunk size: scheduling "
                         "cycles larger than this split into overlapped "
                         "chunks with consumed-capacity carry "
                         "(scheduler/pipeline.py)")
    sv.add_argument("--mesh", default="off",
                    help="solver device mesh shape BxC (bindings x "
                         "clusters axes, e.g. 2x4), 'auto' to factor the "
                         "live device count, or 'off' (default): shards "
                         "every compact solve over the mesh "
                         "(ops/meshing.py); a single-device environment "
                         "silently falls back to the unsharded dispatch")
    sv.add_argument("--aot-cache", default="on", metavar="DIR|off",
                    help="AOT executable plane (ops/aotcache, on by "
                         "default): persist compiled solver executables "
                         "across processes (cache dir keyed by platform, "
                         "host CPU features, jax version and mesh "
                         "topology; DIR overrides the keyed default) and "
                         "AOT pre-compile every pow2 batch shape x jit "
                         "variant this configuration can dispatch on a "
                         "background thread at startup, so a fresh serve "
                         "plane skips the ~100s first-cycle compile "
                         "warmup.  'off' disables both (legacy cold "
                         "start)")
    sv.add_argument("--metrics-port", type=int, default=-1,
                    help="serve /metrics,/healthz,/readyz,/debug/state on "
                         "127.0.0.1:PORT (0 = ephemeral, -1 = disabled)")
    sv.add_argument("--explain", nargs="?", const="1", default="",
                    metavar="RATE",
                    help="arm the explain plane: sampled scheduling "
                         "cycles run the solver's explain jit variant "
                         "and record per-binding placement verdicts "
                         "(filter bitmask, score/capacity breakdown, "
                         "dominant unschedulable reason) in a bounded "
                         "ring at /debug/explain, rendered by "
                         "`karmadactl explain ns/binding --endpoint URL`."
                         "  RATE in (0, 1] samples that fraction of "
                         "cycles (bare --explain = every cycle); the "
                         "disarmed path compiles byte-identical to "
                         "--explain off")
    sv.add_argument("--telemetry", nargs="?", const="512", default="",
                    metavar="RING",
                    help="arm the telemetry plane (obs/timeseries and "
                         "obs/slo): retain a bounded ring of RING metric "
                         "snapshots (default 512) sampled on the "
                         "scheduler's cycle clock, evaluate the SLO "
                         "error budgets with multi-window burn rates, "
                         "refresh per-device memory attribution every "
                         "guarded cycle, and arm the regression "
                         "watchdog against the committed baseline "
                         "envelope; read at /debug/timeseries + "
                         "/debug/slo, render with `karmadactl top "
                         "--endpoint URL`")
    sv.add_argument("--telemetry-interval", type=float, default=1.0,
                    metavar="SECONDS",
                    help="minimum spacing between telemetry ring "
                         "samples on the sampling clock (busy planes "
                         "cycle faster than the ring needs; 0 samples "
                         "every cycle)")
    sv.add_argument("--slo-deadline", type=float, default=1.0,
                    metavar="SECONDS",
                    help="the schedule_p99 objective's latency bound "
                         "(the <1s p99 north star); dwell_p99 uses "
                         "twice this bound — deadline-formed batches "
                         "dwell at the batch deadline by design")
    sv.add_argument("--incident-cooldown", type=float, default=60.0,
                    metavar="SECONDS",
                    help="incident plane (obs/incidents, armed by "
                         "default): minimum spacing between forensic "
                         "bundle captures per trigger kind; bundles "
                         "land under DIR/incidents and are indexed at "
                         "/debug/incidents (`karmadactl incidents`)")
    sv.add_argument("--no-incidents", action="store_true",
                    help="disarm the incident store (triggers become "
                         "no-ops; the per-cycle flight ring stays "
                         "armed)")
    sv.add_argument("--trace-buffer", type=int, default=0,
                    help="arm the flight recorder: retain the last N "
                         "cross-component traces (scheduler cycles, "
                         "pipeline stages, reconciles) at /debug/traces "
                         "plus the slowest cycles at /debug/traces/slow "
                         "(0 = tracing disabled, zero overhead)")
    sv.add_argument("--probe-timeout", type=float, default=240.0,
                    help="device-backend health probe budget (seconds; "
                         "matches the bench/watcher budgets — device init "
                         "over the tunnel has been observed to need "
                         "minutes); a failed probe reroutes --backend "
                         "device to the native C++ backend instead of XLA "
                         "on host CPU")
    sv.add_argument("--no-probe", action="store_true",
                    help="skip the device health probe and run --backend "
                         "device on whatever platform jax initialises "
                         "(tests / known-good hardware)")
    sv.add_argument("--device-cycle-timeout", type=float, default=300.0,
                    help="mid-serve death guard: a device solve cycle "
                         "exceeding this many seconds is abandoned and the "
                         "scheduler degrades to the fastest host backend "
                         "(0 disables; see --device-recover-cycles for "
                         "whether the degrade is permanent)")
    sv.add_argument("--device-recover-cycles", type=int, default=64,
                    metavar="N",
                    help="recoverable degrade: after N scheduling cycles "
                         "on the degraded backend, re-probe the device "
                         "path (half-open: one cycle tries it; a hang "
                         "degrades again with the cooldown doubled per "
                         "consecutive failure).  0 = legacy one-way "
                         "degrade")
    sv.add_argument("--chaos", default="",
                    metavar="SPEC",
                    help="arm the deterministic fault-injection plane "
                         "(karmada_tpu/chaos) with SPEC — "
                         "SITE:MODE[:ARG][@PROB][#COUNT], ';'-separated; "
                         "e.g. 'estimator.rpc:error@0.1;"
                         "device.cycle:hang:30#1'.  Sites: estimator.rpc, "
                         "device.dispatch, device.d2h, device.cycle, "
                         "resident.mirror, store.watch, worker.reconcile, "
                         "lease.heartbeat.  State at /debug/chaos; "
                         "disarmed cost is one list read per seam")
    sv.add_argument("--chaos-seed", type=int, default=0,
                    help="deterministic seed for --chaos probability "
                         "draws (same spec + seed + call sequence fires "
                         "the same faults)")
    sv.add_argument("--check-invariants", action="store_true",
                    help="arm the runtime invariant guards "
                         "(karmada_tpu/analysis/guards): shape/dtype/NaN "
                         "checks at solver entry and d2h boundaries; also "
                         "armable via KARMADA_CHECK_INVARIANTS=1")
    sv.add_argument("--api-port", type=int, default=-1,
                    help="serve the query plane (cluster proxy verbs, "
                         "search cache GET/LIST/WATCH, metrics adapter) "
                         "over HTTP on 127.0.0.1:PORT (0 = ephemeral, "
                         "-1 = disabled); clients use --server URL")
    sv.add_argument("--batch-window", type=int, default=4096,
                    help="max bindings drained into one batched "
                         "scheduling cycle")
    sv.add_argument("--batch-deadline", type=float, default=0.0,
                    help="deadline-vs-size batch formation: cut a cycle "
                         "when --batch-window bindings are ready OR the "
                         "oldest ready binding has waited this many "
                         "seconds; 0 (default) cuts immediately on any "
                         "ready binding")
    sv.add_argument("--admission-limit", type=int, default=0,
                    help="bounded-resident admission gate: total tracked "
                         "bindings in the scheduling queues never exceed "
                         "this; overflow sheds by priority with "
                         "karmada_scheduler_admission_total accounting "
                         "(0 = unbounded)")
    sv.add_argument("--loadgen", default="",
                    metavar="SCENARIO",
                    help="drive THIS plane with real-time synthetic "
                         "traffic from the named loadgen scenario "
                         "(karmadactl loadgen lists the catalog); live "
                         "state at /debug/load")
    sv.add_argument("--loadgen-rate", type=float, default=20.0,
                    help="mean arrival rate for --loadgen, "
                         "arrivals/second")
    sv.add_argument("--loadgen-seed", type=int, default=0,
                    help="deterministic arrival-process seed for "
                         "--loadgen")
    sv.add_argument("--resident", action="store_true",
                    help="arm the resident-state plane "
                         "(karmada_tpu/resident, device backend only): "
                         "cluster-side solver tensors and their device "
                         "mirrors stay resident BETWEEN scheduling "
                         "cycles, advanced by coalesced watch-event "
                         "deltas, and per-binding encoded rows are "
                         "cached so a steady-state cycle re-encodes only "
                         "churned bindings; state at /debug/resident "
                         "(karmadactl resident --endpoint URL)")
    sv.add_argument("--resident-fused", action="store_true",
                    help="fused whole-cycle-on-device steady state "
                         "(requires --resident): the binding-row slot "
                         "store mirrors on device and each cycle's batch "
                         "GATHERS there (ops/resident_gather) — zero "
                         "per-cycle h2d of binding-axis fields; host "
                         "re-encode stays the parity control/fallback")
    sv.add_argument("--resident-audit", type=int, default=64,
                    metavar="N",
                    help="resident parity-audit cadence: every Nth cycle "
                         "re-encodes from scratch and compares bit-exact "
                         "against the resident tensors (mismatch = "
                         "metric + forced rebuild; 0 disables)")
    sv.add_argument("--shortlist", nargs="?", const="64", default="",
                    metavar="K",
                    help="arm the hierarchical two-tier solve "
                         "(ops/shortlist): chunks above the cell "
                         "threshold run a cheap device-side candidate "
                         "kernel (top-K cluster lanes per binding, "
                         "default K=64) and dispatch the dense solver "
                         "over the candidate union — B*K cells instead "
                         "of B*C, bit-exact when every binding's "
                         "eligible set fits K; rows whose eligible set "
                         "exceeds the widen ceiling are truncated out "
                         "and re-solved per-binding at full width "
                         "(truncation-with-recall), so one huge row no "
                         "longer drags its whole chunk dense; remaining "
                         "fallbacks stay loud "
                         "(karmada_shortlist_fallbacks_total, row-level "
                         "karmada_shortlist_fallback_rows_total); "
                         "composes with --resident-fused via a device "
                         "slot-store sub-gather")
    sv.add_argument("--rebalance", nargs="?", const="30", default=None,
                    metavar="INTERVAL",
                    help="arm the rebalance plane (karmada_tpu/rebalance): "
                         "every INTERVAL seconds (default 30) detect "
                         "per-cluster overcommit/spread divergence, "
                         "gracefully evict victims under the shared "
                         "pacing budget, and re-place them through the "
                         "scheduler queue with origin=rebalance; state "
                         "at /debug/rebalance (karmadactl rebalance "
                         "--endpoint URL)")
    sv.add_argument("--facade", nargs="?", const="127.0.0.1:0", default="",
                    metavar="ADDR",
                    help="arm the facade plane (karmada_tpu/facade): "
                         "serve SelectClusters/AssignReplicas/WhatIf "
                         "over the estimator wire tier at ADDR (default "
                         "127.0.0.1:0 = ephemeral port), coalescing "
                         "concurrent callers into one detached solve "
                         "per batch; what-if capacity queries at "
                         "/whatif, counters at /debug/facade "
                         "(karmadactl whatif / karmadactl estimate)")

    rb = sub.add_parser("rebalance")
    rb.add_argument("--endpoint", required=True,
                    help="observability endpoint URL of a live serve "
                         "process (serve --metrics-port PORT)")

    wi = sub.add_parser("whatif")
    wi.add_argument("--endpoint", required=True,
                    help="observability endpoint URL of a live serve "
                         "process with the facade plane armed "
                         "(serve --metrics-port PORT --facade)")
    wi.add_argument("--query", default="placement",
                    choices=["placement", "cluster-loss", "headroom"],
                    help="placement: where would N new replicas land; "
                         "cluster-loss: which single cluster loss "
                         "strands the most replicas; headroom: largest "
                         "replica count that still fully schedules")
    wi.add_argument("--replicas", type=int, default=1,
                    help="replica count (placement) / search seed "
                         "(headroom)")
    wi.add_argument("--cpu", default="",
                    help="per-replica cpu request, e.g. 500m")
    wi.add_argument("--memory", default="",
                    help="per-replica memory request, e.g. 1Gi")
    wi.add_argument("--cluster", default="",
                    help="cluster-loss: restrict to one named candidate")
    wi.add_argument("--duplicated", action="store_true",
                    help="Duplicated scheduling (full replica count on "
                         "every eligible cluster) instead of Divided")
    wi.add_argument("--limit", type=int, default=512,
                    help="cluster-loss: per-cluster re-solve cap")
    wi.add_argument("--format", choices=["text", "json"], default="text")

    es = sub.add_parser("estimate")
    es.add_argument("--facade-addr", required=True, metavar="HOST:PORT",
                    help="wire address of a served facade plane "
                         "(serve --facade prints it)")
    es.add_argument("--replicas", type=int, default=1)
    es.add_argument("--cpu", default="",
                    help="per-replica cpu request, e.g. 500m")
    es.add_argument("--memory", default="",
                    help="per-replica memory request, e.g. 1Gi")
    es.add_argument("--namespace", default="default")
    es.add_argument("--name", default="estimate",
                    help="binding name stamped on the facade ledger "
                         "events for this call")
    es.add_argument("--clusters", default="",
                    help="comma-separated cluster-affinity restriction")
    es.add_argument("--duplicated", action="store_true",
                    help="Duplicated scheduling instead of Divided")
    es.add_argument("--format", choices=["text", "json"], default="text")

    rs = sub.add_parser("resident")
    rs.add_argument("--endpoint", required=True,
                    help="observability endpoint URL of a live serve "
                         "process (serve --metrics-port PORT)")
    rs.add_argument("--recent", type=int, default=0, metavar="N",
                    help="also list the last N per-cycle hit/miss records")

    inc = sub.add_parser("incidents")
    inc.add_argument("id", nargs="?", default="",
                     help="incident ID: dump that one forensic bundle as "
                          "JSON (omit to list the bundle index)")
    inc.add_argument("--endpoint", required=True,
                     help="observability endpoint URL of a live serve "
                          "process (serve --metrics-port PORT)")

    pf = sub.add_parser("profile")
    pf.add_argument("--endpoint", required=True,
                    help="observability endpoint URL of a live serve "
                         "process (serve --metrics-port PORT)")
    pf.add_argument("--seconds", type=float, default=2.0,
                    help="capture-window length (server-capped at 60s); "
                         "artifacts land under the plane's profiles/ dir")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        print(VERSION)
        return 0
    try:
        return _dispatch(args)
    except BrokenPipeError:
        # piped into head/less that exited — the unix-polite outcome
        try:
            sys.stdout.close()
        # vet: ignore[exception-hygiene] double BrokenPipe on close; exiting anyway
        except Exception:  # noqa: BLE001
            pass
        return 0


COMMANDS = {
    "init": cmd_init,
    "join": cmd_join,
    "unjoin": cmd_unjoin,
    "get": cmd_get,
    "apply": cmd_apply,
    "create": cmd_create,
    "edit": cmd_edit,
    "logs": cmd_logs,
    "exec": cmd_exec,
    "attach": cmd_attach,
    "promote": cmd_promote,
    "cordon": cmd_cordon,
    "uncordon": lambda a: cmd_cordon(a, uncordon=True),
    "top": cmd_top,
    "interpret": cmd_interpret,
    "describe": cmd_describe,
    "delete": cmd_delete,
    "label": lambda a: cmd_meta_edit(a, "labels"),
    "annotate": lambda a: cmd_meta_edit(a, "annotations"),
    "taint": cmd_taint,
    "api-resources": cmd_api_resources,
    "explain": cmd_explain,
    "token": cmd_token,
    "register": cmd_register,
    "unregister": cmd_unregister,
    "addons": cmd_addons,
    "deinit": cmd_deinit,
    "patch": cmd_patch,
    "completion": cmd_completion,
    "options": cmd_options,
    "tick": cmd_tick,
    "serve": cmd_serve,
    "trace": cmd_trace,
    "events": cmd_events,
    "vet": cmd_vet,
    "loadgen": cmd_loadgen,
    "rebalance": cmd_rebalance,
    "whatif": cmd_whatif,
    "estimate": cmd_estimate,
    "resident": cmd_resident,
    "incidents": cmd_incidents,
    "profile": cmd_profile,
}


REMOTE_COMMANDS = {
    "get": "cmd_get_remote",
    "logs": "cmd_logs_remote",
    "exec": "cmd_exec_remote",
    "top": "cmd_top_remote",
    "apply": "cmd_apply_remote",
    "delete": "cmd_delete_remote",
    "api-resources": "cmd_api_resources_remote",
}


def cmd_api_resources_remote(args) -> int:
    """api-resources over --server: the /apis discovery root, rendered in
    the same VERSIONS format as the local command (GROUP/TYPE are local
    implementation detail the wire payload does not carry)."""
    code, out = _http_json(args.server, "GET", "/apis")
    if code != 200:
        return _remote_fail(code, out)
    rows = [[kind, _format_versions(info["storageVersion"],
                                    info["servedVersions"])]
            for kind, info in sorted(out.items())]
    _print_table(rows, ["KIND", "VERSIONS"])
    return 0


def _dispatch(args) -> int:
    if args.command == "trace":
        # talks to a live serve process over HTTP; needs neither --dir
        # (no plane is opened) nor --server (different endpoint)
        return cmd_trace(args)
    if args.command == "vet":
        # pure source analysis: no plane, no server
        return cmd_vet(args)
    if args.command == "loadgen":
        # catalog/rehearsal need no plane; --endpoint talks to a live
        # serve process over HTTP
        return cmd_loadgen(args)
    if args.command == "resident":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_resident(args)
    if args.command == "events":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_events(args)
    if args.command == "describe" and getattr(args, "endpoint", ""):
        # live timeline view over HTTP; no plane is opened
        return cmd_describe(args)
    if args.command == "incidents":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_incidents(args)
    if args.command == "profile":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_profile(args)
    if args.command == "top" and getattr(args, "endpoint", ""):
        # live telemetry dashboard over HTTP; no plane is opened
        return cmd_top(args)
    if args.command == "rebalance":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_rebalance(args)
    if args.command == "whatif":
        # talks to a live serve process over HTTP; no plane is opened
        return cmd_whatif(args)
    if args.command == "estimate":
        # talks to a served facade plane over the wire tier; no plane
        # is opened
        return cmd_estimate(args)
    if args.command == "explain":
        # kind mode reads only the model registry; binding mode talks to
        # a live serve process over HTTP — neither opens a plane
        return cmd_explain(args)
    if getattr(args, "server", None):
        handler = REMOTE_COMMANDS.get(args.command)
        if handler is None:
            print(f"{args.command} is not available over --server "
                  "(open the plane with --dir)", file=sys.stderr)
            return 1
        return globals()[handler](args)
    if args.dir is None:
        print("--dir is required (or --server for "
              f"{'/'.join(sorted(REMOTE_COMMANDS))})", file=sys.stderr)
        return 1
    return COMMANDS[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
