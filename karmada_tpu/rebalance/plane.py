"""RebalancePlane: the periodic drain-and-re-place cycle on the solver.

Closes ROADMAP item 5's loop: the reference control plane rebalances
placements with pkg/descheduler on the SAME solver the scheduler runs;
here the serve path gets the equivalent — every `interval_s` (on the
scheduler queue's clock, so compressed virtual-time soaks drive it
deterministically) the plane

  detect    scores per-cluster overcommit and spread divergence with the
            jitted kernel (ops/rebalance_detect) over [C] tensors
            assembled from the live fleet: committed replicas per
            cluster from the store's schedule results, capacity from the
            cluster ResourceSummaries;
  drain     picks victims on each over-threshold cluster (lowest
            schedule priority first, biggest per-cluster allotment
            first) and evicts them through the EXISTING graceful-
            eviction chain (controllers/failover.evict_cluster,
            producer="rebalance") — the replica leaves spec.clusters but
            its Work survives until the replacement reports healthy, so
            serving capacity never dips.  Every eviction draws a token
            from the shared pacing budget (rebalance/pacing.py), the
            same ledger controllers/descheduler.py draws from, so the
            two evictors cannot stampede a cluster in one interval;
  re-place  the eviction's generation bump re-enters the binding through
            the normal push path, and the plane additionally promotes it
            with origin="rebalance" (scheduler.promote) so its queue
            dwell is attributed to the rebalance plane and the next
            cycle re-solves it through the pipelined executor (carry
            chain pricing the remainder);
  audit     asserts the conservation invariant: no binding with an
            in-flight rebalance eviction may serve fewer than its
            desired replicas (spec.clusters + pending eviction tasks >=
            spec.replicas).  Violations are counted
            (karmada_rebalance_conservation_violations_total) and the
            chaos safety auditor (chaos/audit.py) fails a soak on them.

Chaos seam `rebalance.plan` (skip / raise) fires at the top of the
cycle; a raising cycle is contained (counted, never propagated into the
runtime's periodic loop).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karmada_tpu import chaos as chaos_mod
from karmada_tpu.utils.locks import VetLock
from karmada_tpu import obs
from karmada_tpu.controllers.failover import evict_cluster
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.policy import REPLICA_SCHEDULING_DIVIDED
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.ops import rebalance_detect
from karmada_tpu.rebalance.pacing import EvictionBudget
from karmada_tpu.store.store import NotFoundError
from karmada_tpu.utils import events as ev
from karmada_tpu.utils.metrics import REGISTRY

PRODUCER = "rebalance"

CYCLES = REGISTRY.counter(
    "karmada_rebalance_cycles_total",
    "Rebalance detect cycles run (drains or not)",
)

EVICTIONS = REGISTRY.counter(
    "karmada_rebalance_evictions_total",
    "Graceful evictions initiated by the rebalance plane, by cluster "
    "drained from",
    ("cluster",),
)

CYCLE_FAULTS = REGISTRY.counter(
    "karmada_rebalance_cycle_faults_total",
    "Rebalance cycles skipped or aborted by a fault (chaos rebalance.plan "
    "seam included), by kind — the cycle is contained, the plane keeps "
    "running",
    ("kind",),
)

CONSERVATION_VIOLATIONS = REGISTRY.counter(
    "karmada_rebalance_conservation_violations_total",
    "Bindings observed serving fewer than their desired replicas while a "
    "rebalance eviction was in flight (the invariant the drain chain "
    "must never break)",
)

OVERCOMMIT = REGISTRY.gauge(
    "karmada_rebalance_overcommit_milli",
    "Last detect cycle's committed/capacity ratio x1000 per cluster",
    ("cluster",),
)

DRAIN_NEED = REGISTRY.gauge(
    "karmada_rebalance_drain_need",
    "Replicas the last detect cycle wants shed per cluster to get back "
    "inside the thresholds",
    ("cluster",),
)

CONVERGED = REGISTRY.gauge(
    "karmada_rebalance_converged",
    "1 while the last detect cycle found no cluster needing a drain",
)


@dataclass(frozen=True)
class RebalanceConfig:
    """Thresholds + pacing of one rebalance plane.  All milli ratios are
    ints so the jitted detect kernel stays float-free (bit-deterministic
    drain plans)."""

    interval_s: float = 30.0
    # drain a cluster above committed > threshold x capacity
    overcommit_threshold_milli: int = 1000
    # drain a cluster whose committed share exceeds its capacity share
    # by more than this (x1000).  0 (the default) keeps divergence
    # REPORT-ONLY: spread draining can ping-pong when re-placement
    # keeps favoring one most-available cluster, so an operator arms it
    # deliberately, sized against the pacing budget
    spread_tolerance_milli: int = 0
    # pacing: hard cap per cycle across the fleet, and the shared
    # per-cluster-per-window budget (rebalance/pacing.py)
    max_evictions_per_cycle: int = 32
    budget_per_cluster: int = 8
    budget_interval_s: float = 60.0


class RebalancePlane:
    """One per-scheduler rebalance loop; registered as a runtime periodic
    hook (maybe_run) and gated on the scheduler queue's clock."""

    def __init__(self, store, scheduler, cfg: Optional[RebalanceConfig] = None,
                 budget: Optional[EvictionBudget] = None,
                 clock=None) -> None:
        self.store = store
        self.scheduler = scheduler
        self.cfg = cfg if cfg is not None else RebalanceConfig()
        self.clock = clock if clock is not None else scheduler.queue.now
        self.budget = budget if budget is not None else EvictionBudget(
            per_cluster=self.cfg.budget_per_cluster,
            interval_s=self.cfg.budget_interval_s, clock=self.clock)
        self._lock = VetLock("rebalance.plane")
        # guarded-by: _lock — last-cycle snapshot + lifetime totals
        # (readers: /debug/rebalance, the soak report; writer: the one
        # periodic hook)
        self._last: Dict[str, object] = {}
        self._peak_over: Dict[str, int] = {}
        self._cycles = 0
        self._evictions = 0
        self._violations = 0
        self._violation_samples: List[dict] = []
        self._last_run = float("-inf")

    # -- periodic entry ------------------------------------------------------
    def maybe_run(self) -> None:
        """The runtime periodic hook: run a cycle when the interval (on
        the scheduler's clock) has elapsed.  A raising cycle is contained
        and counted — the plane must never take the periodic loop down."""
        now = self.clock()
        if now - self._last_run < self.cfg.interval_s:
            return
        self._last_run = now
        try:
            self.run_cycle()
        # vet: ignore[exception-hygiene] contained + counted; the periodic loop must survive
        except Exception as e:  # noqa: BLE001 — cycle fault containment
            CYCLE_FAULTS.inc(kind=type(e).__name__)
            import traceback

            traceback.print_exc()

    # -- one cycle -----------------------------------------------------------
    def run_cycle(self) -> dict:
        """detect -> drain -> audit; returns the cycle snapshot."""
        if chaos_mod.armed():
            f = chaos_mod.fire(chaos_mod.SITE_REBALANCE_PLAN)
            if f is not None:
                if f.mode == "skip":
                    # the planned cycle is dropped whole; the NEXT interval
                    # re-detects from fresh state, nothing is lost
                    CYCLE_FAULTS.inc(kind="chaos_skip")
                    return {"skipped": "chaos"}
                raise RuntimeError("chaos: rebalance.plan raise")
        with obs.TRACER.span(obs.SPAN_REBALANCE_CYCLE) as cspan:
            clusters = list(self.store.list(Cluster.KIND))
            bindings = list(self.store.list(ResourceBinding.KIND))
            with obs.TRACER.span(obs.SPAN_REBALANCE_DETECT,
                                 clusters=len(clusters),
                                 bindings=len(bindings)):
                names, committed, capacity, valid, by_cluster = (
                    self._assemble(clusters, bindings))
                if names:
                    # tolerance 0 = spread is report-only: div_milli is
                    # bounded by +/-1000, so a gate far above that can
                    # never select a spread drain
                    spread_tol = (self.cfg.spread_tolerance_milli
                                  if self.cfg.spread_tolerance_milli > 0
                                  else 1 << 20)
                    drain_need, over_milli, div_milli = rebalance_detect.score(
                        committed, capacity, valid,
                        self.cfg.overcommit_threshold_milli,
                        spread_tol)
                else:
                    drain_need = over_milli = div_milli = np.zeros(
                        0, np.int64)
            evicted = 0
            with obs.TRACER.span(obs.SPAN_REBALANCE_DRAIN) as dspan:
                evicted = self._drain(names, drain_need, by_cluster)
                if dspan:
                    dspan.set_attr(evicted=evicted)
            violations = self._audit_conservation(bindings)
            snapshot = self._publish(names, committed, capacity, drain_need,
                                     over_milli, div_milli, evicted,
                                     violations)
            if cspan:
                cspan.set_attr(evicted=evicted,
                               converged=snapshot["converged"])
        return snapshot

    # -- detect assembly -----------------------------------------------------
    def _assemble(self, clusters, bindings) -> Tuple:
        """[C] committed/capacity/valid tensors + the per-cluster victim
        candidates.  Committed counts the store's CURRENT schedule
        results (spec.clusters); capacity is the allocatable pod count —
        the denominator churn flaps move, which is exactly what makes a
        previously-fine placement overcommitted."""
        names = [c.metadata.name for c in clusters]
        idx = {n: i for i, n in enumerate(names)}
        committed = np.zeros(len(names), np.int64)
        valid = np.zeros(len(names), dtype=bool)
        # capacity reuses the shortlist plane's coarse per-cluster
        # aggregate (fleet_capacity, implemented jax-free in ops/tensors
        # and re-exported by ops/shortlist): memoized by (name, rv), so
        # only clusters whose status actually moved re-parse their
        # Quantity dicts — detect assembly stays O(C) dict lookups per
        # cycle at 10k clusters instead of O(C) Quantity parses
        from karmada_tpu.ops.tensors import fleet_capacity

        capacity = fleet_capacity(clusters)
        for i, c in enumerate(clusters):
            summary = c.status.resource_summary
            pods = summary.allocatable.get("pods") if summary else None
            valid[i] = (not c.metadata.deleting) and pods is not None
        # cluster -> [(key, priority, replicas_here, rb)] victim candidates
        by_cluster: Dict[str, List[Tuple]] = {}
        for rb in bindings:
            eligible = self._eligible(rb)
            for t in rb.spec.clusters:
                ci = idx.get(t.name)
                if ci is None:
                    continue
                committed[ci] += t.replicas
                if eligible:
                    by_cluster.setdefault(t.name, []).append(
                        ((rb.namespace, rb.name),
                         rb.spec.schedule_priority or 0, t.replicas, rb))
        return names, committed, capacity, valid, by_cluster

    @staticmethod
    def _eligible(rb: ResourceBinding) -> bool:
        """Drain candidates: Divided bindings with no pending rebalance
        eviction (an in-flight drain must settle before the same binding
        is drained again) and scheduling not suspended.  Duplicated
        placements are never drained — a re-solve would place them right
        back on every feasible cluster."""
        if rb.metadata.deleting:
            return False
        if rb.spec.suspension is not None and rb.spec.suspension.scheduling:
            return False
        if any(t.producer == PRODUCER
               for t in rb.spec.graceful_eviction_tasks):
            return False
        placement = rb.spec.placement
        if placement is None or placement.replica_scheduling is None:
            return False
        return (placement.replica_scheduling.replica_scheduling_type
                == REPLICA_SCHEDULING_DIVIDED)

    # -- drain ---------------------------------------------------------------
    def _drain(self, names, drain_need, by_cluster) -> int:
        """Evict victims on over-threshold clusters under the pacing
        budget; returns evictions performed.  Victim order: lowest
        schedule priority first, then largest per-cluster allotment
        (fewest evictions to cover the need), then name — fully
        deterministic, so virtual-clock soaks replay bit-exact."""
        order = sorted(range(len(names)),
                       key=lambda i: (-int(drain_need[i]), names[i]))
        evicted = 0
        capped = False
        # keys drained THIS cycle: a binding spanning two over-threshold
        # clusters must settle its first drain before the next — the
        # same rule _eligible enforces between cycles via the pending
        # task, which _assemble's snapshot cannot see mid-cycle
        drained_keys: set = set()
        for ci in order:
            need = int(drain_need[ci])
            if need <= 0 or capped:
                break
            cname = names[ci]
            victims = sorted(by_cluster.get(cname, ()),
                             key=lambda v: (v[1], -v[2], v[0]))
            for key, prio, reps, _rb in victims:
                if evicted >= self.cfg.max_evictions_per_cycle:
                    capped = True
                    break
                if need <= 0:
                    break
                if key in drained_keys:
                    continue
                if not self.budget.try_acquire(cname, consumer=PRODUCER):
                    # the denial is a lifecycle fact on the CLUSTER's
                    # timeline: the drain wanted to act and pacing said no
                    ev.emit(ev.ObjectRef(kind="Cluster", name=cname),
                            ev.TYPE_WARNING, ev.REASON_EVICTION_BUDGET_DENIED,
                            "rebalance drain deferred: per-cluster eviction "
                            "pacing budget exhausted for this window",
                            origin=PRODUCER)
                    break  # this cluster's window is spent; next interval
                if self._evict(key, cname, prio):
                    EVICTIONS.inc(cluster=cname)
                    drained_keys.add(key)
                    evicted += 1
                    need -= reps
        with self._lock:
            self._evictions += evicted
        return evicted

    def _evict(self, key, cname: str, priority: int) -> bool:
        """One graceful eviction + the re-place promotion.  The eviction
        mutate bumps the binding's generation (spec changed), so it
        re-enters scheduling through the normal push path; promote()
        re-tags the queue entry with origin="rebalance" so its dwell and
        admission are attributed to this plane."""
        ns, name = key
        changed = []

        def do_evict(obj: ResourceBinding) -> None:
            changed.clear()  # mutate may retry the closure
            if evict_cluster(obj, cname, reason="Rebalance",
                             producer=PRODUCER, now=self.clock()):
                changed.append(True)

        try:
            self.store.mutate(ResourceBinding.KIND, ns, name, do_evict)
        except NotFoundError:
            return False
        if changed:
            ev.emit_key(key, ev.TYPE_NORMAL, ev.REASON_REBALANCE_EVICTED,
                        f"gracefully evicted from {cname} by the rebalance "
                        "drain (re-placed with a priority push)",
                        origin=PRODUCER)
            self.scheduler.promote(key, priority=priority, origin=PRODUCER)
        return bool(changed)

    # -- conservation audit --------------------------------------------------
    def _audit_conservation(self, bindings) -> List[dict]:
        """No binding with an in-flight rebalance eviction may serve
        fewer than its desired replicas: serving = spec.clusters replicas
        + pending eviction-task replicas (those Works stay alive until
        the task drains).  A shortfall means a task drained before the
        replacement landed — the exact failure the graceful chain
        exists to prevent."""
        violations: List[dict] = []
        for rb in bindings:
            tasks = [t for t in rb.spec.graceful_eviction_tasks
                     if t.producer == PRODUCER]
            if not tasks:
                continue
            serving = (sum(t.replicas for t in rb.spec.clusters)
                       + sum(t.replicas for t in tasks))
            desired = rb.spec.replicas
            if serving < desired:
                violations.append({
                    "binding": f"{rb.namespace}/{rb.name}",
                    "serving": serving, "desired": desired})
        if violations:
            CONSERVATION_VIOLATIONS.inc(len(violations))
            with self._lock:
                self._violations += len(violations)
                self._violation_samples = (
                    self._violation_samples + violations)[-16:]
        return violations

    # -- state ---------------------------------------------------------------
    def _publish(self, names, committed, capacity, drain_need, over_milli,
                 div_milli, evicted: int, violations) -> dict:
        CYCLES.inc()
        per_cluster = {}
        for i, n in enumerate(names):
            per_cluster[n] = {
                "committed": int(committed[i]),
                "capacity": int(capacity[i]),
                "over_milli": int(over_milli[i]),
                "div_milli": int(div_milli[i]),
                "drain_need": int(drain_need[i]),
            }
            OVERCOMMIT.set(float(over_milli[i]), cluster=n)
            DRAIN_NEED.set(float(drain_need[i]), cluster=n)
        converged = not any(int(d) > 0 for d in drain_need)
        CONVERGED.set(1.0 if converged else 0.0)
        snapshot = {
            "t": round(self.clock(), 6),
            "clusters": per_cluster,
            "evicted": evicted,
            "converged": converged,
            "violations": len(violations),
        }
        with self._lock:
            self._cycles += 1
            self._last = snapshot
            for n, row in per_cluster.items():
                if row["over_milli"] > self._peak_over.get(n, 0):
                    self._peak_over[n] = row["over_milli"]
        return snapshot

    def converged(self) -> bool:
        """True when the last detect cycle found nothing to drain (and at
        least one cycle ran)."""
        with self._lock:
            return bool(self._last) and bool(self._last.get("converged"))

    def pending_drains(self) -> int:
        """In-flight rebalance eviction tasks across the store (drained
        tasks leave the list, so 0 means every drain settled)."""
        n = 0
        for rb in self.store.list(ResourceBinding.KIND):
            n += sum(1 for t in rb.spec.graceful_eviction_tasks
                     if t.producer == PRODUCER)
        return n

    def stats(self) -> dict:
        """The /debug/rebalance payload (and the soak report's
        `rebalance` section)."""
        with self._lock:
            last = dict(self._last)
            peak = dict(self._peak_over)
            cycles = self._cycles
            evictions = self._evictions
            violations = self._violations
            samples = list(self._violation_samples)
        return {
            "enabled": True,
            "config": {
                "interval_s": self.cfg.interval_s,
                "overcommit_threshold_milli":
                    self.cfg.overcommit_threshold_milli,
                "spread_tolerance_milli": self.cfg.spread_tolerance_milli,
                "max_evictions_per_cycle": self.cfg.max_evictions_per_cycle,
            },
            "cycles": cycles,
            "evictions": evictions,
            "conservation_violations": violations,
            "violation_samples": samples,
            "budget": self.budget.state(),
            # the drain story in two numbers per cluster: how overcommitted
            # it ever got vs where the last cycle left it
            "peak_over_milli": peak,
            "last": last,
        }
