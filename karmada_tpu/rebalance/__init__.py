"""Rebalance plane: descheduler-driven drain-and-re-place on the solver.

  plane.py    RebalancePlane — the periodic detect -> drain -> re-place
              cycle (jitted detect kernel in ops/rebalance_detect),
              graceful-eviction drains, conservation audit
  pacing.py   EvictionBudget — the shared per-cluster eviction-pacing
              ledger every serve-path evictor draws from (this plane +
              controllers/descheduler.py)

Armed by `Scheduler(rebalance=INTERVAL_S)` / `serve --rebalance`.  The
active plane registers process-wide so /debug/rebalance
(utils/httpserve) and `karmadactl rebalance` can publish it without
plumbing — the same pattern as the resident and load planes.  The
LATEST armed plane wins the registry; a process that builds a second
scheduler without --rebalance keeps the previous plane visible (its
store outlives it in-process) — harnesses that need a clean slate call
set_active(None).
"""

from __future__ import annotations

import threading
from typing import Optional

from karmada_tpu.utils.locks import VetLock

from karmada_tpu.rebalance.pacing import EvictionBudget  # noqa: F401
from karmada_tpu.rebalance.plane import (  # noqa: F401
    PRODUCER,
    RebalanceConfig,
    RebalancePlane,
)

_ACTIVE: Optional[RebalancePlane] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = VetLock("rebalance.active")


def set_active(plane: Optional[RebalancePlane]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = plane


def active() -> Optional[RebalancePlane]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def state_payload() -> dict:
    """The /debug/rebalance payload; {"enabled": false} when no plane is
    armed so dashboards can poll unconditionally."""
    plane = active()
    if plane is None:
        return {"enabled": False}
    return plane.stats()


def render_state(state: dict) -> str:
    """Human one-screen rendering of a /debug/rebalance payload
    (karmadactl rebalance --endpoint)."""
    if not state.get("enabled"):
        return ("no rebalance plane is armed on this plane "
                "(serve --rebalance[=INTERVAL] to arm one)")
    cfg = state.get("config") or {}
    last = state.get("last") or {}
    lines = [
        f"rebalance plane: {state.get('cycles')} cycle(s), "
        f"{state.get('evictions')} eviction(s), "
        f"{state.get('conservation_violations')} conservation violation(s)",
        f"  thresholds: overcommit {cfg.get('overcommit_threshold_milli')}m "
        f"spread {cfg.get('spread_tolerance_milli')}m; "
        f"interval {cfg.get('interval_s')}s, "
        f"max {cfg.get('max_evictions_per_cycle')} eviction(s)/cycle",
        f"  budget: {state.get('budget')}",
    ]
    if last:
        lines.append(
            f"  last cycle: evicted {last.get('evicted')}, "
            f"{'converged' if last.get('converged') else 'draining'}")
        for name, row in sorted((last.get("clusters") or {}).items()):
            lines.append(
                f"    {name}: committed {row['committed']}/"
                f"{row['capacity']} (x{row['over_milli'] / 1000:.2f}, "
                f"divergence {row['div_milli'] / 1000:+.2f}), "
                f"drain_need {row['drain_need']}")
    return "\n".join(lines)
