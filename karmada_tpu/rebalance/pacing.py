"""Shared eviction pacing: ONE per-cluster token budget for every evictor.

Two serve-path evictors act on the same fleet — the stuck-replica mover
(controllers/descheduler.py) and the rebalance plane's drain step
(rebalance/plane.py).  Each is individually rate-limited, but two
individually-paced evictors can still stampede one cluster in the same
interval.  This budget is the shared ledger both draw from: at most
`per_cluster` eviction acquisitions per cluster per `interval_s` window,
whoever asks first wins, and every denial is counted by consumer so a
starved evictor is visible on a dashboard.

The window is a fixed tumbling interval (not a continuous token bucket):
tumbling windows replay exactly on the virtual clock the compressed
soaks inject, which is what makes the pacing property testable.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict

from karmada_tpu.utils.locks import VetLock
from karmada_tpu.utils.metrics import REGISTRY

BUDGET_SPENT = REGISTRY.counter(
    "karmada_rebalance_eviction_budget_spent_total",
    "Eviction-pacing tokens granted from the shared per-cluster budget, "
    "by consumer (descheduler / rebalance)",
    ("consumer",),
)

BUDGET_DENIED = REGISTRY.counter(
    "karmada_rebalance_eviction_budget_denied_total",
    "Eviction attempts refused because the cluster's shared pacing "
    "budget for the current interval was exhausted, by consumer",
    ("consumer",),
)


class EvictionBudget:
    """Per-cluster tumbling-window eviction allowance shared by every
    serve-path evictor.  `try_acquire` is the only gate: a False return
    means the cluster already absorbed its allowed evictions this
    interval and the caller must wait for the next window."""

    def __init__(self, per_cluster: int = 8, interval_s: float = 60.0,
                 clock: Callable[[], float] = time.time) -> None:
        self.per_cluster = max(1, int(per_cluster))
        self.interval_s = float(interval_s)
        self.clock = clock
        self._lock = VetLock("rebalance.budget")
        # guarded-by: _lock — current window start (rolled in place by
        # each locked section when the interval elapses)
        self._window_start = clock()
        # guarded-by: _lock — per-cluster spend in the current window
        self._spent: Dict[str, int] = {}

    def try_acquire(self, cluster: str, consumer: str = "rebalance") -> bool:
        """One eviction token for `cluster`, or False when the cluster's
        budget for this window is spent (counted per consumer)."""
        with self._lock:
            now = self.clock()
            if now - self._window_start >= self.interval_s:
                self._window_start = now
                self._spent = {}
            spent = self._spent.get(cluster, 0)
            if spent >= self.per_cluster:
                BUDGET_DENIED.inc(consumer=consumer)
                return False
            self._spent[cluster] = spent + 1
        BUDGET_SPENT.inc(consumer=consumer)
        return True

    def remaining(self, cluster: str) -> int:
        with self._lock:
            now = self.clock()
            if now - self._window_start >= self.interval_s:
                self._window_start = now
                self._spent = {}
            return self.per_cluster - self._spent.get(cluster, 0)

    def state(self) -> dict:
        with self._lock:
            now = self.clock()
            return {
                "per_cluster": self.per_cluster,
                "interval_s": self.interval_s,
                "window_age_s": round(max(0.0, now - self._window_start), 6),
                "spent": dict(self._spent),
            }
