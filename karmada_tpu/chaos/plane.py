"""ChaosPlane: deterministic, seeded fault injection at named seams.

The serve plane survives member-cluster death by *design* (taints,
graceful eviction, admission shedding) but none of the planes UNDER the
solver — estimator RPC, device dispatch, resident mirrors, the watch
bus, worker reconciles, lease heartbeats — had a way to fail on demand,
so their failure handling was untested guesswork.  This module gives
every such seam a named injection site:

    estimator.rpc      error | timeout | slow | garbage
    device.dispatch    raise
    device.d2h         raise | poison
    device.cycle       hang
    resident.mirror    corrupt
    store.watch        drop | dup | stall | reorder
    worker.reconcile   error
    lease.heartbeat    drop

Faults are armed process-wide (`configure(spec)`, `serve --chaos SPEC`,
`Scheduler(chaos=)`) from a spec string:

    SPEC  := FAULT (';' FAULT)*
    FAULT := SITE ':' MODE [':' ARG] ['@' PROB] ['#' COUNT]

e.g. ``estimator.rpc:error@0.5`` (half of all estimator calls fail),
``device.cycle:hang:0.3#1`` (exactly one device cycle sleeps 0.3s),
``resident.mirror:corrupt#1``.  Probability draws come from a
per-rule ``random.Random`` seeded from (plane seed, site, mode, rule
index), so the same spec + seed + call sequence fires the same faults —
loadgen scenarios schedule arm/clear events on their virtual clock and
the whole storm replays bit-identically.

Disarmed cost is one list read per seam traversal (``armed()``), the
same contract as analysis/guards: the seams live directly on the
production hot paths and must be free when off.  The chaos plane never
touches a jit signature — every site is host-side — so the disarmed
solve compiles byte-identically (tier-1 compile-cache-counter test).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu.utils.locks import VetLock
from karmada_tpu.utils.metrics import REGISTRY

INJECTIONS = REGISTRY.counter(
    "karmada_chaos_injections_total",
    "Faults fired by the chaos plane, by injection site and mode",
    ("site", "mode"),
)

# -- the injection-site catalog ----------------------------------------------
SITE_ESTIMATOR_RPC = "estimator.rpc"
SITE_DEVICE_DISPATCH = "device.dispatch"
SITE_DEVICE_D2H = "device.d2h"
SITE_DEVICE_CYCLE = "device.cycle"
SITE_RESIDENT_MIRROR = "resident.mirror"
SITE_STORE_WATCH = "store.watch"
SITE_WORKER_RECONCILE = "worker.reconcile"
SITE_LEASE_HEARTBEAT = "lease.heartbeat"
SITE_REBALANCE_PLAN = "rebalance.plan"

#: site -> modes it supports (parse_spec validates against this; a seam
#: only ever interprets its own modes, so an unknown mode cannot arm)
SITES: Dict[str, Tuple[str, ...]] = {
    SITE_ESTIMATOR_RPC: ("error", "timeout", "slow", "garbage"),
    SITE_DEVICE_DISPATCH: ("raise",),
    SITE_DEVICE_D2H: ("raise", "poison"),
    SITE_DEVICE_CYCLE: ("hang",),
    SITE_RESIDENT_MIRROR: ("corrupt",),
    SITE_STORE_WATCH: ("drop", "dup", "stall", "reorder"),
    SITE_WORKER_RECONCILE: ("error",),
    SITE_LEASE_HEARTBEAT: ("drop",),
    # rebalance plane (rebalance/plane.py run_cycle): "skip" drops the
    # whole planned cycle (the next interval re-detects), "raise" aborts
    # it mid-plan — both must be contained and counted, never lose a
    # binding or leak a partial drain
    SITE_REBALANCE_PLAN: ("skip", "raise"),
}


class ChaosFault(RuntimeError):
    """The exception injected faults raise at their seam.  Deliberately a
    plain RuntimeError subclass: the surrounding machinery must handle it
    through its NORMAL failure paths (retry, backoff, degrade), never
    through chaos-aware special cases — special-casing would test the
    chaos plane, not the plane under it."""

    def __init__(self, site: str, mode: str) -> None:
        super().__init__(f"chaos fault injected at {site} (mode={mode})")
        self.site = site
        self.mode = mode


class Fault:
    """One fired fault, returned to the seam for interpretation."""

    __slots__ = ("site", "mode", "arg")

    def __init__(self, site: str, mode: str, arg: Optional[float]) -> None:
        self.site = site
        self.mode = mode
        self.arg = arg

    @property
    def delay(self) -> float:
        return self.arg if self.arg is not None else 0.0


class FaultRule:
    """One armed fault: site + mode + optional arg, probability, and a
    remaining-fire budget (None = unlimited)."""

    def __init__(self, site: str, mode: str, arg: Optional[float],
                 prob: float, count: Optional[int], seed: int,
                 index: int) -> None:
        self.site = site
        self.mode = mode
        self.arg = arg
        self.prob = prob
        self.count = count
        self.fired = 0
        # deterministic per-rule stream: the draw sequence depends only on
        # (plane seed, site, mode, rule index) and the traversal order
        self.rng = random.Random(
            (seed & 0xFFFFFFFF) ^ hash_str(f"{site}|{mode}|{index}"))

    def spent(self) -> bool:
        return self.count is not None and self.fired >= self.count

    def draw(self) -> bool:
        if self.prob >= 1.0:
            return True
        return self.rng.random() < self.prob

    def to_dict(self) -> dict:
        return {"site": self.site, "mode": self.mode, "arg": self.arg,
                "prob": self.prob, "count": self.count, "fired": self.fired}


def hash_str(s: str) -> int:
    """Stable string hash (builtin hash() is randomized per process, and
    the chaos plane's whole point is replayable fault sequences)."""
    import zlib

    return zlib.crc32(s.encode("utf-8"))


def parse_spec(spec: str, seed: int = 0) -> List[FaultRule]:
    """Parse a fault spec string into rules; ValueError on an unknown
    site/mode or malformed grammar (a typo'd chaos spec must fail the
    serve command, never silently arm nothing)."""
    rules: List[FaultRule] = []
    for i, part in enumerate(p.strip() for p in spec.replace(",", ";")
                             .split(";")):
        if not part:
            continue
        count: Optional[int] = None
        prob = 1.0
        if "#" in part:
            part, _, c = part.rpartition("#")
            try:
                count = int(c)
            except ValueError:
                raise ValueError(f"chaos spec: bad count {c!r}") from None
        if "@" in part:
            part, _, pr = part.rpartition("@")
            try:
                prob = float(pr)
            except ValueError:
                raise ValueError(
                    f"chaos spec: bad probability {pr!r}") from None
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    f"chaos spec: probability must be in (0, 1], got {prob}")
        bits = part.split(":")
        if len(bits) < 2 or len(bits) > 3:
            raise ValueError(
                f"chaos spec: expected SITE:MODE[:ARG][@PROB][#COUNT], "
                f"got {part!r}")
        site, mode = bits[0].strip(), bits[1].strip()
        arg: Optional[float] = None
        if len(bits) == 3:
            try:
                arg = float(bits[2])
            except ValueError:
                raise ValueError(
                    f"chaos spec: bad arg {bits[2]!r} (must be a number)"
                ) from None
        modes = SITES.get(site)
        if modes is None:
            raise ValueError(
                f"chaos spec: unknown site {site!r}; sites: "
                f"{', '.join(sorted(SITES))}")
        if mode not in modes:
            raise ValueError(
                f"chaos spec: site {site!r} has no mode {mode!r}; "
                f"supported: {', '.join(modes)}")
        rules.append(FaultRule(site, mode, arg, prob, count, seed, i))
    return rules


class ChaosPlane:
    """The armed rule set + fire log.  All mutation under one lock (fire
    is called from worker/publisher/estimator-pool threads); the lock is
    only ever taken while ARMED, so the disarmed path stays lock-free."""

    def __init__(self, seed: int = 0, log_cap: int = 256) -> None:
        self.seed = seed
        self._lock = VetLock("chaos.plane")
        self._rules: List[FaultRule] = []  # guarded-by: _lock
        self._next_index = 0  # guarded-by: _lock
        # guarded-by: _lock — bounded fire log (site, mode, seq, ts)
        self._log: deque = deque(maxlen=log_cap)
        self._seq = 0  # guarded-by: _lock
        self.fired_by_site: Dict[str, int] = {}  # guarded-by: _lock
        # guarded-by: _lock — (site, mode) fire totals; survives clear()
        # so the safety auditor can reason about a CLOSED fault window's
        # modes (e.g. slow fires never produce typed errors)
        self.fired_by_mode: Dict[Tuple[str, str], int] = {}

    def add(self, spec: str) -> None:
        with self._lock:
            base = self._next_index
            rules = parse_spec(spec, seed=self.seed + base)
            self._next_index = base + max(len(rules), 1)
            self._rules.extend(rules)

    def clear(self, site: Optional[str] = None) -> int:
        """Remove every rule (site=None) or just one site's; returns the
        number removed.  Loadgen fault windows end with a clear event."""
        with self._lock:
            before = len(self._rules)
            self._rules = ([] if site is None else
                           [r for r in self._rules if r.site != site])
            return before - len(self._rules)

    def fire(self, site: str, **ctx) -> Optional[Fault]:
        """First matching rule with budget whose probability draw passes
        fires (and is consumed against its count); None = no fault."""
        with self._lock:
            for rule in self._rules:
                if rule.site != site or rule.spent():
                    continue
                if not rule.draw():
                    return None  # a draw was made: the traversal is spent
                rule.fired += 1
                self._seq += 1
                self.fired_by_site[site] = self.fired_by_site.get(site, 0) + 1
                mk = (site, rule.mode)
                self.fired_by_mode[mk] = self.fired_by_mode.get(mk, 0) + 1
                self._log.append({"seq": self._seq, "site": site,
                                  "mode": rule.mode, "ts": time.time(),
                                  "ctx": {k: str(v)[:64]
                                          for k, v in ctx.items()}})
                fault = Fault(site, rule.mode, rule.arg)
                break
            else:
                return None
        INJECTIONS.inc(site=site, mode=fault.mode)
        self._annotate_span(fault)
        # lifecycle ledger: every fired fault is a timeline fact (keyed
        # by site so one outage window coalesces into a counted entry);
        # the safety auditor's accountability leg can then point at the
        # exact virtual time a seam was hit
        from karmada_tpu.obs import events as obs_events

        obs_events.emit(
            obs_events.ObjectRef(kind="ChaosPlane", name=site),
            obs_events.TYPE_WARNING, obs_events.REASON_CHAOS_FAULT_INJECTED,
            f"fault injected at {site} (mode={fault.mode})", origin="chaos")
        return fault

    @staticmethod
    def _annotate_span(fault: Fault) -> None:
        """Stamp the ambient flight-recorder span so a chaos-touched cycle
        is self-evident in its trace (the auditor's 'every fault surfaced
        in a trace span' leg reads exactly this)."""
        from karmada_tpu import obs

        if not obs.TRACER.enabled:
            return
        sp = obs.TRACER.current()
        if sp is not None:
            sp.set_attr(chaos_site=fault.site, chaos_mode=fault.mode)

    def fires(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self.fired_by_site.get(site, 0)
            return sum(self.fired_by_site.values())

    def fires_by_mode(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self.fired_by_mode)

    def fire_log(self) -> List[dict]:
        with self._lock:
            return list(self._log)

    def unspent_rules(self) -> List[dict]:
        """Rules that still have budget left (a finished chaos soak with
        unspent single-shot rules means the fault never reached its seam
        — the safety auditor reports it)."""
        with self._lock:
            return [r.to_dict() for r in self._rules
                    if r.count is not None and r.fired < r.count]

    def state(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "seed": self.seed,
                "rules": [r.to_dict() for r in self._rules],
                "fired_total": sum(self.fired_by_site.values()),
                "fired_by_site": dict(self.fired_by_site),
                "recent": list(self._log)[-32:],
            }


# -- process-wide arming ------------------------------------------------------
# guarded by convention, not a lock: configure/disarm happen at plane
# startup / soak install, fire() readers take the plane's own lock.  The
# disarmed fast path is exactly one list read (the guards._ARMED pattern).
_ARMED = [False]
_PLANE: List[Optional[ChaosPlane]] = [None]


def armed() -> bool:
    return _ARMED[0]


def active() -> Optional[ChaosPlane]:
    return _PLANE[0]


def configure(spec: str = "", seed: int = 0) -> ChaosPlane:
    """Arm the process-wide chaos plane with `spec` (may be empty: an
    armed-but-ruleless plane accepts scheduled add()/clear() events, the
    loadgen fault-window shape).  Raises ValueError on a bad spec with
    the plane left disarmed."""
    plane = ChaosPlane(seed=seed)
    if spec:
        plane.add(spec)  # validate before arming
    _PLANE[0] = plane
    _ARMED[0] = True
    return plane


def disarm() -> None:
    _ARMED[0] = False
    _PLANE[0] = None


def fire(site: str, **ctx) -> Optional[Fault]:
    """The seam entry: None when disarmed (one list read) or when no
    armed rule fires.  Call under an ``armed()`` guard so the disarmed
    path pays nothing but the guard itself."""
    plane = _PLANE[0]
    if plane is None:
        return None
    return plane.fire(site, **ctx)


def raise_if(site: str, **ctx) -> None:
    """Fire-and-raise convenience for sites whose only failure shape is
    an exception (worker.reconcile, device.dispatch)."""
    f = fire(site, **ctx)
    if f is not None and f.mode in ("raise", "error"):
        raise ChaosFault(site, f.mode)


def state_payload() -> dict:
    """/debug/chaos: the armed plane's state, or {"enabled": false} so
    dashboards can poll unconditionally."""
    plane = _PLANE[0]
    if plane is None or not _ARMED[0]:
        return {"enabled": False}
    return plane.state()
