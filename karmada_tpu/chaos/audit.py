"""Post-soak safety auditor: conservation invariants over a chaos run.

A fault-injection soak is only evidence if something PROVES the plane
stayed safe while faults were flying.  This module does that proof over
one finished LoadDriver run:

  conservation   every injected binding is exactly one of scheduled /
                 still queued / shed-accounted — none lost, and no
                 scheduled binding is double-placed (duplicate target
                 clusters in spec.clusters);
  accountability every fired fault has an observable consequence: an
                 estimator fault is a typed error count or a broken-open
                 circuit, a device fault is a contained cycle fault or a
                 backend degrade, a resident corruption is an audit
                 mismatch + forced rebuild, a single-shot rule that
                 never reached its seam is itself reported;
  recovery       a degrade that happened re-armed (when recovery is
                 configured), an opened circuit is closed again by the
                 end of the run (when the outage was cleared).

`capture_baseline()` snapshots the relevant counters at soak install;
`audit_soak(driver, baseline)` returns the payload embedded in the SOAK
report (`safety_audit`) and CHAOS_r*.json — `violations` is the list the
chaos tests assert empty.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karmada_tpu.chaos import plane as chaos_plane
from karmada_tpu.obs import events as obs_events


def _readers() -> Dict[str, object]:
    """name -> zero-arg reader.  Mostly cross-label Counter.total(); the
    resident-audit reader is pinned to outcome="mismatch" — the forced
    audit ALWAYS runs on a corruption fire, so counting its outcome="ok"
    leg too would make the mismatch proof vacuous."""
    from karmada_tpu.estimator import client as est_client
    from karmada_tpu.rebalance import plane as rebalance_plane
    from karmada_tpu.resident import state as resident_state
    from karmada_tpu.scheduler import metrics as sched_metrics
    from karmada_tpu.store import worker as store_worker

    return {
        "rebalance_conservation":
            rebalance_plane.CONSERVATION_VIOLATIONS.total,
        "rebalance_cycle_faults": rebalance_plane.CYCLE_FAULTS.total,
        "estimator_errors": est_client.ESTIMATOR_ERRORS.total,
        "circuit_transitions": est_client.CIRCUIT_TRANSITIONS.total,
        "cycle_faults": sched_metrics.CYCLE_FAULTS.total,
        "backend_degraded": sched_metrics.BACKEND_DEGRADED.total,
        "backend_rearmed": sched_metrics.BACKEND_REARMED.total,
        "resident_audits_mismatch": (
            lambda: resident_state.RESIDENT_AUDITS.value(
                outcome="mismatch")),
        "resident_rebuilds": resident_state.RESIDENT_REBUILDS.total,
        "worker_errors": store_worker.RECONCILE_ERRORS.total,
        "chaos_injections": chaos_plane.INJECTIONS.total,
    }


def capture_baseline() -> Dict[str, float]:
    """Counter readings at soak install time (the registry is
    process-wide and cumulative; the audit reasons over this run's
    deltas only)."""
    return {name: read() for name, read in _readers().items()}


def _deltas(baseline: Dict[str, float]) -> Dict[str, float]:
    return {name: read() - baseline.get(name, 0.0)
            for name, read in _readers().items()}


def surface_violations(violations: List[dict]) -> None:
    """Violations used to land only in the bench payload — surface each
    on the lifecycle ledger too (``REASON_SafetyViolation``, keyed by
    the violated invariant, on the implicated binding's timeline when
    one is named) and fire the incident trigger per invariant kind so a
    forensic bundle lands."""
    if not violations:
        return
    by_kind: Dict[str, List[dict]] = {}
    for v in violations:
        kind = str(v.get("kind", "unknown"))
        by_kind.setdefault(kind, []).append(v)
        msg = (f"safety invariant {kind!r} violated: "
               f"{v.get('detail', '')}")
        ref = v.get("binding")
        if isinstance(ref, str) and "/" in ref:
            ns, _, nm = ref.partition("/")
            obs_events.emit_key((ns, nm), obs_events.TYPE_WARNING,
                                obs_events.REASON_SAFETY_VIOLATION,
                                msg, origin="chaos-audit")
        else:
            obs_events.emit(obs_events.SCHEDULER_REF,
                            obs_events.TYPE_WARNING,
                            obs_events.REASON_SAFETY_VIOLATION,
                            msg, origin="chaos-audit")
    from karmada_tpu.obs import incidents as obs_incidents

    for kind, vs in sorted(by_kind.items()):
        obs_incidents.trigger(
            obs_incidents.TRIGGER_SAFETY_VIOLATION,
            f"safety auditor: {len(vs)} {kind!r} violation(s)",
            refs=[v["binding"] for v in vs
                  if isinstance(v.get("binding"), str)][:16],
            detail={"kind": kind, "count": len(vs),
                    "violations": vs[:10]})


def audit_soak(driver, baseline: Optional[Dict[str, float]] = None) -> dict:
    """The safety-audit payload for one finished LoadDriver run.  Must be
    called after `_drain` while the plane (store + queues) is intact and
    the chaos plane is still armed."""
    from karmada_tpu.models.work import ResourceBinding

    baseline = baseline or {}
    deltas = _deltas(baseline)
    violations: List[dict] = []
    plane = chaos_plane.active()
    sched = driver.plane.scheduler
    store = driver.plane.store

    # -- conservation: injected == scheduled + queued + shed-accounted ------
    scheduled = queued = missing = double_placed = 0
    with driver._lock:  # noqa: SLF001 — the auditor is a driver-side report
        flights = dict(driver._flight)  # noqa: SLF001
    for key, rec in flights.items():
        if rec.done:
            scheduled += 1
            rb = store.try_get(ResourceBinding.KIND, key[0], key[1])
            if rb is not None:
                names = [t.name for t in rb.spec.clusters]
                if len(names) != len(set(names)):
                    double_placed += 1
                    violations.append({
                        "kind": "double-placed", "binding": "/".join(key),
                        "clusters": names})
            continue
        with sched._queue_lock:  # noqa: SLF001 — consistent queue membership
            resident = sched.queue.has(key)
        if resident:
            queued += 1
        else:
            missing += 1
    adm = driver.admission_delta()
    shed_budget = adm.get("shed", 0) + adm.get("displaced", 0)
    if missing > shed_budget:
        violations.append({
            "kind": "binding-lost",
            "detail": f"{missing} binding(s) neither scheduled nor queued "
                      f"but only {shed_budget} shed/displaced decisions "
                      "account for terminally-dropped bindings"})
    conservation = {
        "injected": len(flights),
        "scheduled": scheduled,
        "queued_residual": queued,
        "unaccounted": missing,
        "shed_budget": shed_budget,
        "double_placed": double_placed,
    }

    # -- ledger-derived conservation (the lifecycle-ledger variant) ----------
    # the same invariant proved a SECOND way, from the event timelines
    # alone: every injected binding has a non-empty timeline whose
    # terminal event is consistent with the store/queue state the
    # recompute above read.  A disagreement means one of the two
    # accountings lies — both land in `violations`.
    ledger_conservation = _ledger_conservation(flights, sched, driver,
                                               violations)

    # -- fault accountability ------------------------------------------------
    fires: Dict[str, int] = {}
    unspent: List[dict] = []
    if plane is not None:
        fires = dict(plane.fired_by_site)
        unspent = plane.unspent_rules()
    for rule in unspent:
        violations.append({
            "kind": "fault-unfired",
            "detail": "a budgeted fault never reached its seam "
                      "(site dead or scenario mis-ordered)", "rule": rule})
    # slow-mode fires delay but do not error; only the FAILING estimator
    # modes must have been classified as typed errors (retries traverse
    # the seam again, so errors can only exceed distinct logical calls).
    # Per-mode totals come from the plane's persistent (site, mode)
    # ledger — armed rules vanish on clear(), so a closed outage window
    # must still account here.
    est_fail_fires = 0
    if plane is not None:
        est_fail_fires = sum(
            n for (site, mode), n in plane.fires_by_mode().items()
            if site == chaos_plane.SITE_ESTIMATOR_RPC and mode != "slow")
    if est_fail_fires and deltas["estimator_errors"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": chaos_plane.SITE_ESTIMATOR_RPC,
            "detail": f"{est_fail_fires} failing estimator fault(s) fired "
                      "but karmada_estimator_errors_total never moved"})
    device_fires = (fires.get(chaos_plane.SITE_DEVICE_DISPATCH, 0)
                    + fires.get(chaos_plane.SITE_DEVICE_D2H, 0))
    if device_fires and deltas["cycle_faults"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": "device.dispatch/d2h",
            "detail": f"{device_fires} device fault(s) fired but no cycle "
                      "fault was contained "
                      "(karmada_scheduler_cycle_faults_total)"})
    hang_fires = fires.get(chaos_plane.SITE_DEVICE_CYCLE, 0)
    if hang_fires and deltas["backend_degraded"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": chaos_plane.SITE_DEVICE_CYCLE,
            "detail": f"{hang_fires} device-cycle hang(s) fired but the "
                      "backend never degraded"})
    # rebalance plane: the conservation invariant holds across the whole
    # soak (no binding with an in-flight rebalance drain ever served
    # fewer than its desired replicas), and a fired rebalance.plan fault
    # must be visible as a contained cycle fault
    if deltas["rebalance_conservation"] > 0:
        violations.append({
            "kind": "rebalance-conservation",
            "detail": f"{int(deltas['rebalance_conservation'])} binding(s) "
                      "dropped below their desired replica count while a "
                      "rebalance eviction was in flight"})
    plan_fires = fires.get(chaos_plane.SITE_REBALANCE_PLAN, 0)
    if plan_fires and deltas["rebalance_cycle_faults"] <= 0:
        violations.append({
            "kind": "fault-unaccounted",
            "site": chaos_plane.SITE_REBALANCE_PLAN,
            "detail": f"{plan_fires} rebalance.plan fault(s) fired but "
                      "no rebalance cycle fault was contained "
                      "(karmada_rebalance_cycle_faults_total)"})
    corrupt_fires = fires.get(chaos_plane.SITE_RESIDENT_MIRROR, 0)
    if corrupt_fires and deltas["resident_audits_mismatch"] <= 0:
        violations.append({
            "kind": "fault-unaccounted",
            "site": chaos_plane.SITE_RESIDENT_MIRROR,
            "detail": f"{corrupt_fires} resident corruption(s) fired but "
                      "the parity audit never reported a mismatch"})

    # -- recovery ------------------------------------------------------------
    recovery: Dict[str, object] = {}
    if deltas["backend_degraded"] > 0:
        recovery["backend_degraded"] = deltas["backend_degraded"]
        recovery["backend_rearmed"] = deltas["backend_rearmed"]
        rearm_cfg = getattr(sched, "device_recover_cycles", None)
        if rearm_cfg and deltas["backend_rearmed"] <= 0:
            violations.append({
                "kind": "recovery-missed",
                "detail": "the backend degraded and recovery is configured "
                          f"(device_recover_cycles={rearm_cfg}) but it "
                          "never re-armed"})
        if rearm_cfg and sched.backend != "device" and \
                deltas["backend_rearmed"] > 0:
            violations.append({
                "kind": "recovery-missed",
                "detail": f"backend ended the run on {sched.backend!r} "
                          "despite a re-arm (degraded again without "
                          "another hang?)"})
    breaker = getattr(driver, "estimator_breaker", None)
    if breaker is not None:
        states = breaker.states()
        recovery["circuit_states"] = states
        stuck = [c for c, s in states.items() if s != "closed"]
        if est_fail_fires and stuck and not _outage_still_armed(plane):
            violations.append({
                "kind": "recovery-missed",
                "detail": "estimator outage ended but circuit(s) "
                          f"{stuck} never closed again"})

    surface_violations(violations)

    return {
        "violations": violations,
        "conservation": conservation,
        "ledger_conservation": ledger_conservation,
        "fault_fires": fires,
        "metric_deltas": {k: round(v, 6) for k, v in deltas.items()},
        "recovery": recovery,
    }


#: lifecycle-ledger reason -> terminal-state class for the ledger-derived
#: conservation walk (newest matching event wins)
_TERMINAL_STATES = {
    obs_events.REASON_SCHEDULE_BINDING_SUCCEED: "scheduled",
    obs_events.REASON_BINDING_SHED: "shed",
    obs_events.REASON_BINDING_DISPLACED: "shed",
    obs_events.REASON_SCHEDULE_BINDING_FAILED: "queued",
    obs_events.REASON_BINDING_ENQUEUED: "queued",
    obs_events.REASON_EVICT_WORKLOAD_FROM_CLUSTER: "evicted",
    obs_events.REASON_REBALANCE_EVICTED: "evicted",
}


def _ledger_terminal(timeline) -> tuple:
    """(terminal state, reasons seen) of one timeline: the newest event
    whose reason names a terminal class decides."""
    seen = set()
    terminal = "missing"
    for evd in timeline:
        seen.add(evd["reason"])
    for evd in reversed(timeline):
        state = _TERMINAL_STATES.get(evd["reason"])
        if state is not None:
            terminal = state
            break
    return terminal, seen


def _ledger_conservation(flights, sched, driver, violations) -> dict:
    """The ledger-derived conservation verdict: classify every injected
    binding from its event timeline and cross-check against the live
    store/queue state (the legacy recompute's inputs).

    Consistency rules per binding:
      * still resident in a queue  -> terminal `queued` or `evicted`
        (an eviction's re-push lands an enqueued event next, so a
        resident binding's tail is one of exactly these);
      * observed scheduled (flight.done) and not resident -> terminal
        `scheduled`, or `shed` only when a ScheduleBindingSucceed event
        precedes it (a once-scheduled binding re-offered by a cluster
        kill may legitimately be shed while re-waiting);
      * neither -> terminal `shed` (the only legitimate way to drop);
      * an empty timeline is always a gap (the ledger missed a life).
    """
    if not obs_events.armed():
        return {"enabled": False}
    led = obs_events.ledger()
    # capacity eviction during THIS run: an early binding's whole
    # timeline may have been pruned oldest-first — that is the bounded
    # journal doing its job, not a missed life, so pruned timelines are
    # REPORTED (the gap_free flag still drops) but never violations.
    # With zero evictions, a missing timeline can only be a real gap.
    base = getattr(driver, "_events_base", None) or {}
    evicted_delta = led.counters()["evicted"] - base.get("evicted", 0)
    # run scoping: the process ledger outlives drivers (a pytest process
    # runs many soaks), and deterministic binding names recur across
    # runs — only events whose ACTIVITY postdates this run's install
    # baseline count, or a prior run's stale terminal would mask a real
    # gap in this one
    seq_base = base.get("seq", 0)
    counts: Dict[str, int] = {}
    disagreements: List[dict] = []
    pruned = 0
    for key, rec in flights.items():
        ns, name = key
        timeline = [e for e in led.timeline("ResourceBinding", ns, name)
                    if e["last_seq"] > seq_base]
        terminal, seen = _ledger_terminal(timeline)
        if terminal == "missing" and evicted_delta > 0:
            pruned += 1
            counts["pruned"] = counts.get("pruned", 0) + 1
            continue
        counts[terminal] = counts.get(terminal, 0) + 1
        with sched._queue_lock:  # noqa: SLF001 — consistent membership
            resident = sched.queue.has(key)
        if resident:
            ok = terminal in ("queued", "evicted")
            expect = "queued|evicted (still resident)"
        elif rec.done:
            ok = terminal == "scheduled" or (
                terminal == "shed"
                and obs_events.REASON_SCHEDULE_BINDING_SUCCEED in seen)
            expect = "scheduled (observed done)"
        else:
            ok = terminal == "shed"
            expect = "shed (terminally dropped)"
        if not ok:
            disagreements.append({
                "binding": f"{ns}/{name}", "terminal": terminal,
                "expected": expect, "events": len(timeline)})
    for d in disagreements[:8]:
        violations.append({
            "kind": ("timeline-gap" if d["terminal"] == "missing"
                     else "ledger-disagreement"),
            "detail": f"binding {d['binding']} timeline terminal "
                      f"{d['terminal']!r} but store state expects "
                      f"{d['expected']}", **d})
    if len(disagreements) > 8:
        violations.append({
            "kind": "ledger-disagreement",
            "detail": f"{len(disagreements) - 8} further timeline "
                      "disagreement(s) truncated"})
    return {
        "enabled": True,
        "checked": len(flights),
        "terminal": counts,
        "gap_free": counts.get("missing", 0) == 0 and pruned == 0,
        "pruned_by_eviction": pruned,
        "evicted_events": int(evicted_delta),
        "disagreements": len(disagreements),
        "agrees": not disagreements,
    }


def _outage_still_armed(plane) -> bool:
    """True while an unlimited estimator fault rule is still armed (the
    circuit legitimately stays open until the outage clears)."""
    if plane is None:
        return False
    with plane._lock:  # noqa: SLF001 — read-only introspection
        return any(r.site == chaos_plane.SITE_ESTIMATOR_RPC
                   and not r.spent() for r in plane._rules)  # noqa: SLF001
