"""Post-soak safety auditor: conservation invariants over a chaos run.

A fault-injection soak is only evidence if something PROVES the plane
stayed safe while faults were flying.  This module does that proof over
one finished LoadDriver run:

  conservation   every injected binding is exactly one of scheduled /
                 still queued / shed-accounted — none lost, and no
                 scheduled binding is double-placed (duplicate target
                 clusters in spec.clusters);
  accountability every fired fault has an observable consequence: an
                 estimator fault is a typed error count or a broken-open
                 circuit, a device fault is a contained cycle fault or a
                 backend degrade, a resident corruption is an audit
                 mismatch + forced rebuild, a single-shot rule that
                 never reached its seam is itself reported;
  recovery       a degrade that happened re-armed (when recovery is
                 configured), an opened circuit is closed again by the
                 end of the run (when the outage was cleared).

`capture_baseline()` snapshots the relevant counters at soak install;
`audit_soak(driver, baseline)` returns the payload embedded in the SOAK
report (`safety_audit`) and CHAOS_r*.json — `violations` is the list the
chaos tests assert empty.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karmada_tpu.chaos import plane as chaos_plane


def _readers() -> Dict[str, object]:
    """name -> zero-arg reader.  Mostly cross-label Counter.total(); the
    resident-audit reader is pinned to outcome="mismatch" — the forced
    audit ALWAYS runs on a corruption fire, so counting its outcome="ok"
    leg too would make the mismatch proof vacuous."""
    from karmada_tpu.estimator import client as est_client
    from karmada_tpu.rebalance import plane as rebalance_plane
    from karmada_tpu.resident import state as resident_state
    from karmada_tpu.scheduler import metrics as sched_metrics
    from karmada_tpu.store import worker as store_worker

    return {
        "rebalance_conservation":
            rebalance_plane.CONSERVATION_VIOLATIONS.total,
        "rebalance_cycle_faults": rebalance_plane.CYCLE_FAULTS.total,
        "estimator_errors": est_client.ESTIMATOR_ERRORS.total,
        "circuit_transitions": est_client.CIRCUIT_TRANSITIONS.total,
        "cycle_faults": sched_metrics.CYCLE_FAULTS.total,
        "backend_degraded": sched_metrics.BACKEND_DEGRADED.total,
        "backend_rearmed": sched_metrics.BACKEND_REARMED.total,
        "resident_audits_mismatch": (
            lambda: resident_state.RESIDENT_AUDITS.value(
                outcome="mismatch")),
        "resident_rebuilds": resident_state.RESIDENT_REBUILDS.total,
        "worker_errors": store_worker.RECONCILE_ERRORS.total,
        "chaos_injections": chaos_plane.INJECTIONS.total,
    }


def capture_baseline() -> Dict[str, float]:
    """Counter readings at soak install time (the registry is
    process-wide and cumulative; the audit reasons over this run's
    deltas only)."""
    return {name: read() for name, read in _readers().items()}


def _deltas(baseline: Dict[str, float]) -> Dict[str, float]:
    return {name: read() - baseline.get(name, 0.0)
            for name, read in _readers().items()}


def audit_soak(driver, baseline: Optional[Dict[str, float]] = None) -> dict:
    """The safety-audit payload for one finished LoadDriver run.  Must be
    called after `_drain` while the plane (store + queues) is intact and
    the chaos plane is still armed."""
    from karmada_tpu.models.work import ResourceBinding

    baseline = baseline or {}
    deltas = _deltas(baseline)
    violations: List[dict] = []
    plane = chaos_plane.active()
    sched = driver.plane.scheduler
    store = driver.plane.store

    # -- conservation: injected == scheduled + queued + shed-accounted ------
    scheduled = queued = missing = double_placed = 0
    with driver._lock:  # noqa: SLF001 — the auditor is a driver-side report
        flights = dict(driver._flight)  # noqa: SLF001
    for key, rec in flights.items():
        if rec.done:
            scheduled += 1
            rb = store.try_get(ResourceBinding.KIND, key[0], key[1])
            if rb is not None:
                names = [t.name for t in rb.spec.clusters]
                if len(names) != len(set(names)):
                    double_placed += 1
                    violations.append({
                        "kind": "double-placed", "binding": "/".join(key),
                        "clusters": names})
            continue
        with sched._queue_lock:  # noqa: SLF001 — consistent queue membership
            resident = sched.queue.has(key)
        if resident:
            queued += 1
        else:
            missing += 1
    adm = driver.admission_delta()
    shed_budget = adm.get("shed", 0) + adm.get("displaced", 0)
    if missing > shed_budget:
        violations.append({
            "kind": "binding-lost",
            "detail": f"{missing} binding(s) neither scheduled nor queued "
                      f"but only {shed_budget} shed/displaced decisions "
                      "account for terminally-dropped bindings"})
    conservation = {
        "injected": len(flights),
        "scheduled": scheduled,
        "queued_residual": queued,
        "unaccounted": missing,
        "shed_budget": shed_budget,
        "double_placed": double_placed,
    }

    # -- fault accountability ------------------------------------------------
    fires: Dict[str, int] = {}
    unspent: List[dict] = []
    if plane is not None:
        fires = dict(plane.fired_by_site)
        unspent = plane.unspent_rules()
    for rule in unspent:
        violations.append({
            "kind": "fault-unfired",
            "detail": "a budgeted fault never reached its seam "
                      "(site dead or scenario mis-ordered)", "rule": rule})
    # slow-mode fires delay but do not error; only the FAILING estimator
    # modes must have been classified as typed errors (retries traverse
    # the seam again, so errors can only exceed distinct logical calls).
    # Per-mode totals come from the plane's persistent (site, mode)
    # ledger — armed rules vanish on clear(), so a closed outage window
    # must still account here.
    est_fail_fires = 0
    if plane is not None:
        est_fail_fires = sum(
            n for (site, mode), n in plane.fires_by_mode().items()
            if site == chaos_plane.SITE_ESTIMATOR_RPC and mode != "slow")
    if est_fail_fires and deltas["estimator_errors"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": chaos_plane.SITE_ESTIMATOR_RPC,
            "detail": f"{est_fail_fires} failing estimator fault(s) fired "
                      "but karmada_estimator_errors_total never moved"})
    device_fires = (fires.get(chaos_plane.SITE_DEVICE_DISPATCH, 0)
                    + fires.get(chaos_plane.SITE_DEVICE_D2H, 0))
    if device_fires and deltas["cycle_faults"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": "device.dispatch/d2h",
            "detail": f"{device_fires} device fault(s) fired but no cycle "
                      "fault was contained "
                      "(karmada_scheduler_cycle_faults_total)"})
    hang_fires = fires.get(chaos_plane.SITE_DEVICE_CYCLE, 0)
    if hang_fires and deltas["backend_degraded"] <= 0:
        violations.append({
            "kind": "fault-unaccounted", "site": chaos_plane.SITE_DEVICE_CYCLE,
            "detail": f"{hang_fires} device-cycle hang(s) fired but the "
                      "backend never degraded"})
    # rebalance plane: the conservation invariant holds across the whole
    # soak (no binding with an in-flight rebalance drain ever served
    # fewer than its desired replicas), and a fired rebalance.plan fault
    # must be visible as a contained cycle fault
    if deltas["rebalance_conservation"] > 0:
        violations.append({
            "kind": "rebalance-conservation",
            "detail": f"{int(deltas['rebalance_conservation'])} binding(s) "
                      "dropped below their desired replica count while a "
                      "rebalance eviction was in flight"})
    plan_fires = fires.get(chaos_plane.SITE_REBALANCE_PLAN, 0)
    if plan_fires and deltas["rebalance_cycle_faults"] <= 0:
        violations.append({
            "kind": "fault-unaccounted",
            "site": chaos_plane.SITE_REBALANCE_PLAN,
            "detail": f"{plan_fires} rebalance.plan fault(s) fired but "
                      "no rebalance cycle fault was contained "
                      "(karmada_rebalance_cycle_faults_total)"})
    corrupt_fires = fires.get(chaos_plane.SITE_RESIDENT_MIRROR, 0)
    if corrupt_fires and deltas["resident_audits_mismatch"] <= 0:
        violations.append({
            "kind": "fault-unaccounted",
            "site": chaos_plane.SITE_RESIDENT_MIRROR,
            "detail": f"{corrupt_fires} resident corruption(s) fired but "
                      "the parity audit never reported a mismatch"})

    # -- recovery ------------------------------------------------------------
    recovery: Dict[str, object] = {}
    if deltas["backend_degraded"] > 0:
        recovery["backend_degraded"] = deltas["backend_degraded"]
        recovery["backend_rearmed"] = deltas["backend_rearmed"]
        rearm_cfg = getattr(sched, "device_recover_cycles", None)
        if rearm_cfg and deltas["backend_rearmed"] <= 0:
            violations.append({
                "kind": "recovery-missed",
                "detail": "the backend degraded and recovery is configured "
                          f"(device_recover_cycles={rearm_cfg}) but it "
                          "never re-armed"})
        if rearm_cfg and sched.backend != "device" and \
                deltas["backend_rearmed"] > 0:
            violations.append({
                "kind": "recovery-missed",
                "detail": f"backend ended the run on {sched.backend!r} "
                          "despite a re-arm (degraded again without "
                          "another hang?)"})
    breaker = getattr(driver, "estimator_breaker", None)
    if breaker is not None:
        states = breaker.states()
        recovery["circuit_states"] = states
        stuck = [c for c, s in states.items() if s != "closed"]
        if est_fail_fires and stuck and not _outage_still_armed(plane):
            violations.append({
                "kind": "recovery-missed",
                "detail": "estimator outage ended but circuit(s) "
                          f"{stuck} never closed again"})

    return {
        "violations": violations,
        "conservation": conservation,
        "fault_fires": fires,
        "metric_deltas": {k: round(v, 6) for k, v in deltas.items()},
        "recovery": recovery,
    }


def _outage_still_armed(plane) -> bool:
    """True while an unlimited estimator fault rule is still armed (the
    circuit legitimately stays open until the outage clears)."""
    if plane is None:
        return False
    with plane._lock:  # noqa: SLF001 — read-only introspection
        return any(r.site == chaos_plane.SITE_ESTIMATOR_RPC
                   and not r.spent() for r in plane._rules)  # noqa: SLF001
