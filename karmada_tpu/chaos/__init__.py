"""Chaos plane: deterministic fault injection + post-soak safety audit.

Public surface:

  armed() / fire(site, **ctx) / raise_if(site, **ctx)
      the seam API (one list read when disarmed — guard call sites with
      ``if chaos.armed():``)
  configure(spec, seed) / disarm() / active()
      process-wide arming (`serve --chaos SPEC`, `Scheduler(chaos=)`,
      loadgen scenario fault events)
  parse_spec(spec) / SITES / SITE_*
      the fault grammar and the injection-site catalog
  state_payload()
      /debug/chaos
  capture_baseline() / audit_soak(driver, baseline)
      the conservation/accountability/recovery auditor (chaos/audit.py)

See docs/ROBUSTNESS.md for the site catalog, spec grammar, and the
invariants the auditor proves.
"""

from karmada_tpu.chaos.audit import (  # noqa: F401 — public surface
    audit_soak,
    capture_baseline,
)
from karmada_tpu.chaos.plane import (  # noqa: F401 — public surface
    SITE_DEVICE_CYCLE,
    SITE_DEVICE_D2H,
    SITE_DEVICE_DISPATCH,
    SITE_ESTIMATOR_RPC,
    SITE_LEASE_HEARTBEAT,
    SITE_REBALANCE_PLAN,
    SITE_RESIDENT_MIRROR,
    SITE_STORE_WATCH,
    SITE_WORKER_RECONCILE,
    SITES,
    ChaosFault,
    ChaosPlane,
    Fault,
    active,
    armed,
    configure,
    disarm,
    fire,
    parse_spec,
    raise_if,
    state_payload,
)
