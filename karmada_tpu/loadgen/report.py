"""SOAK reporting: flight-recorder spans -> SLO payload.

The scheduler's cycle spans (obs.SPAN_CYCLE) carry bounded per-binding
samples — `e2e_samples` (first-attempt-to-outcome schedule latency on
the queue clock) and `dwell_samples` (queue wait of the drained batch),
each with its deterministic stride (scheduler/service.py).  This module
aggregates those samples across every trace the soak recorded into
p50/p95/p99, folds in the admission counters, starvation ages, and
per-stage utilization, and shapes the single JSON payload `bench.py
--soak` emits (the SOAK_r*.json contract) and `watch_bench.py` streams
as an {"event": "soak", ...} line.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from karmada_tpu import obs

SOAK_VERSION = 1


def percentiles(sorted_values: List[float],
                qs: Iterable[float] = (0.5, 0.95, 0.99)) -> Dict[str, float]:
    """Nearest-rank percentiles over an already-sorted sample list, plus
    mean/max/count — the SLO summary shape used throughout the payload."""
    return weighted_percentiles([(v, 1) for v in sorted_values], qs)


def weighted_percentiles(
        sorted_pairs: List, qs: Iterable[float] = (0.5, 0.95, 0.99),
) -> Dict[str, float]:
    """Percentiles over (value, weight) pairs sorted by value.  Weights
    are the span-sample strides: a 4096-binding cycle's 512 strided
    samples each stand for ~8 bindings, and ignoring that would
    underweight exactly the large overloaded cycles whose latency the
    SLO exists to expose.  `count` is the summed weight (~measurements
    represented), and the quantile walk is over cumulative weight."""
    if not sorted_pairs:
        return {"count": 0}
    total = sum(w for _, w in sorted_pairs)
    out: Dict[str, float] = {}
    for q in qs:
        rank = q * total
        acc = 0.0
        pick = sorted_pairs[-1][0]
        for v, w in sorted_pairs:
            acc += w
            if acc >= rank:
                pick = v
                break
        out[f"p{int(q * 100)}"] = round(pick, 6)
    out["mean"] = round(sum(v * w for v, w in sorted_pairs) / total, 6)
    out["max"] = round(sorted_pairs[-1][0], 6)
    out["count"] = int(total)
    return out


def _cycle_spans(recorder) -> List[dict]:
    spans: List[dict] = []
    if recorder is None:
        return spans
    for tr in recorder.recent():
        for s in tr["spans"]:
            if s["name"] == obs.SPAN_CYCLE:
                spans.append(s)
    return spans


def _stage_utilization(recorder) -> dict:
    """Per-span-name time totals across every recorded trace, with each
    stage's share of the summed cycle-span time — where a wall-clock
    second of scheduling actually goes."""
    if recorder is None:
        return {}
    agg: Dict[str, dict] = {}
    cycle_total = 0.0
    for tr in recorder.recent():
        for s in tr["spans"]:
            d = s["end_s"] - s["start_s"]
            a = agg.setdefault(s["name"], {"count": 0, "total_s": 0.0,
                                           "max_s": 0.0})
            a["count"] += 1
            a["total_s"] += d
            if d > a["max_s"]:
                a["max_s"] = d
            if s["name"] == obs.SPAN_CYCLE:
                cycle_total += d
    for name, a in agg.items():
        a["total_s"] = round(a["total_s"], 6)
        a["max_s"] = round(a["max_s"], 6)
        if cycle_total > 0:
            a["of_cycle"] = round(a["total_s"] / cycle_total, 4)
    return agg


def span_samples(recorder, attr: str, stride_attr: str) -> List:
    """Every `attr` sample across the soak's cycle spans as
    (value, stride) pairs sorted by value — the stride each span
    recorded (scheduler/service._span_samples) is the sample's weight."""
    pairs: List = []
    for s in _cycle_spans(recorder):
        stride = s["attrs"].get(stride_attr) or 1
        pairs.extend((v, stride) for v in (s["attrs"].get(attr) or ()))
    pairs.sort(key=lambda p: p[0])
    return pairs


def _ledger_summary(driver) -> dict:
    """Lifecycle-ledger deltas over one driver run (None baseline = the
    ledger section degrades to lifetime counters)."""
    from karmada_tpu.obs import events as obs_events

    cur = obs_events.ledger().counters()
    base = getattr(driver, "_events_base", None) or {}
    recorded = cur["recorded"] - base.get("recorded", 0)
    coalesced = cur["coalesced"] - base.get("coalesced", 0)
    base_rsn = base.get("by_reason", {})
    by_reason = {r: n - base_rsn.get(r, 0)
                 for r, n in cur["by_reason"].items()
                 if n - base_rsn.get(r, 0) > 0}
    duration = max(float(getattr(driver, "duration_s", 0.0)), 1e-9)
    return {
        "armed": obs_events.armed(),
        "recorded": recorded,
        "coalesced": coalesced,
        "coalesce_ratio": round(coalesced / recorded, 4) if recorded else 0.0,
        "events_per_s": round(recorded / duration, 3),
        "evicted": cur["evicted"] - base.get("evicted", 0),
        "by_reason": by_reason,
    }


def build_soak_report(driver) -> dict:
    """The SOAK payload for one finished LoadDriver run."""
    recorder = getattr(driver, "recorder", None)
    e2e = span_samples(recorder, "e2e_samples", "e2e_stride")
    dwell = span_samples(recorder, "dwell_samples", "dwell_stride")
    cycles = _cycle_spans(recorder)
    batch_sizes = sorted(s["attrs"].get("bindings", 0) for s in cycles)
    fs = driver.flight_summary()
    lat = fs.pop("latencies_sorted")
    scenario = driver.scenario
    deadline_s = (scenario.deadline_s(driver.model)
                  if not driver.realtime else None)
    payload = {
        "version": SOAK_VERSION,
        "scenario": scenario.name,
        "description": scenario.description,
        "seed": driver.seed,
        "realtime": driver.realtime,
        "model": (None if driver.realtime else {
            "per_binding_s": driver.model.per_binding_s,
            "per_cycle_s": driver.model.per_cycle_s,
            "capacity_rate": round(driver.model.capacity_rate, 3),
        }),
        "arrival": {
            "load_factor": scenario.load_factor,
            "shape": scenario.shape,
            "mean_rate": round(driver.mean_rate, 3),
            "arrivals": len(driver._arrivals),  # noqa: SLF001 — report owner
        },
        "duration_s": round(driver.duration_s, 3),
        "wall_s": round(driver.wall_s, 3),
        # SLOs from the flight recorder (scheduler cycle-span samples,
        # stride-weighted so large strided cycles count fully)
        "schedule_latency_s": weighted_percentiles(e2e),
        "queue_dwell_s": weighted_percentiles(dwell),
        # driver-side ground truth (store-bus observed inject->scheduled),
        # cross-checking the span-derived quantiles above
        "driver_latency_s": percentiles(lat),
        "admission": driver.admission_delta(),
        "queue_depth": {
            "max": fs["max_depth"],
            "bound": scenario.admission_limit(),
        },
        "starvation": {
            "max_oldest_age_s": fs["max_oldest_age_s"],
            "deadline_s": deadline_s,
            "overload_entered": fs["overload_seen"],
        },
        "cycles": {
            "count": len(cycles),
            "batch_size": percentiles([float(b) for b in batch_sizes]),
            # an empty cut leaves NO span, so the spans cannot count it;
            # the scheduler counts the invariant breach at the pop site
            "empty": driver.plane.scheduler.queue_state()["empty_cuts"],
        },
        "stage_utilization": _stage_utilization(recorder),
        # resident-state plane (karmada_tpu/resident): hit rate, rebuild
        # reasons and audit outcomes for the soak window; None when the
        # plane runs rebuild-per-cycle
        "resident": (driver.plane.scheduler.resident_state()
                     if hasattr(driver.plane.scheduler, "resident_state")
                     else None),
        # rebalance plane (karmada_tpu/rebalance): cycle/eviction totals,
        # last detect scores per cluster, conservation-violation count;
        # None when the plane is disarmed
        "rebalance": (driver.plane.scheduler.rebalance_state()
                      if hasattr(driver.plane.scheduler, "rebalance_state")
                      else None),
        "residual_queue": getattr(driver, "residual", {}),
        **{k: fs[k] for k in ("injected", "scheduled", "failed_attempts",
                              "reschedules")},
    }
    # telemetry plane (obs/slo): when the SLO evaluator is armed for
    # this soak (bench --soak / --chaos / --rebalance, serve
    # --telemetry), the payload carries the multi-window burn-rate
    # verdict computed over the soak's own virtual-clock series — every
    # bench mode renders an SLO verdict, not only /debug/slo
    from karmada_tpu.obs import slo as obs_slo

    payload["slo"] = (obs_slo.state_payload()
                      if obs_slo.active() is not None else None)
    # lifecycle ledger (obs/events): this run's event deltas against the
    # driver's install-time baseline — events/s on the soak's own clock,
    # the coalesce ratio (how much the tail-bump saved the ring), and
    # the per-reason tally the timeline summaries key on
    payload["events"] = _ledger_summary(driver)
    # incident plane (obs/incidents): when a store is armed for this
    # soak, embed its capture/suppression summary so SOAK/CHAOS payloads
    # record which triggers fired and whether the cooldown held
    from karmada_tpu.obs import incidents as obs_incidents

    payload["incidents"] = (obs_incidents.state_payload()
                            if obs_incidents.active() is not None else None)
    if payload["incidents"] is not None:
        # the index alone: full bundles live on disk / /debug/incidents
        payload["incidents"].pop("flight", None)
    audit = getattr(driver, "safety_audit", None)
    if audit is not None:
        # chaos soak (karmada_tpu/chaos): the fault ledger and the
        # conservation/accountability/recovery proof — CHAOS_r*.json is
        # exactly this payload (bench.py --chaos)
        payload["chaos"] = getattr(driver, "chaos_state", {})
        payload["safety_audit"] = audit
        breaker = getattr(driver, "estimator_breaker", None)
        if breaker is not None:
            payload["estimator_circuit"] = {
                "states": breaker.states(),
                "transitions": breaker.transition_log(),
            }
    return payload


def render_load_state(state: dict) -> str:
    """Human one-screen rendering of a /debug/load payload
    (karmadactl loadgen --endpoint)."""
    if not state.get("enabled"):
        return ("no load generator is active on this plane "
                "(serve --loadgen SCENARIO to start one)")
    lines = [
        f"scenario {state['scenario']} "
        f"({'realtime' if state.get('realtime') else 'compressed'}, "
        f"seed {state.get('seed')})",
        f"  t {state.get('t_s')}s / {state.get('duration_s')}s; "
        f"arrivals {state.get('arrivals_injected')}/"
        f"{state.get('arrivals_total')}; "
        f"events {state.get('events_applied')}/{state.get('events_total')}",
        f"  injected {state.get('injected')} scheduled "
        f"{state.get('scheduled')} failed-attempts "
        f"{state.get('failed_attempts')} reschedules "
        f"{state.get('reschedules')}",
        f"  admission {state.get('admission')}",
    ]
    q = state.get("queue") or {}
    lines.append(f"  queue depths {q.get('depths')} "
                 f"oldest {q.get('oldest_age_s')}")
    lines.append(f"  overload={q.get('overload')} "
                 f"batch_window={q.get('batch_window')} "
                 f"deadline={q.get('batch_deadline_s')} "
                 f"admission_limit={q.get('admission_limit')}")
    return "\n".join(lines)
