"""Deterministic-seed arrival processes for the load generator.

Open-loop traffic: arrival times are drawn up front from a seeded RNG,
so a scenario replays bit-identically — the soak tests assert exact
admission accounting, which only holds when the traffic itself is
reproducible.  All processes are expressed as a non-homogeneous Poisson
process over a rate function `rate(t)` (arrivals/second on whatever
clock the driver injects) and realized by Lewis-Shedler thinning: draw
candidate gaps at `max_rate`, keep each candidate with probability
`rate(t) / max_rate`.
"""

from __future__ import annotations

import math
import random
from typing import Callable, List

RateFn = Callable[[float], float]


def constant_rate(rate: float) -> RateFn:
    """Steady traffic: the same expected arrivals/second forever."""
    return lambda t: rate


def diurnal_rate(base: float, amplitude: float, period_s: float,
                 t0: float = 0.0) -> RateFn:
    """Diurnal sine: rate(t) = base * (1 + amplitude * sin(...)), floored
    at 0.  `amplitude` is a fraction of base (0.8 swings between 0.2x
    and 1.8x base); the mean over whole periods stays `base`."""

    def fn(t: float) -> float:
        phase = 2.0 * math.pi * ((t - t0) / period_s)
        return max(0.0, base * (1.0 + amplitude * math.sin(phase)))

    return fn


def burst_rate(base: float, burst: float, t_start: float,
               t_end: float) -> RateFn:
    """Failover-storm shape: steady `base` with a [t_start, t_end)
    window at `burst` (absolute rate, not additive)."""

    def fn(t: float) -> float:
        return burst if t_start <= t < t_end else base

    return fn


def poisson_times(rate_fn: RateFn, max_rate: float, t0: float, t1: float,
                  rng: random.Random) -> List[float]:
    """Arrival times of a non-homogeneous Poisson process on [t0, t1)
    via thinning.  `max_rate` must dominate rate_fn over the interval
    (candidates are drawn at max_rate and kept at rate/max_rate); a
    rate_fn exceeding it silently truncates the process, so callers
    compute max_rate from the same parameters as rate_fn."""
    if max_rate <= 0.0 or t1 <= t0:
        return []
    out: List[float] = []
    t = t0
    while True:
        # exponential gap at the dominating rate
        t += -math.log(1.0 - rng.random()) / max_rate
        if t >= t1:
            return out
        if rng.random() * max_rate < rate_fn(t):
            out.append(t)
