"""Sustained-traffic serve harness (the load generator subsystem).

Every BENCH_r*.json measures one one-shot batch; production is
continuous arrival.  This package drives the serve plane with open-loop
synthetic traffic and closes the loop with the scheduler's admission /
batch-formation machinery (scheduler/queue.py, scheduler/service.py):

  arrival.py    deterministic-seed arrival processes (steady Poisson,
                diurnal sine, failover-storm burst) via thinning
  scenarios.py  the scenario catalog: arrival shape + cluster-event
                schedule + queue/admission tuning per named scenario
  driver.py     LoadDriver: injects bindings and cluster events into a
                running plane through the same store/worker paths real
                traffic uses; compressed virtual-clock mode for tier-1
                and bench soaks, real-time mode for `serve --loadgen`
  report.py     SOAK payload: p50/p95/p99 schedule latency and queue
                dwell from flight-recorder cycle spans, admission/shed
                accounting, starvation age, per-stage utilization

Exposure: `bench.py --soak SCENARIO` emits the SOAK payload,
`watch_bench.py` streams it as an {"event": "soak", ...} line, a live
driver publishes state at /debug/load (utils/httpserve), and
`karmadactl loadgen` lists/renders/rehearses scenarios.
"""

from karmada_tpu.loadgen.driver import (  # noqa: F401 — public surface
    LoadDriver,
    RealClock,
    ServeSlice,
    ServiceModel,
    VirtualClock,
    load_state,
    warm_device_path,
)
from karmada_tpu.loadgen.scenarios import SCENARIOS, get_scenario  # noqa: F401
