"""LoadDriver: open-loop synthetic traffic against the serve plane.

The driver injects ResourceBindings and cluster events through the SAME
store/worker paths real traffic takes — store.create/mutate fires the
watch bus, the scheduler's _on_event pushes through the admission gate,
the worker drains batched cycles — so a soak exercises the production
admission / batch-formation / backoff machinery, not a simulation of it.

Two execution modes:

  * compressed (the default): an injected VirtualClock plus a
    ServiceModel.  The driver wraps `scheduler.schedule_batch`; each
    cycle advances virtual time by its modeled cost
    (per_cycle_s + n * per_binding_s), stepping the clock through every
    arrival that lands WHILE the cycle runs so their queue timestamps
    are exact.  An arrival rate of "2x capacity" is then a precise
    statement (capacity = 1/per_binding_s), wall time is whatever the
    real solves cost, and every assertion is deterministic.
  * realtime: wall clock, no wrapping — arrivals are paced by a daemon
    thread against a live serve plane (`karmadactl serve --loadgen`).

The active driver registers itself process-wide so /debug/load
(utils/httpserve) can publish live state.
"""

from __future__ import annotations

import random
import threading
import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from karmada_tpu.loadgen.scenarios import Scenario
from karmada_tpu.utils.locks import VetLock
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    ClusterAffinity,
    Placement,
    PropagationPolicy,
    PropagationSpec,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    ReplicaSchedulingStrategy,
    ResourceSelector,
)
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import (
    COND_SCHEDULED,
    ObjectReference,
    ResourceBinding,
    ResourceBindingSpec,
)
from karmada_tpu.scheduler import metrics as sched_metrics
from karmada_tpu.scheduler.queue import SchedulingQueue
from karmada_tpu.scheduler.service import Scheduler
from karmada_tpu.obs import events as obs_events
from karmada_tpu.store.store import DELETED, Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import Runtime
from karmada_tpu.utils.quantity import Quantity

LOADGEN_NS = "loadgen"
PRIORITY_HIGH = 10


class VirtualClock:
    """Injectable monotonic clock for compressed-time soaks — the same
    object serves as the SchedulingQueue's `now` and the driver's event
    timeline, so dwell/e2e are measured on one consistent axis."""

    def __init__(self, start: float = 1_000_000.0) -> None:
        self._t = start  # guarded-by: _lock
        self._lock = VetLock("loadgen.clock")

    def now(self) -> float:
        return self._t

    __call__ = now

    def advance(self, dt: float) -> float:
        with self._lock:
            self._t += max(0.0, dt)
            return self._t

    def advance_to(self, t: float) -> float:
        with self._lock:
            if t > self._t:
                self._t = t
            return self._t


class RealClock:
    """Wall clock with the same surface (realtime mode); advances are
    no-ops because reality advances itself."""

    def now(self) -> float:
        return _time.time()

    __call__ = now

    def advance(self, dt: float) -> float:
        return self.now()

    def advance_to(self, t: float) -> float:
        return self.now()


@dataclass(frozen=True)
class ServiceModel:
    """Virtual cost of one scheduling cycle in compressed mode.  The
    plane's solve capacity is 1/per_binding_s bindings/second (the
    per-cycle overhead is why trickle batching matters: many small
    cycles pay it per few bindings).  bench --soak calibrates
    per_binding_s from a real measured cycle; tier-1 tests fix it."""

    per_binding_s: float = 0.01
    per_cycle_s: float = 0.02

    def cost(self, n: int) -> float:
        return self.per_cycle_s + n * self.per_binding_s

    @property
    def capacity_rate(self) -> float:
        return 1.0 / self.per_binding_s


def build_cluster(name: str, cpu_milli: int = 64_000, memory_gi: int = 256,
                  pods: int = 1000, region: str = "") -> Cluster:
    return Cluster(
        metadata=ObjectMeta(name=name),
        spec=ClusterSpec(region=region or None),
        status=ClusterStatus(
            api_enablements=[APIEnablement("apps/v1", ["Deployment"])],
            resource_summary=ResourceSummary(
                allocatable={"cpu": Quantity.parse(str(cpu_milli) + "m"),
                             "memory": Quantity.parse(f"{memory_gi}Gi"),
                             "pods": Quantity.parse(str(pods))},
            ),
        ),
    )


def _scheduling_strategy(divided: bool) -> ReplicaSchedulingStrategy:
    if divided:
        # Divided + Aggregated: pack the replicas into the fewest
        # most-available clusters — the shape rebalance drains act on
        return ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED)
    return ReplicaSchedulingStrategy(
        replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)


def build_binding(name: str, priority: int = 0,
                  namespace: str = LOADGEN_NS,
                  resource_name: Optional[str] = None,
                  replicas: int = 1,
                  divided: bool = False,
                  affinity: Optional[List[str]] = None) -> ResourceBinding:
    """A synthetic binding: Duplicated placement over every feasible
    cluster (no affinity restriction), so cluster kills force real
    rescheduling work — or, with `divided`, Divided+Aggregated packing
    of `replicas` into the fewest clusters (the rebalance plane's
    drainable shape).  `resource_name` points every binding at one
    shared template (full-ControlPlane runs, where the binding
    controller renders real Works from it).  `affinity` restricts the
    placement to the named clusters (the megafleet shape: per-tenant
    eligible sets a shortlist k covers)."""
    rb = ResourceBinding()
    rb.metadata.namespace = namespace
    rb.metadata.name = name
    rb.spec = ResourceBindingSpec(
        resource=ObjectReference(api_version="apps/v1", kind="Deployment",
                                 namespace=namespace,
                                 name=resource_name or name,
                                 uid=f"uid-{name}"),
        replicas=replicas,
        placement=Placement(
            cluster_affinity=(ClusterAffinity(cluster_names=list(affinity))
                              if affinity else None),
            replica_scheduling=_scheduling_strategy(divided)),
        schedule_priority=priority or None,
    )
    return rb


def build_workload_manifest(name: str, replicas: int,
                            namespace: str = LOADGEN_NS) -> dict:
    """A Deployment template for policy-path injection: the detector
    matches it against the loadgen PropagationPolicy and renders the
    ResourceBinding — the full template -> policy -> binding fan-out."""
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": {"loadgen.karmada.io/injected": "true"}},
        "spec": {"replicas": replicas, "template": {"spec": {
            "containers": [{"name": "app", "image": "app:1",
                            "resources": {"requests": {"cpu": "100m"}}}],
        }}},
    }


def build_loadgen_policy(divided: bool,
                         namespace: str = LOADGEN_NS) -> PropagationPolicy:
    """ONE PropagationPolicy claiming every policy-path workload in the
    loadgen namespace (detector/policy fan-out under load)."""
    return PropagationPolicy(
        metadata=ObjectMeta(name="lg-policy", namespace=namespace),
        spec=PropagationSpec(
            resource_selectors=[ResourceSelector(
                api_version="apps/v1", kind="Deployment",
                namespace=namespace)],
            placement=Placement(
                replica_scheduling=_scheduling_strategy(divided)),
        ),
    )


def warm_device_path(plane, sizes: Tuple[int, ...] = (2, 9, 17, 64),
                     aot_variants: bool = True) -> None:
    """Compile-warm a device-backend slice before a guarded soak: direct
    schedule_batch calls pay the jit compile cost OUTSIDE the mid-serve
    death guard's window, so a tight device_cycle_timeout_s measures
    stuck cycles, not first-call compiles.  `sizes` spans the pow2
    binding-axis buckets (8/16/32/64 for the default batch_window 64)
    the soak's variable cuts will hit — an unseen shape mid-soak would
    compile fresh and read as a hung cycle.  The warm bindings stay in
    the store as ordinary residents (not flight-tracked, so reports and
    audits ignore them).

    The store-driven cycles above only compile the PLAIN pow2 variants;
    with `aot_variants` (default) the remaining jit variants this
    scheduler can actually dispatch — explain-sampled cycles, the carry /
    donated chain of multi-chunk cycles, the fused resident-gather
    executable when --resident-fused armed it, mesh-placed when a solver
    mesh is active — are AOT pre-compiled too (ops/aotcache), so the
    first explain-sampled, donated, or fused cycle mid-soak doesn't eat
    a silent mid-traffic compile that reads as a hung cycle."""
    from karmada_tpu.models.work import ResourceBinding as _RB

    sched = plane.scheduler
    prev = sched.device_cycle_timeout_s
    sched.device_cycle_timeout_s = None
    made = 0
    try:
        clusters = list(plane.store.list(Cluster.KIND))
        for size in sizes:
            names = []
            for _ in range(size):
                names.append(f"lg-warm{made:03d}")
                made += 1
                plane.store.create(build_binding(names[-1]))
            rbs = [plane.store.try_get(_RB.KIND, LOADGEN_NS, name)
                   for name in names]
            sched.schedule_batch(
                [rb for rb in rbs if rb is not None], clusters)
        if aot_variants:
            from karmada_tpu.ops import aotcache

            variants = tuple(
                v for v in aotcache.variants_for(
                    sched.explain,
                    sched.batch_window > sched.pipeline_chunk,
                    fused=getattr(sched, "resident_fused", False),
                    shortlist=bool(getattr(sched, "shortlist_k", None)))
                if v != aotcache.VARIANT_PLAIN)
            if variants:
                aotcache.warm_executables(
                    clusters, sched._general,  # noqa: SLF001 — same package
                    shapes=sizes, variants=variants, waves=sched.waves,
                    keep_sel=sched.enable_empty_workload_propagation,
                    shortlist_k=getattr(sched, "shortlist_k", None))
    finally:
        sched.device_cycle_timeout_s = prev


class ReplacementStatusEcho:
    """Stand-in for the member status-collection chain in the
    scheduler-only slice: whenever a binding's schedule result changes,
    report every target cluster applied + Healthy in aggregated_status.
    The graceful-eviction controller then drains rebalance eviction
    tasks on the PRODUCTION signal (replacement healthy), not only on
    grace expiry.  Terminates trivially: once the echo matches the spec,
    further events are no-ops (the store's drain loop is re-entrancy
    safe for subscriber writes)."""

    def __init__(self, store: ObjectStore) -> None:
        self.store = store
        store.bus.subscribe(self._on_event, kind=ResourceBinding.KIND)

    def _on_event(self, event: Event) -> None:
        if event.type == DELETED:
            return
        rb = event.obj
        want = {t.name for t in rb.spec.clusters}
        have = {i.cluster_name for i in rb.status.aggregated_status
                if i.applied and i.health == "Healthy"}
        if want == have:
            return
        from karmada_tpu.models.work import AggregatedStatusItem

        def echo(obj: ResourceBinding) -> None:
            obj.status.aggregated_status = [
                AggregatedStatusItem(cluster_name=t.name, applied=True,
                                     health="Healthy")
                for t in obj.spec.clusters]

        try:
            self.store.mutate(ResourceBinding.KIND, rb.metadata.namespace,
                              rb.metadata.name, echo)
        except NotFoundError:
            pass


class ServeSlice:
    """The scheduler-owning slice of a ControlPlane: store + runtime +
    batched scheduler over the same SchedulingQueue/worker machinery
    serve mode runs.  The full ControlPlane wires ~30 controllers the
    soak does not exercise; the slice keeps tier-1 soaks inside budget.
    LoadDriver duck-types its plane — anything exposing .store /
    .runtime / .scheduler (a ControlPlane included) drives the same.

    Scenario-driven extras: `policy_path` scenarios get the real
    ResourceDetector (template -> policy -> binding fan-out), and
    `rebalance_interval_cycles` scenarios arm the rebalance plane plus
    the graceful-eviction chain it drains through (with the status echo
    standing in for member health collection)."""

    def __init__(self, scenario: Scenario, clock, model: ServiceModel,
                 backend: str = "serial", explain: float = 0.0,
                 resident: bool = False,
                 resident_audit_interval: int = 64,
                 device_cycle_timeout_s: Optional[float] = None,
                 device_recover_cycles: Optional[int] = None) -> None:
        self.store = ObjectStore()
        self.runtime = Runtime()
        reb_interval = scenario.rebalance_interval_s(model)
        reb_cfg = reb_budget = None
        if reb_interval > 0:
            from karmada_tpu.rebalance import EvictionBudget, RebalanceConfig

            # per-cluster budget sized so a hotspot drain takes a couple
            # of windows (pacing visible in the soak, convergence still
            # bounded); the window is the rebalance interval itself
            reb_budget = EvictionBudget(per_cluster=24,
                                        interval_s=reb_interval,
                                        clock=clock)
            reb_cfg = RebalanceConfig(interval_s=reb_interval)
        self.scheduler = Scheduler(
            self.store, self.runtime, backend=backend,
            batch_window=scenario.batch_window,
            batch_deadline_s=scenario.deadline_s(model),
            queue=SchedulingQueue(now=clock,
                                  max_resident=scenario.admission_limit()),
            explain=explain,
            resident=resident,
            resident_audit_interval=resident_audit_interval,
            device_cycle_timeout_s=device_cycle_timeout_s,
            device_recover_cycles=device_recover_cycles,
            rebalance=(reb_interval or None),
            rebalance_cfg=reb_cfg,
            rebalance_budget=reb_budget,
            # scenario-driven shortlist tier (ops/shortlist): compressed
            # scales must still arm, so the cell threshold drops to 0 —
            # the scenario IS the operator's explicit opt-in
            shortlist_k=(scenario.shortlist_k or None),
            shortlist_min_cells=0,
        )
        if scenario.policy_path:
            from karmada_tpu.controllers.detector import ResourceDetector
            from karmada_tpu.interpreter import ResourceInterpreter

            self.interpreter = ResourceInterpreter()
            self.interpreter.attach_store(self.store)
            self.detector = ResourceDetector(self.store, self.runtime,
                                             self.interpreter)
        if reb_interval > 0:
            from karmada_tpu.controllers.failover import (
                GracefulEvictionController,
            )

            # grace period far beyond the soak horizon: ONLY replacement
            # health may drain a task, so a conservation breach cannot
            # hide behind a grace-expiry drain
            self.graceful_eviction = GracefulEvictionController(
                self.store, self.runtime, grace_period_s=1e9, clock=clock)
            self.status_echo = ReplacementStatusEcho(self.store)
        for i in range(scenario.n_clusters):
            # group-affine fleets (scenario.n_regions > 0): clusters
            # round-robin into regions; megafleet bindings target one
            # region each via cluster affinity
            region = (f"lg-r{i % scenario.n_regions}"
                      if scenario.n_regions > 0 else "")
            self.store.create(build_cluster(f"lg-m{i}", region=region))


@dataclass
class _Flight:
    """Per-injected-binding lifecycle record (driver-side ground truth,
    cross-checking the span-derived report quantiles)."""

    t_inject: float
    priority: int
    done: bool = False
    t_done: float = 0.0
    failed_attempts: int = 0
    reschedules: int = 0


# -- /debug/load registry -----------------------------------------------------
_ACTIVE: Optional["LoadDriver"] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = VetLock("loadgen.active")


def set_active(driver: Optional["LoadDriver"]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = driver


def load_state() -> dict:
    """The /debug/load payload: the active driver's live snapshot, or
    {"enabled": false} so dashboards can poll unconditionally."""
    with _ACTIVE_LOCK:
        driver = _ACTIVE
    if driver is None:
        return {"enabled": False}
    return driver.snapshot()


class LoadDriver:
    def __init__(
        self,
        plane,                       # .store / .runtime / .scheduler
        scenario: Scenario,
        clock=None,
        model: Optional[ServiceModel] = None,
        seed: int = 0,
        realtime: bool = False,
        # realtime only: mean arrival rate in real arrivals/second (the
        # scenario shape scales around it via its load_factor)
        realtime_rate: float = 20.0,
        trace_capacity: int = 4096,
        # point every synthetic binding at one shared resource template
        # (full-ControlPlane runs, so Works render from a real object)
        resource_name: Optional[str] = None,
    ) -> None:
        self.plane = plane
        self.scenario = scenario
        self.realtime = realtime
        self.resource_name = resource_name
        # policy-path mode: inject Deployment templates the detector
        # renders into bindings (the plane must wire a detector —
        # ServeSlice does for policy_path scenarios; a ControlPlane
        # always has one)
        self.policy_path = scenario.policy_path
        self.clock = clock if clock is not None else (
            RealClock() if realtime else VirtualClock())
        self.model = model if model is not None else ServiceModel()
        self.seed = seed
        self.rng = random.Random(seed)
        self.trace_capacity = trace_capacity
        # realtime runs drive a LIVE (possibly persistent) plane: binding
        # names must not collide with an earlier soak's leftovers in the
        # same store, so each run gets a wall-clock tag.  Compressed runs
        # keep the bare deterministic names (fresh plane, reproducible).
        self._name_tag = (f"{_time.time_ns() % 0xffffff:06x}-"
                          if realtime else "")
        # realtime: honor the documented contract (realtime_rate is the
        # MEAN arrival rate) for every shape — mean_rate is linear in
        # capacity, so solving mean_rate(cap) == realtime_rate is one
        # division.  Dividing by load_factor alone would overshoot burst
        # scenarios (their burst window adds arrivals on top of base).
        cap = (realtime_rate / max(scenario.mean_rate(1.0), 1e-9)
               if realtime else self.model.capacity_rate)
        self.capacity_rate = cap
        self.t0 = self.clock.now()
        self.duration_s = scenario.duration_s(cap)
        rate_fn, max_rate = scenario.rate_fn(cap, self.t0, self.duration_s)
        self.mean_rate = scenario.mean_rate(cap)
        from karmada_tpu.loadgen.arrival import poisson_times

        self._arrivals: List[float] = poisson_times(
            rate_fn, max_rate, self.t0, self.t0 + self.duration_s, self.rng)
        self._events: List[Tuple[float, object]] = sorted(
            ((self.t0 + ev.at_frac * self.duration_s, ev)
             for ev in scenario.events), key=lambda p: p[0])
        self._arr_idx = 0
        self._evt_idx = 0
        self._n_injected = 0
        self._lock = VetLock("loadgen.flight")
        self._flight: Dict[Tuple[str, str], _Flight] = {}  # guarded-by: _lock
        self._max_depth: Dict[str, int] = {}  # guarded-by: _lock
        self._max_oldest: Dict[str, float] = {}  # guarded-by: _lock
        self._overload_seen = False
        self._killed: List[Cluster] = []   # kill/revive LIFO (driver thread)
        self._flapped: Dict[str, dict] = {}  # name -> original allocatable
        self._flap_rr = 0  # rotating flap_down victim cursor (driver thread)
        # "whatif" event answers (facade capacity queries fired mid-soak;
        # the whatif scenario asserts they leave placements bit-identical)
        self.whatif_results: List[dict] = []
        self._installed = False
        self._orig_schedule = None
        self._prev_recorder = None
        self._base_admission: Dict[str, float] = {}
        self._wall_t0 = 0.0
        self.wall_s = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.residual: dict = {}
        # chaos plumbing (scenario.chaotic): the driver arms the process-
        # wide chaos plane, runs the per-cycle estimator fan-out harness
        # (circuit-breaker dynamics on the virtual clock), and runs the
        # safety auditor before uninstall (harness + audit: compressed
        # mode only; a realtime chaotic scenario still arms the plane and
        # applies its scheduled fault windows)
        self._chaos = scenario.chaotic
        self._audit_baseline: dict = {}
        self.estimator_client = None
        self.estimator_breaker = None
        self.chaos_state: dict = {}
        self.safety_audit: Optional[dict] = None

    # -- wiring --------------------------------------------------------------
    def _setup_chaos(self) -> None:
        """Arm the chaos plane (empty: the scenario's fault events add
        rules at their scheduled times) and, in compressed mode, the
        estimator fan-out harness: the production AccurateEstimatorClient
        against one LocalTransport per loadgen cluster, retry sleeps
        no-oped (virtual time must not wall-sleep) and the circuit
        breaker's open-window on the soak's virtual clock.  One fan-out
        per scheduling cycle gives the breaker its traffic."""
        from karmada_tpu import chaos as chaos_mod

        chaos_mod.configure("", seed=self.seed)
        self._audit_baseline = chaos_mod.capture_baseline()
        if self.realtime:
            return
        from karmada_tpu.estimator.client import (
            AccurateEstimatorClient,
            CircuitBreaker,
        )
        from karmada_tpu.estimator.wire import LocalTransport

        breaker = CircuitBreaker(
            failure_threshold=3,
            reset_timeout_s=self.model.cost(self.scenario.batch_window),
            clock=self.clock)
        client = AccurateEstimatorClient(
            breaker=breaker, sleep=lambda _s: None)
        for c in self.plane.store.list(Cluster.KIND):
            client.register(
                c.metadata.name,
                LocalTransport(lambda _m, _r: {"maxReplicas": 50,
                                               "unschedulableReplicas": 0}))
        self.estimator_client = client
        self.estimator_breaker = breaker

    def _estimator_probe(self) -> None:
        """One per-cycle estimator fan-out across the live fleet (the
        harness's stand-in for the scheduler's accurate-tier traffic).
        Uses the unschedulable-replicas call — the one estimator method
        with no rv-keyed memo, so every probe really crosses the wire
        and the outage window's faults reach the breaker."""
        for c in self.plane.store.list(Cluster.KIND):
            self.estimator_client.unschedulable_replicas(
                c.metadata.name, "Deployment", LOADGEN_NS, "probe")

    def _install(self) -> None:
        from karmada_tpu import obs

        assert not self._installed
        self._installed = True
        self._wall_t0 = _time.perf_counter()
        if self.policy_path:
            # one policy claims every injected template (detector fan-out)
            policy = build_loadgen_policy(
                self.scenario.binding_style == "divided")
            if self.plane.store.try_get(
                    PropagationPolicy.KIND, LOADGEN_NS,
                    policy.name) is None:
                self.plane.store.create(policy)
        if self._chaos:
            self._setup_chaos()
        # arm the flight recorder (the report derives its latency/dwell
        # quantiles from cycle-span samples); restore on uninstall so a
        # soak inside a test suite leaves the global tracer untouched.
        # Realtime mode never builds a report, so it must NOT flip the
        # process-wide tracer on as a side effect — a serve operator
        # arms tracing with --trace-buffer, not --loadgen
        self._prev_recorder = obs.TRACER.recorder
        if self._prev_recorder is None and not self.realtime:
            obs.TRACER.configure(capacity=self.trace_capacity, slow_keep=8)
        self.recorder = obs.TRACER.recorder
        self._base_admission = {
            d: sched_metrics.ADMISSION.value(decision=d)
            for d in ("admitted", "shed", "displaced")}
        self.plane.store.bus.subscribe(self._on_store_event)
        # lifecycle-ledger baseline: the SOAK report embeds this run's
        # event deltas (events/s, coalesce ratio, per-reason counts)
        self._events_base = obs_events.ledger().counters()
        self._prev_queue_now = None
        self._prev_events_clock = None
        if not self.realtime:
            # the ledger stamps on the SAME virtual clock the queue runs
            # on (the obs_timeseries.maybe_sample discipline): compressed
            # soak events must order against the virtual timeline, not
            # interleave wall time with it
            self._prev_events_clock = obs_events.set_clock(self.clock)
            sched = self.plane.scheduler
            # compressed time only works when the scheduler's queue stamps
            # on the SAME clock the driver advances — a duck-typed plane
            # (ControlPlane built without an injected queue) arrives on
            # wall clock, where backoff expiries would never fire inside
            # the virtual-time drain and dwell would mix time axes.
            # Re-point it; _uninstall restores.  (The queue is empty at
            # install for any fresh plane; pre-resident wall-stamped
            # entries would keep wall-clock backoff expiries.)
            if sched.queue.now is not self.clock:
                self._prev_queue_now = sched.queue.now
                sched.queue.now = self.clock
            # remember whether schedule_batch was already instance-patched
            # (a spy, a nested wrapper) so uninstall can restore EXACTLY
            # the prior state instead of pinning a new instance attribute
            self._had_instance_schedule = "schedule_batch" in vars(sched)
            self._orig_schedule = sched.schedule_batch

            def wrapped(bindings, clusters):
                # the cycle occupies [t, t + cost): step the clock through
                # every arrival landing while it runs (exact queue
                # timestamps), then stamp outcomes at completion time
                t_end = self.clock.now() + self.model.cost(len(bindings))
                self._inject_due(t_end)
                self.clock.advance_to(t_end)
                if self.estimator_client is not None:
                    # chaos harness: one estimator fan-out per cycle keeps
                    # the circuit breaker fed on the same virtual clock
                    self._estimator_probe()
                res = self._orig_schedule(bindings, clusters)
                self._sample_queue()
                return res

            sched.schedule_batch = wrapped
        set_active(self)

    def _uninstall(self) -> None:
        from karmada_tpu import obs

        if not self._installed:
            return
        self._installed = False
        self.wall_s = _time.perf_counter() - self._wall_t0
        if self._orig_schedule is not None:
            if self._had_instance_schedule:
                self.plane.scheduler.schedule_batch = self._orig_schedule
            else:
                del self.plane.scheduler.schedule_batch  # class method shows
            self._orig_schedule = None
        if self._prev_queue_now is not None:
            self.plane.scheduler.queue.now = self._prev_queue_now
            self._prev_queue_now = None
        if self._prev_events_clock is not None:
            obs_events.set_clock(self._prev_events_clock)
            self._prev_events_clock = None
        self.plane.store.bus.unsubscribe(self._on_store_event)
        obs.TRACER.recorder = self._prev_recorder
        if self._chaos:
            # the chaos plane is process-wide: a finished soak must not
            # leave faults armed for whatever runs next
            from karmada_tpu import chaos as chaos_mod

            chaos_mod.disarm()
        set_active(None)

    # -- traffic -------------------------------------------------------------
    def _inject_binding(self, t: float) -> None:
        self._n_injected += 1
        if self.policy_path:
            # template in, binding out: the detector matches the loadgen
            # policy and renders the ResourceBinding, so the soak load
            # crosses the full controller fan-out.  The flight is keyed
            # by the binding the detector WILL create.
            from karmada_tpu.controllers.detector import binding_name

            name = f"lg-{self._name_tag}w{self._n_injected:06d}"
            key = (LOADGEN_NS, binding_name("Deployment", name))
            with self._lock:
                self._flight[key] = _Flight(t_inject=t, priority=0)
            self.plane.store.create(Unstructured.from_manifest(
                build_workload_manifest(
                    name, self.scenario.binding_replicas)))
            return
        name = f"lg-{self._name_tag}b{self._n_injected:06d}"
        prio = (PRIORITY_HIGH
                if self.rng.random() < self.scenario.priority_high_frac
                else 0)
        affinity = None
        if self.scenario.n_regions > 0:
            # tenant-clustered arrival: the targeted region advances per
            # batch_window block, not per binding — real traffic arrives
            # in per-tenant bursts, and it is exactly this locality that
            # keeps a chunk's candidate union narrow under the shortlist
            affinity = self._region_names(
                (self._n_injected // max(self.scenario.batch_window, 1))
                % self.scenario.n_regions)
        with self._lock:
            self._flight[(LOADGEN_NS, name)] = _Flight(t_inject=t,
                                                       priority=prio)
        self.plane.store.create(build_binding(
            name, priority=prio, resource_name=self.resource_name,
            replicas=self.scenario.binding_replicas,
            divided=self.scenario.binding_style == "divided",
            affinity=affinity))

    def _region_names(self, group: int) -> List[str]:
        """Cluster names of one region group (group-affine scenarios),
        derived once from the live store so any plane shape works."""
        cached = getattr(self, "_region_name_cache", None)
        if cached is None:
            cached = {}
            for c in self.plane.store.list(Cluster.KIND):
                r = c.spec.region
                if r:
                    cached.setdefault(r, []).append(c.metadata.name)
            self._region_name_cache = cached
        key = f"lg-r{group}"
        return cached.get(key) or sorted(
            n for names in cached.values() for n in names) or None

    def _apply_cluster_event(self, spec) -> None:
        if spec.kind == "whatif":
            # a facade capacity query riding the soak (karmada_tpu/facade):
            # a hypothetical solve on a copy-on-write fork of live state —
            # the whatif scenario's control run proves it never moves a
            # placement.  `spec` names the query (default placement),
            # `count` carries the replica count.
            from karmada_tpu.facade import messages as facade_messages
            from karmada_tpu.facade import whatif as facade_whatif

            req = facade_messages.WhatIfRequest(
                query=spec.spec or facade_messages.QUERY_PLACEMENT,
                replicas=max(spec.count, 1),
                resource_request={"cpu": "500m", "memory": "512Mi"})
            resp = facade_whatif.run_query(self.plane.scheduler,
                                           self.plane.store, req)
            self.whatif_results.append(resp.to_json())
            return
        if spec.kind in ("chaos", "chaos_clear"):
            # scheduled fault window on the same virtual clock as the
            # traffic: arm/clear rules on the process-wide chaos plane
            from karmada_tpu import chaos as chaos_mod

            plane = chaos_mod.active()
            if plane is not None:
                if spec.kind == "chaos":
                    plane.add(spec.spec)
                else:
                    plane.clear(spec.spec or None)
            return
        if spec.count <= 0:
            return  # a zero-count event is a no-op, NOT alive[-0:] == all
        store = self.plane.store
        clusters = list(store.list(Cluster.KIND))
        if spec.kind == "kill":
            import copy

            alive = [c for c in clusters
                     if c.metadata.name not in self._flapped]
            victims = alive[-spec.count:] if alive else []
            dead = set()
            for c in victims:
                # stash the REAL cluster (spec + status capacity) so a
                # later revive restores what actually died — against a
                # live plane the members carry operator-chosen capacity,
                # not the loadgen defaults; metadata is rebuilt fresh so
                # the re-create is not poisoned by deletion bookkeeping
                self._killed.append(Cluster(
                    metadata=ObjectMeta(
                        name=c.metadata.name,
                        labels=dict(c.metadata.labels),
                        annotations=dict(c.metadata.annotations)),
                    spec=copy.deepcopy(c.spec),
                    status=copy.deepcopy(c.status)))
                dead.add(c.metadata.name)
                try:
                    store.delete(Cluster.KIND, "", c.metadata.name)
                except NotFoundError:
                    pass
            # failover: evict placements referencing dead clusters — the
            # spec change bumps the generation, so every affected binding
            # re-enters the scheduler through the normal push path (the
            # same storm the graceful-eviction machinery produces)
            for rb in list(store.list(ResourceBinding.KIND)):
                if not any(tc.name in dead for tc in rb.spec.clusters):
                    continue

                def evict(obj: ResourceBinding) -> None:
                    obj.spec.clusters = [tc for tc in obj.spec.clusters
                                         if tc.name not in dead]

                store.mutate(ResourceBinding.KIND, rb.metadata.namespace,
                             rb.metadata.name, evict)
                obs_events.emit_key(
                    (rb.metadata.namespace, rb.metadata.name),
                    obs_events.TYPE_WARNING,
                    obs_events.REASON_EVICT_WORKLOAD_FROM_CLUSTER,
                    "evicted from killed cluster(s): placements referenced "
                    "a dead cluster (failover re-schedule)",
                    origin="loadgen")
                with self._lock:
                    rec = self._flight.get(
                        (rb.metadata.namespace, rb.metadata.name))
                    if rec is not None:
                        rec.reschedules += 1
        elif spec.kind == "revive":
            for _ in range(min(spec.count, len(self._killed))):
                store.create(self._killed.pop())
        elif spec.kind == "flap_down":
            if not clusters:
                return
            # rotate the victim window across flap events: the churn
            # scenario promises a ROTATING cluster, and store.list comes
            # back name-sorted, so a fixed [:count] slice would flap the
            # same first cluster forever
            n = len(clusters)
            victims = [clusters[(self._flap_rr + i) % n]
                       for i in range(min(spec.count, n))]
            self._flap_rr = (self._flap_rr + spec.count) % n
            for c in victims:
                name = c.metadata.name

                def flap_down(obj: Cluster) -> None:
                    summary = obj.status.resource_summary
                    if name not in self._flapped:
                        self._flapped[name] = dict(summary.allocatable)
                    summary.allocatable = {
                        k: Quantity.from_milli(int(v.milli * spec.scale))
                        for k, v in summary.allocatable.items()}

                store.mutate(Cluster.KIND, "", name, flap_down)
        elif spec.kind == "flap_up":
            # restore the longest-flapped clusters (insertion order),
            # wherever they sort in the store list — with rotation the
            # flapped set no longer lines up with clusters[:count]
            for name in list(self._flapped)[:spec.count]:

                def flap_up(obj: Cluster) -> None:
                    orig = self._flapped.pop(name, None)
                    if orig is not None:
                        obj.status.resource_summary.allocatable = orig

                store.mutate(Cluster.KIND, "", name, flap_up)

    def _next_pending_time(self) -> Optional[float]:
        t_arr = (self._arrivals[self._arr_idx]
                 if self._arr_idx < len(self._arrivals) else None)
        t_evt = (self._events[self._evt_idx][0]
                 if self._evt_idx < len(self._events) else None)
        if t_arr is None:
            return t_evt
        if t_evt is None:
            return t_arr
        return min(t_arr, t_evt)

    def _inject_due(self, limit_t: float) -> None:
        """Inject every arrival / cluster event with time <= limit_t,
        stepping the clock to each event's exact time first so queue
        timestamps (and therefore dwell) are exact."""
        while True:
            t = self._next_pending_time()
            if t is None or t > limit_t:
                return
            self.clock.advance_to(t)
            t_arr = (self._arrivals[self._arr_idx]
                     if self._arr_idx < len(self._arrivals) else None)
            if t_arr is not None and t_arr <= t:
                self._arr_idx += 1
                self._inject_binding(t)
            else:
                _, spec = self._events[self._evt_idx]
                self._evt_idx += 1
                self._apply_cluster_event(spec)
            # sample at inject time, not only after each cycle's pop: the
            # pre-cut peak (the depth the max_resident + one-batch ceiling
            # is actually about) is otherwise systematically missed
            self._sample_queue()

    # -- observation ---------------------------------------------------------
    def _on_store_event(self, event: Event) -> None:
        if event.kind != ResourceBinding.KIND or event.type == DELETED:
            return
        rb = event.obj
        key = (rb.metadata.namespace, rb.metadata.name)
        cond = next((c for c in rb.status.conditions
                     if c.type == COND_SCHEDULED), None)
        if cond is None:
            return
        scheduled = (cond.status == "True"
                     and rb.status.scheduler_observed_generation
                     == rb.metadata.generation)
        with self._lock:
            rec = self._flight.get(key)
            if rec is None:
                return
            if scheduled and not rec.done:
                rec.done = True
                rec.t_done = self.clock.now()
            elif cond.status == "False":
                rec.failed_attempts += 1

    def _sample_queue(self) -> None:
        state = self.plane.scheduler.queue_state()
        with self._lock:
            for q, d in state["depths"].items():
                if d > self._max_depth.get(q, 0):
                    self._max_depth[q] = d
            for q, a in state["oldest_age_s"].items():
                if a > self._max_oldest.get(q, 0.0):
                    self._max_oldest[q] = a
            if state["overload"]:
                self._overload_seen = True

    def admission_delta(self) -> Dict[str, int]:
        return {d: int(sched_metrics.ADMISSION.value(decision=d)
                       - self._base_admission.get(d, 0.0))
                for d in ("admitted", "shed", "displaced")}

    def flight_summary(self, include_latencies: bool = True) -> dict:
        """Aggregate flight state.  include_latencies=False skips the
        O(n log n) latency sort — the /debug/load snapshot path runs
        under the same lock the store-event callback takes, so a
        dashboard poll must not stall the serve plane's event path."""
        with self._lock:
            lat = (sorted(r.t_done - r.t_inject
                          for r in self._flight.values() if r.done)
                   if include_latencies else [])
            return {
                "injected": len(self._flight),
                "scheduled": sum(1 for r in self._flight.values() if r.done),
                "failed_attempts": sum(r.failed_attempts
                                       for r in self._flight.values()),
                "reschedules": sum(r.reschedules
                                   for r in self._flight.values()),
                "latencies_sorted": lat,
                "max_depth": dict(self._max_depth),
                "max_oldest_age_s": {k: round(v, 6) for k, v
                                     in self._max_oldest.items()},
                "overload_seen": self._overload_seen,
            }

    def snapshot(self) -> dict:
        """Live state for /debug/load (and the realtime progress view)."""
        fs = self.flight_summary(include_latencies=False)
        fs.pop("latencies_sorted", None)
        return {
            "enabled": True,
            "scenario": self.scenario.name,
            "realtime": self.realtime,
            "seed": self.seed,
            "t_s": round(self.clock.now() - self.t0, 3),
            "duration_s": round(self.duration_s, 3),
            "arrivals_injected": self._arr_idx,
            "arrivals_total": len(self._arrivals),
            "events_applied": self._evt_idx,
            "events_total": len(self._events),
            "admission": self.admission_delta(),
            "queue": self.plane.scheduler.queue_state(),
            **fs,
        }

    # -- compressed-mode execution -------------------------------------------
    def run(self) -> dict:
        """Run the whole scenario in compressed virtual time and return
        the SOAK report payload (loadgen/report.py)."""
        from karmada_tpu.loadgen import report

        self._install()
        try:
            while self._next_pending_time() is not None:
                self._inject_due(self._next_pending_time())
                self.plane.runtime.tick()
                self._sample_queue()
            self._drain()
            # rebalance convergence (hotspot -> drain -> re-place ->
            # converge): the paced drains create NEW scheduling work
            # after the arrival stream ends, so keep stepping rebalance
            # intervals until the detector reports nothing left to drain
            # and every eviction task has settled (or the round budget
            # runs out — the residual then shows in the report)
            reb = getattr(self.plane.scheduler, "rebalance_plane", None)
            if reb is not None and not self.realtime:
                for _ in range(64):
                    if reb.converged() and reb.pending_drains() == 0:
                        break
                    self.clock.advance(reb.cfg.interval_s)
                    self.plane.runtime.tick()
                    self._drain()
            if self._chaos:
                # chaos epilogue while the plane + rules are still armed:
                # deliver any still-held watch events (a stalled event
                # must not outlive the fault window), snapshot the fire
                # log, and run the safety auditor over the intact queues
                from karmada_tpu import chaos as chaos_mod

                flushed = self.plane.store.bus.flush_held()
                if flushed:
                    self.plane.runtime.tick()
                    self._drain()
                self.chaos_state = chaos_mod.state_payload()
                self.safety_audit = chaos_mod.audit_soak(
                    self, self._audit_baseline)
        finally:
            self._uninstall()
        return report.build_soak_report(self)

    def _drain(self, max_steps: int = 64) -> None:
        """Post-arrival convergence: step virtual time until the queue
        empties (or give up after max_steps — the residual is reported,
        never silently dropped).  The step starts at the batch deadline
        so end-of-run stragglers cut on their normal schedule instead of
        accruing a full synthetic second of dwell; only when a step
        makes no progress (entries waiting out backoff timers) does it
        escalate toward the backoff ceiling."""
        sched = self.plane.scheduler
        deadline = self.scenario.deadline_s(self.model)
        backoff_step = max(sched.queue.initial_backoff_s, 0.1)
        for _ in range(max_steps):
            state = sched.queue_state()
            if sum(state["depths"].values()) == 0:
                break
            if state["depths"]["active"] > 0 and deadline > 0:
                # a deferred straggler batch cuts when its OLDEST entry
                # hits the (possibly overload-widened) deadline: jump
                # exactly there (+epsilon), so only that one entry's
                # dwell touches the deadline — a blind stride would push
                # the whole batch past it and straight into the
                # reported p99
                eff = deadline * (sched.overload_deadline_factor
                                  if state["overload"] else 1.0)
                age = state["oldest_age_s"]["active"]
                step = max(eff - age, 0.0) + 1e-6
            else:
                # waiting out backoff/unschedulable timers: stride, and
                # escalate toward the backoff ceiling
                step = backoff_step
                backoff_step = min(backoff_step * 2,
                                   sched.queue.max_backoff_s)
            self.clock.advance(step)
            self.plane.runtime.tick()
            self._sample_queue()
        self.residual = sched.queue_state()["depths"]

    # -- realtime execution (serve --loadgen) --------------------------------
    def start(self) -> "LoadDriver":
        assert self.realtime, "start() is the realtime entry; use run()"
        self._install()
        self._thread = threading.Thread(target=self._run_realtime,
                                        daemon=True, name="loadgen-driver")
        self._thread.start()
        return self

    def _run_realtime(self) -> None:
        while not self._stop.is_set():
            t = self._next_pending_time()
            if t is None:
                break
            wait = t - self.clock.now()
            if wait > 0 and self._stop.wait(wait):
                break
            self._inject_due(self.clock.now())
            self._sample_queue()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._uninstall()
