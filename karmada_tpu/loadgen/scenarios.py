"""The loadgen scenario catalog.

A Scenario is a complete sustained-traffic experiment: an arrival shape
(as a multiple of the plane's measured solve capacity, so the same
scenario is meaningful on a laptop's serial backend and a TPU pod), a
cluster-event schedule (kills / revivals / capacity flaps at fractions
of the scenario duration), and the queue tuning it runs under
(batch_window, batch-formation deadline, admission bound).

Sizes are expressed relative to capacity rather than absolute seconds:

  * load_factor       mean arrival rate = load_factor x capacity, where
                      capacity = 1 / per_binding_s of the service model
                      (measured by bench --soak, fixed in tier-1 tests);
  * deadline_cycles   batch deadline = that many full-batch service
                      times (model.cost(batch_window));
  * admission_batches admission bound = that many batch_windows.

The compressed catalog entries are a few hundred bindings (tier-1
budget); *-heavy variants are the same shapes scaled up, marked slow.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Tuple

from karmada_tpu.loadgen import arrival


@dataclass(frozen=True)
class ClusterEventSpec:
    """One scheduled fleet event.  kinds:
    kill        delete `count` clusters and evict their placements (the
                failover storm: every affected binding reschedules)
    revive      recreate the most recently killed `count` clusters
    flap_down   scale `count` clusters' allocatable by `scale` (< 1)
    flap_up     restore flapped clusters to full capacity
    chaos       arm `spec` (karmada_tpu/chaos fault grammar) on the
                process-wide chaos plane — fault windows open here
    chaos_clear clear the chaos site named in `spec` (empty = all) —
                fault windows close here
    whatif      fire one facade capacity query (karmada_tpu/facade)
                against the live plane: `spec` names the query
                (placement | cluster-loss | headroom, default
                placement), `count` carries the replica count; answers
                accumulate on the driver's whatif_results and MUST
                leave live placements bit-identical
    """

    at_frac: float  # fraction of the scenario duration
    kind: str       # kill|revive|flap_down|flap_up|chaos|chaos_clear|whatif
    count: int = 1
    scale: float = 0.5
    spec: str = ""  # chaos fault spec / site / whatif query name


@dataclass(frozen=True)
class Scenario:
    name: str
    description: str
    n_bindings: int
    load_factor: float                  # mean arrival rate, x capacity
    shape: str = "steady"               # steady | diurnal | burst
    diurnal_amplitude: float = 0.0      # fraction of base rate
    diurnal_periods: float = 1.0        # sine periods over the duration
    burst_factor: float = 0.0           # burst-window rate, x capacity
    burst_start_frac: float = 0.0
    burst_end_frac: float = 0.0
    n_clusters: int = 6
    priority_high_frac: float = 0.1     # fraction injected at priority 10
    batch_window: int = 64
    deadline_cycles: float = 2.0        # batch deadline, full-batch costs
    admission_batches: float = 4.0      # admission bound, batch_windows
    events: Tuple[ClusterEventSpec, ...] = field(default_factory=tuple)
    slow: bool = False                  # heavy variant (excluded tier-1)
    # workload shape: "duplicated" places every binding on all feasible
    # clusters; "divided" (Divided + Aggregated) packs binding_replicas
    # into the fewest most-available clusters — the shape rebalance
    # drains act on (a duplicated re-solve would go right back)
    binding_style: str = "duplicated"
    binding_replicas: int = 1
    # policy-path mode (ROADMAP item 2 leftover): inject workloads as
    # Deployment templates matched by ONE PropagationPolicy, so the soak
    # exercises the detector/policy fan-out (template -> policy match ->
    # binding render) instead of creating ResourceBindings directly
    policy_path: bool = False
    # rebalance plane: cycle interval in full-batch service times
    # (model.cost(batch_window)); 0 leaves the plane disarmed
    rebalance_interval_cycles: float = 0.0
    # shortlist tier (ops/shortlist): top-k candidate lanes per binding
    # for the hierarchical two-tier solve; 0 keeps every chunk dense.
    # Device-backend slices only (the host backends never build
    # SolverBatches); the slice arms it with min_cells=0 so compressed
    # scales exercise the exact production tier-selection path
    shortlist_k: int = 0
    # group-affine fleet: clusters carry a region in `n_regions` groups
    # and each binding's placement targets ONE group — the million-user
    # shape (per-tenant affinity) whose eligible sets fit k
    n_regions: int = 0

    @property
    def chaotic(self) -> bool:
        """True when the schedule contains chaos fault events — the
        driver arms the chaos plane and the safety auditor runs."""
        return any(e.kind in ("chaos", "chaos_clear") for e in self.events)

    # -- derived quantities (given the service model's capacity) ------------
    def mean_rate(self, capacity_rate: float) -> float:
        """Expected arrivals/second over the whole run."""
        base = self.load_factor * capacity_rate
        if self.shape == "burst" and self.burst_factor > 0:
            wfrac = max(0.0, self.burst_end_frac - self.burst_start_frac)
            return (base * (1.0 - wfrac)
                    + self.burst_factor * capacity_rate * wfrac)
        return base  # the sine averages out over whole periods

    def duration_s(self, capacity_rate: float) -> float:
        """Virtual duration such that ~n_bindings arrive in expectation."""
        return self.n_bindings / max(self.mean_rate(capacity_rate), 1e-9)

    def rate_fn(self, capacity_rate: float, t0: float,
                duration: float) -> Tuple[arrival.RateFn, float]:
        """(rate function over absolute time, dominating max rate)."""
        base = self.load_factor * capacity_rate
        if self.shape == "diurnal":
            period = duration / max(self.diurnal_periods, 1e-9)
            fn = arrival.diurnal_rate(base, self.diurnal_amplitude,
                                      period, t0=t0)
            return fn, base * (1.0 + abs(self.diurnal_amplitude))
        if self.shape == "burst" and self.burst_factor > 0:
            burst = self.burst_factor * capacity_rate
            fn = arrival.burst_rate(base, burst,
                                    t0 + self.burst_start_frac * duration,
                                    t0 + self.burst_end_frac * duration)
            return fn, max(base, burst)
        return arrival.constant_rate(base), base

    def deadline_s(self, model) -> float:
        return self.deadline_cycles * model.cost(self.batch_window)

    def rebalance_interval_s(self, model) -> float:
        """Rebalance cycle interval on the virtual clock (0 = disarmed)."""
        return self.rebalance_interval_cycles * model.cost(self.batch_window)

    def admission_limit(self) -> int:
        return max(self.batch_window,
                   int(math.ceil(self.admission_batches * self.batch_window)))


def _churn_events(flaps: int, count: int = 1,
                  scale: float = 0.4) -> Tuple[ClusterEventSpec, ...]:
    """Alternating capacity flaps spread across the run: down at odd
    slots, restored at the following even slot."""
    out = []
    for i in range(flaps):
        frac = (i + 1) / (flaps + 1)
        kind = "flap_down" if i % 2 == 0 else "flap_up"
        out.append(ClusterEventSpec(at_frac=frac, kind=kind, count=count,
                                    scale=scale))
    return tuple(out)


SCENARIOS: Dict[str, Scenario] = {s.name: s for s in (
    # no-overload steady state: the SLO reference point — sheds nothing,
    # p99 dwell under the deadline (asserted by the soak tests and the
    # bench acceptance run).  deadline_cycles 6 keeps the deadline well
    # above the ~2-cycle batch fill time at this load: cuts are full
    # batches except genuine stragglers, and a deadline-cut batch's
    # oldest dwell IS the deadline by construction, so the SLO only
    # holds when such cuts are rare — i.e. the deadline needs headroom.
    Scenario(
        name="steady",
        description="steady Poisson at 0.5x solve capacity, quiet fleet",
        n_bindings=320, load_factor=0.5, deadline_cycles=6.0,
    ),
    # diurnal sine: peaks briefly above capacity (1.08x), troughs near
    # idle — exercises deadline-triggered trickle batching at the trough
    # and queue growth + catch-up at the peak
    Scenario(
        name="diurnal",
        description="diurnal sine, mean 0.6x capacity, peak 1.08x",
        n_bindings=360, load_factor=0.6, deadline_cycles=6.0,
        shape="diurnal", diurnal_amplitude=0.8, diurnal_periods=1.0,
    ),
    # failover storm: a third in, arrivals burst to 2x capacity while two
    # clusters die (their placements evict and reschedule); the admission
    # gate must shed the excess and keep depth bounded.  The tight
    # deadline (0.5 cycles) makes the pre-storm phase trickle-batch so
    # plenty of placements exist to evict when the kill lands, and the
    # small admission bound (2 batch_windows) forces real shedding.
    Scenario(
        name="storm",
        description="failover storm: 2x-capacity arrival burst + 2 "
                    "cluster kills, revived later",
        n_bindings=600, load_factor=0.5,
        deadline_cycles=0.5, admission_batches=2.0,
        shape="burst", burst_factor=2.0,
        burst_start_frac=0.4, burst_end_frac=0.65,
        events=(
            ClusterEventSpec(at_frac=0.4, kind="kill", count=2),
            ClusterEventSpec(at_frac=0.8, kind="revive", count=2),
        ),
    ),
    # cluster churn: capacity flaps every ~14% of the run — every flap is
    # a Cluster event, i.e. a full unschedulable-requeue + store rescan,
    # the most expensive control-plane reaction per event
    Scenario(
        name="churn",
        description="capacity flaps on a rotating cluster under 0.6x "
                    "steady load",
        n_bindings=360, load_factor=0.6, deadline_cycles=6.0,
        events=_churn_events(flaps=6, count=1, scale=0.4),
    ),
    # the compressed chaos soak (ISSUE 8 acceptance shape): storm-grade
    # arrivals + a cluster kill/revive, an estimator outage window (the
    # circuit must open, then half-open-recover after the clear), one
    # mid-cycle device fault of each flavor (a hang that degrades the
    # backend — which must re-arm — and a dispatch raise that the cycle
    # containment re-queues), and one resident-mirror corruption (the
    # forced parity audit must rebuild bit-exact).  Event order matters:
    # the hang lands while the estimator outage is still open (failures
    # overlap), and the corruption waits until the backend has had its
    # recovery cooldown.  Run it with ServeSlice(backend="device",
    # resident=True, device_cycle_timeout_s=..., device_recover_cycles=..)
    # — bench.py --chaos and tests/test_chaos.py both do.
    Scenario(
        name="chaos",
        description="failure storm: 1.5x burst + kill/revive + estimator "
                    "outage + device hang/raise + resident corruption",
        n_bindings=420, load_factor=0.5,
        deadline_cycles=1.0, admission_batches=3.0,
        shape="burst", burst_factor=1.5,
        burst_start_frac=0.3, burst_end_frac=0.55,
        events=(
            ClusterEventSpec(at_frac=0.2, kind="chaos",
                             spec="estimator.rpc:error"),
            ClusterEventSpec(at_frac=0.3, kind="kill", count=1),
            ClusterEventSpec(at_frac=0.35, kind="chaos",
                             spec="device.cycle:hang:3#1"),
            ClusterEventSpec(at_frac=0.5, kind="chaos_clear",
                             spec="estimator.rpc"),
            ClusterEventSpec(at_frac=0.6, kind="revive", count=1),
            ClusterEventSpec(at_frac=0.75, kind="chaos",
                             spec="resident.mirror:corrupt#1"),
            ClusterEventSpec(at_frac=0.85, kind="chaos",
                             spec="device.dispatch:raise#1"),
        ),
    ),
    # what-if isolation proof: steady traffic with facade capacity
    # queries fired mid-soak (one of each kind, twice over).  Every
    # query runs a DETACHED solve on a copy-on-write fork of live
    # state, so the acceptance check is brutal and simple: the final
    # placement map must be bit-identical to a control run with the
    # whatif events stripped (tests/test_facade.py proves it).
    Scenario(
        name="whatif",
        description="steady 0.5x load with facade what-if capacity "
                    "queries riding the soak; placements must not move",
        n_bindings=320, load_factor=0.5, deadline_cycles=6.0,
        binding_style="divided", binding_replicas=2,
        events=(
            ClusterEventSpec(at_frac=0.3, kind="whatif", count=50,
                             spec="placement"),
            ClusterEventSpec(at_frac=0.4, kind="whatif", count=8,
                             spec="headroom"),
            ClusterEventSpec(at_frac=0.5, kind="whatif", count=16,
                             spec="cluster-loss"),
            ClusterEventSpec(at_frac=0.7, kind="whatif", count=200,
                             spec="placement"),
            ClusterEventSpec(at_frac=0.8, kind="whatif", count=4,
                             spec="headroom"),
        ),
    ),
    # hotspot (ISSUE 10 rebalance acceptance shape): 4 of 6 clusters
    # start capacity-crushed, so the Divided+Aggregated workload packs
    # onto the 2 "hot" survivors (skewed arrivals).  Then capacity
    # churn: the cold 4 restore AND the hot 2 flap down — placements
    # that were fine are now overcommitted, the exact situation the
    # scheduler never revisits and the rebalance plane exists for.  The
    # plane must drain the hot clusters to within the overcommit
    # threshold (paced by the shared eviction budget), re-place victims
    # through the normal queue with origin=rebalance, and converge with
    # zero conservation violations.  Workloads flow through the
    # detector/policy path (one PropagationPolicy matches every injected
    # Deployment), and one chaos rebalance.plan:skip fault proves the
    # seam + auditor accountability.
    Scenario(
        name="hotspot",
        description="skewed arrivals pack 2 hot clusters, capacity churn "
                    "overcommits them; rebalance drains + re-places",
        n_bindings=160, load_factor=0.5, deadline_cycles=2.0,
        n_clusters=6,
        binding_style="divided", binding_replicas=3,
        policy_path=True,
        rebalance_interval_cycles=2.0,
        events=(
            ClusterEventSpec(at_frac=0.0, kind="flap_down", count=4,
                             scale=0.05),
            ClusterEventSpec(at_frac=0.55, kind="flap_up", count=4),
            ClusterEventSpec(at_frac=0.6, kind="flap_down", count=2,
                             scale=0.1),
            ClusterEventSpec(at_frac=0.75, kind="chaos",
                             spec="rebalance.plan:skip#1"),
        ),
    ),
    # million-binding shape at compressed scale: a group-affine fleet
    # (each binding's affinity targets one region, so eligible sets fit
    # the shortlist k) under the hierarchical two-tier solve — the
    # production tier-selection path end-to-end on the virtual clock.
    # Device-backend slices only (bench --megafleet and the shortlist
    # soak test drive it with backend="device").
    Scenario(
        name="megafleet",
        description="group-affine fleet under the two-tier shortlist "
                    "solve: per-region affinity bindings, steady Poisson",
        n_bindings=320, load_factor=0.5, deadline_cycles=6.0,
        n_clusters=48, n_regions=8, shortlist_k=8,
        binding_style="divided", binding_replicas=3,
        batch_window=64,
    ),
    Scenario(
        name="megafleet-heavy",
        description="group-affine two-tier solve at production-shaped "
                    "counts",
        n_bindings=20000, load_factor=0.6, deadline_cycles=4.0,
        n_clusters=512, n_regions=32, shortlist_k=32,
        binding_style="divided", binding_replicas=5,
        batch_window=512,
        slow=True,
    ),
    # heavy variants: same shapes, production-shaped counts; marked slow
    # (bench --soak and the opt-in slow tests run them)
    Scenario(
        name="storm-heavy",
        description="failover storm at 5000 bindings",
        n_bindings=5000, load_factor=0.5,
        deadline_cycles=0.5, admission_batches=2.0,
        shape="burst", burst_factor=2.0,
        burst_start_frac=0.4, burst_end_frac=0.65,
        n_clusters=16, batch_window=256,
        events=(
            ClusterEventSpec(at_frac=0.4, kind="kill", count=4),
            ClusterEventSpec(at_frac=0.8, kind="revive", count=4),
        ),
        slow=True,
    ),
    Scenario(
        name="diurnal-heavy",
        description="diurnal sine at 5000 bindings, two periods",
        n_bindings=5000, load_factor=0.6, deadline_cycles=6.0,
        shape="diurnal", diurnal_amplitude=0.8, diurnal_periods=2.0,
        n_clusters=16, batch_window=256,
        slow=True,
    ),
)}


def get_scenario(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; available: "
            f"{', '.join(sorted(SCENARIOS))}") from None
