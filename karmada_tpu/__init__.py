"""karmada-tpu: a TPU-native multi-cluster orchestration control plane.

A brand-new framework with the capabilities of the karmada reference
(Kubernetes multi-cluster orchestration: CRD-style data model, watch/reconcile
controllers, a scheduler, capacity estimators), redesigned TPU-first: the
replica-assignment hot path (filter/score/spread/divide, reference
`pkg/scheduler/core/generic_scheduler.go:71-116`) runs as one batched,
vmapped JAX program over dense (bindings x clusters) tensors on TPU,
instead of one serial Go loop per binding.

Layout (mirrors SURVEY.md layer map):
  models/    L0 API data model (Cluster, PropagationPolicy, ResourceBinding, Work, ...)
  store/     L0 object store + watch bus (etcd/apiserver semantics, in-proc)
  ops/       solver kernels: serial golden path (numpy) + batched TPU path (JAX)
  parallel/  device mesh, sharding, batching/padding discipline
  scheduler/ L4 scheduling service (queues, batch window, patch-back)
  estimator/ L4 capacity estimation (general math + accurate per-node tier)
  interpreter/ L2 resource interpreter (GetReplicas/ReviseReplica/...)
  controllers/ L3 propagation loop (detector, binding, execution, status, ...)
  utils/     quantities, interning, workers
"""

__version__ = "0.1.0"
