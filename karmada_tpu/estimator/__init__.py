from karmada_tpu.estimator.general import GeneralEstimator, UNAUTHENTIC_REPLICA  # noqa: F401
