"""Estimator clients: the scheduler side of the capacity protocol.

Mirrors reference pkg/estimator/client: the `ReplicaEstimator` /
`UnschedulableReplicaEstimator` interfaces (interface.go:39-70), the
accurate gRPC client with per-cluster fan-out (accurate.go:55-170 --
getClusterReplicasConcurrently), the UNAUTHENTIC_REPLICA=-1 sentinel for
clusters without an estimator endpoint, and the registry the scheduler
min-merges across (serial.make_cal_available).

Beyond the reference: SnapshotEstimator pulls each estimator's whole
free-capacity table (CapacitySnapshot) on a refresh interval and answers
MaxAvailableReplicas locally -- per-binding RPCs collapse to one snapshot
fetch per cluster per cycle, which is what lets the batched TPU solver
evaluate 100k bindings without 100k x clusters network calls.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple

from karmada_tpu import obs
from karmada_tpu.utils.metrics import REGISTRY
from karmada_tpu.estimator.wire import (
    CapacitySnapshotResponse,
    MaxAvailableReplicasRequest,
    MaxAvailableReplicasResponse,
    Transport,
    UNAUTHENTIC_REPLICA,
    UnschedulableReplicasRequest,
    UnschedulableReplicasResponse,
    replicas_on_node,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.work import ReplicaRequirements, TargetCluster

RPC_SKIPPED = REGISTRY.counter(
    "karmada_estimator_rpc_skipped_total",
    "Per-cluster estimator RPCs short-circuited because the cluster's "
    "observed resourceVersion and the request signature were unchanged "
    "since the previous cycle (the memoized answer served instead)",
    ("method",),
)


def _rpc_span(cluster: str, method: str):
    """An "estimator.rpc" span under the ambient trace, or the no-op span
    when tracing is off OR no trace is active: a per-cluster RPC outside
    any cycle/reconcile (e.g. a periodic-hook fan-out) must not mint
    hundreds of single-span root traces and flood the bounded ring."""
    tracer = obs.TRACER
    if not tracer.enabled or tracer.current() is None:
        return obs.NOOP_SPAN
    return tracer.span(obs.SPAN_ESTIMATOR_RPC, cluster=cluster,
                       method=method)


def _traced_map(pool: ThreadPoolExecutor, fn, clusters: List[Cluster],
                method: str) -> list:
    """pool.map with flight-recorder spans: each per-cluster RPC runs
    under an "estimator.rpc" span, parented (across the pool's thread
    boundary) into whatever trace the calling thread was inside — the
    scheduler cycle, a descheduler reconcile.  Disabled tracing, or a
    call with no ambient trace, takes the plain pool.map path."""
    tracer = obs.TRACER
    parent = tracer.current() if tracer.enabled else None
    if parent is None:
        return list(pool.map(fn, clusters))

    def traced_one(cluster: Cluster):
        with tracer.attach(parent):
            with tracer.span(obs.SPAN_ESTIMATOR_RPC, cluster=cluster.name,
                             method=method):
                return fn(cluster)

    return list(pool.map(traced_one, clusters))


class AccurateEstimatorClient:
    """Per-cluster RPC fan-out (accurate.go): one transport per member."""

    def __init__(self, max_workers: int = 16, timeout_replicas: int = UNAUTHENTIC_REPLICA) -> None:
        self.transports: Dict[str, Transport] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._timeout_replicas = timeout_replicas
        self._memo_lock = threading.Lock()
        # guarded-by: _memo_lock — per (method, cluster): the cluster
        # resourceVersion the memoized answers were observed at, and the
        # successful answers keyed by request signature.  A cluster whose
        # rv is unchanged since the last cycle re-serves the memo instead
        # of refetching (karmada_estimator_rpc_skipped_total); any rv
        # move drops the whole entry.  Only SUCCESSFUL responses memoize
        # — an unreachable estimator must be retried next call, not
        # pinned UNAUTHENTIC until the cluster happens to churn.  Each
        # entry holds at most _MEMO_CAP signatures (a stable cluster
        # with a diverse workload mix must not grow the scheduler
        # process unboundedly); overflow drops the oldest insertions.
        self._memo: Dict[Tuple[str, str], Tuple[int, Dict[str, int]]] = {}

    #: per-(method, cluster) signature cap for the rv-keyed RPC memo
    _MEMO_CAP = 256

    def register(self, cluster: str, transport: Transport) -> None:
        self.transports[cluster] = transport

    def deregister(self, cluster: str) -> None:
        t = self.transports.pop(cluster, None)
        if t is not None:
            t.close()
        with self._memo_lock:
            for key in [k for k in self._memo if k[1] == cluster]:
                del self._memo[key]

    # -- rv-keyed RPC memo ---------------------------------------------------
    @staticmethod
    def _req_sig(payload: dict) -> str:
        return json.dumps(payload, sort_keys=True, default=str)

    def _memo_get(self, method: str, cluster: Cluster,
                  sig: str) -> Optional[int]:
        rv = cluster.metadata.resource_version
        with self._memo_lock:
            entry = self._memo.get((method, cluster.name))
            if entry is None or entry[0] != rv:
                return None
            answer = entry[1].get(sig)
        if answer is not None:
            RPC_SKIPPED.inc(method=method)
        return answer

    def _memo_put(self, method: str, cluster: Cluster, sig: str,
                  answer: int) -> None:
        rv = cluster.metadata.resource_version
        with self._memo_lock:
            entry = self._memo.get((method, cluster.name))
            if entry is None or entry[0] != rv:
                entry = (rv, {})
                self._memo[(method, cluster.name)] = entry
            answers = entry[1]
            while len(answers) >= self._MEMO_CAP:
                answers.pop(next(iter(answers)))  # oldest insertion
            answers[sig] = answer

    # -- ReplicaEstimator ----------------------------------------------------
    def max_available_replicas(
        self,
        clusters: List[Cluster],
        requirements: Optional[ReplicaRequirements],
    ) -> List[TargetCluster]:
        # the memo key already carries the cluster name, so the request
        # signature is computed ONCE per call from a name-free template
        # instead of one json.dumps per cluster on the fan-out hot path
        sig = self._req_sig(
            MaxAvailableReplicasRequest.from_requirements(
                "", requirements).to_json())

        def one(cluster: Cluster) -> TargetCluster:
            transport = self.transports.get(cluster.name)
            if transport is None:
                return TargetCluster(cluster.name, UNAUTHENTIC_REPLICA)
            req = MaxAvailableReplicasRequest.from_requirements(
                cluster.name, requirements
            )
            payload = req.to_json()
            cached = self._memo_get("MaxAvailableReplicas", cluster, sig)
            if cached is not None:
                return TargetCluster(cluster.name, cached)
            try:
                resp = MaxAvailableReplicasResponse.from_json(
                    transport.call("MaxAvailableReplicas", payload)
                )
                self._memo_put("MaxAvailableReplicas", cluster, sig,
                               resp.max_replicas)
                return TargetCluster(cluster.name, resp.max_replicas)
            except Exception:  # noqa: BLE001 -- unreachable estimator
                return TargetCluster(cluster.name, self._timeout_replicas)

        return _traced_map(self._pool, one, clusters,
                           "MaxAvailableReplicas")

    def max_available_component_sets(
        self, clusters: List[Cluster], components
    ) -> List[TargetCluster]:
        """MaxAvailableComponentSets fan-out (estimation.go:66-103 client
        side): unreachable/unregistered estimators answer UNAUTHENTIC."""
        from karmada_tpu.estimator.wire import (
            MaxAvailableComponentSetsRequest,
            MaxAvailableComponentSetsResponse,
        )

        # name-free signature computed once per call (see
        # max_available_replicas)
        sig = self._req_sig(
            MaxAvailableComponentSetsRequest.from_components(
                "", components).to_json())

        def one(cluster: Cluster) -> TargetCluster:
            transport = self.transports.get(cluster.name)
            if transport is None:
                return TargetCluster(cluster.name, UNAUTHENTIC_REPLICA)
            req = MaxAvailableComponentSetsRequest.from_components(
                cluster.name, components
            )
            payload = req.to_json()
            cached = self._memo_get("MaxAvailableComponentSets", cluster,
                                    sig)
            if cached is not None:
                return TargetCluster(cluster.name, cached)
            try:
                resp = MaxAvailableComponentSetsResponse.from_json(
                    transport.call("MaxAvailableComponentSets", payload)
                )
                self._memo_put("MaxAvailableComponentSets", cluster, sig,
                               resp.max_sets)
                return TargetCluster(cluster.name, resp.max_sets)
            except Exception:  # noqa: BLE001 -- unreachable estimator
                return TargetCluster(cluster.name, self._timeout_replicas)

        return _traced_map(self._pool, one, clusters,
                           "MaxAvailableComponentSets")

    # -- UnschedulableReplicaEstimator --------------------------------------
    def unschedulable_replicas(
        self, cluster: str, kind: str, namespace: str, name: str
    ) -> int:
        transport = self.transports.get(cluster)
        if transport is None:
            return UNAUTHENTIC_REPLICA
        req = UnschedulableReplicasRequest(
            cluster=cluster, resource_kind=kind, namespace=namespace, name=name
        )
        try:
            with _rpc_span(cluster, "GetUnschedulableReplicas"):
                resp = UnschedulableReplicasResponse.from_json(
                    transport.call("GetUnschedulableReplicas", req.to_json())
                )
            return resp.unschedulable_replicas
        except Exception:  # noqa: BLE001
            return UNAUTHENTIC_REPLICA


class SnapshotEstimator:
    """Capacity-tensor shipping: refresh per-cluster node-free tables and
    answer MaxAvailableReplicas locally (no per-call RPC)."""

    def __init__(self, client: AccurateEstimatorClient,
                 refresh_interval_s: float = 5.0,
                 max_age_s: Optional[float] = None) -> None:
        self.client = client
        self.refresh_interval_s = refresh_interval_s
        # a snapshot older than this is stale: fall back to UNAUTHENTIC so a
        # dead/deregistered estimator cannot keep advertising capacity
        self.max_age_s = max_age_s if max_age_s is not None else 6 * refresh_interval_s
        self._snapshots: Dict[str, CapacitySnapshotResponse] = {}
        self._fetched_at: Dict[str, float] = {}
        self._lock = threading.Lock()

    def refresh(self, cluster: str, force: bool = False) -> None:
        transport = self.client.transports.get(cluster)
        if transport is None:
            return
        with self._lock:
            last = self._fetched_at.get(cluster, 0.0)
            if not force and time.time() - last < self.refresh_interval_s:
                return
        try:
            with _rpc_span(cluster, "CapacitySnapshot"):
                snap = CapacitySnapshotResponse.from_json(
                    transport.call("CapacitySnapshot", {})
                )
        except Exception:  # noqa: BLE001
            return
        with self._lock:
            self._snapshots[cluster] = snap
            self._fetched_at[cluster] = time.time()

    def _fresh_snapshot(self, cluster_name: str) -> Optional[CapacitySnapshotResponse]:
        """The current snapshot, or None when it is absent/stale or the
        estimator endpoint is gone (callers answer UNAUTHENTIC)."""
        self.refresh(cluster_name)
        with self._lock:
            snap = self._snapshots.get(cluster_name)
            age = time.time() - self._fetched_at.get(cluster_name, 0.0)
        if cluster_name not in self.client.transports:
            return None
        if snap is None or age > self.max_age_s:
            return None
        return snap

    def max_available_replicas(
        self,
        clusters: List[Cluster],
        requirements: Optional[ReplicaRequirements],
    ) -> List[TargetCluster]:
        out: List[TargetCluster] = []
        for cluster in clusters:
            snap = self._fresh_snapshot(cluster.name)
            if snap is None:
                out.append(TargetCluster(cluster.name, UNAUTHENTIC_REPLICA))
                continue
            total = 0
            for i, f in enumerate(snap.node_free):
                labels = snap.node_labels[i] if i < len(snap.node_labels) else {}
                total += replicas_on_node(f, labels, requirements)
            out.append(TargetCluster(cluster.name, total))
        return out

    def max_available_component_sets(
        self, clusters: List[Cluster], components
    ) -> List[TargetCluster]:
        """Component-set capacity from the shipped free table (pool-level,
        same bound as AccurateEstimatorServer, via the shared
        wire.max_sets_from_free_table)."""
        from karmada_tpu.estimator.wire import max_sets_from_free_table

        out: List[TargetCluster] = []
        for cluster in clusters:
            snap = self._fresh_snapshot(cluster.name)
            if snap is None:
                out.append(TargetCluster(cluster.name, UNAUTHENTIC_REPLICA))
                continue
            out.append(TargetCluster(
                cluster.name, max_sets_from_free_table(snap.node_free, components)
            ))
        return out
