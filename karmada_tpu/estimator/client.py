"""Estimator clients: the scheduler side of the capacity protocol.

Mirrors reference pkg/estimator/client: the `ReplicaEstimator` /
`UnschedulableReplicaEstimator` interfaces (interface.go:39-70), the
accurate gRPC client with per-cluster fan-out (accurate.go:55-170 --
getClusterReplicasConcurrently), the UNAUTHENTIC_REPLICA=-1 sentinel for
clusters without an estimator endpoint, and the registry the scheduler
min-merges across (serial.make_cal_available).

Beyond the reference: SnapshotEstimator pulls each estimator's whole
free-capacity table (CapacitySnapshot) on a refresh interval and answers
MaxAvailableReplicas locally -- per-binding RPCs collapse to one snapshot
fetch per cluster per cycle, which is what lets the batched TPU solver
evaluate 100k bindings without 100k x clusters network calls.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu import chaos, obs
from karmada_tpu.utils.locks import VetLock
from karmada_tpu.utils.metrics import REGISTRY
from karmada_tpu.estimator.wire import (
    CapacitySnapshotResponse,
    MaxAvailableReplicasRequest,
    MaxAvailableReplicasResponse,
    Transport,
    UNAUTHENTIC_REPLICA,
    UnschedulableReplicasRequest,
    UnschedulableReplicasResponse,
    replicas_on_node,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.work import ReplicaRequirements, TargetCluster

RPC_SKIPPED = REGISTRY.counter(
    "karmada_estimator_rpc_skipped_total",
    "Per-cluster estimator RPCs short-circuited because the cluster's "
    "observed resourceVersion and the request signature were unchanged "
    "since the previous cycle (the memoized answer served instead)",
    ("method",),
)

ESTIMATOR_ERRORS = REGISTRY.counter(
    "karmada_estimator_errors_total",
    "Estimator RPC failures by typed classification (unreachable / "
    "timeout / malformed per attempt, circuit_open per short-circuited "
    "call) — a dead estimator is no longer indistinguishable from a "
    "full cluster",
    ("kind",),
)

ESTIMATOR_RETRIES = REGISTRY.counter(
    "karmada_estimator_retries_total",
    "Estimator RPC retry attempts (bounded, full-jitter exponential "
    "backoff) by method",
    ("method",),
)

CIRCUIT_STATE = REGISTRY.gauge(
    "karmada_estimator_circuit_state",
    "Per-cluster estimator circuit-breaker state "
    "(0 = closed, 1 = open, 2 = half-open)",
    ("cluster",),
)

CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "karmada_estimator_circuit_transitions_total",
    "Estimator circuit-breaker state transitions by target state",
    ("to",),
)


# -- typed error classification ----------------------------------------------
class EstimatorError(Exception):
    """Base of the typed estimator failure taxonomy; `kind` is the
    karmada_estimator_errors_total label."""

    kind = "unreachable"


class EstimatorUnreachable(EstimatorError):
    kind = "unreachable"


class EstimatorTimeout(EstimatorError):
    kind = "timeout"


class EstimatorMalformed(EstimatorError):
    kind = "malformed"


class EstimatorCircuitOpen(EstimatorError):
    kind = "circuit_open"


def classify_exception(exc: BaseException) -> EstimatorError:
    """Map a raw transport/parse failure onto the typed taxonomy.
    TimeoutError first: socket.timeout IS a TimeoutError which IS an
    OSError, so the order of these checks is the classification."""
    if isinstance(exc, EstimatorError):
        return exc
    if isinstance(exc, TimeoutError):
        return EstimatorTimeout(str(exc))
    if isinstance(exc, (ConnectionError, OSError)):
        return EstimatorUnreachable(str(exc))
    # ValueError/TypeError/KeyError/AttributeError from response parsing,
    # json decode faults, and RuntimeError (a server-serialized error
    # frame): the endpoint answered but the reply could not be used
    return EstimatorMalformed(f"{type(exc).__name__}: {exc}")


# -- per-cluster circuit breaker ----------------------------------------------
CIRCUIT_CLOSED = "closed"
CIRCUIT_OPEN = "open"
CIRCUIT_HALF_OPEN = "half-open"
_CIRCUIT_VALUE = {CIRCUIT_CLOSED: 0.0, CIRCUIT_OPEN: 1.0,
                  CIRCUIT_HALF_OPEN: 2.0}


class CircuitBreaker:
    """Classic closed -> open -> half-open breaker, one circuit per
    member cluster: `failure_threshold` consecutive failed CALLS (each
    already retried) open the circuit; while open every call
    short-circuits to the sentinel without touching the wire; after
    `reset_timeout_s` ONE probe call is allowed through (half-open) —
    success closes the circuit, failure re-opens it for another full
    timeout.  `clock` is injectable so compressed-time soaks drive the
    open-window on the loadgen virtual clock."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock if clock is not None else time.monotonic
        self._lock = VetLock("estimator.breaker")
        self._state: Dict[str, str] = {}  # guarded-by: _lock
        self._failures: Dict[str, int] = {}  # guarded-by: _lock
        self._opened_at: Dict[str, float] = {}  # guarded-by: _lock
        self._probing: set = set()  # guarded-by: _lock
        # guarded-by: _lock — bounded transition log (soak reporting)
        self.transitions: deque = deque(maxlen=256)

    def _set(self, cluster: str, state: str) -> None:
        """Transition (call under _lock); metrics + log on real moves."""
        # the armed runtime detector turns the static waivers below into
        # an enforced precondition: off-lock callers raise loudly
        self._lock.require_held("CircuitBreaker._set")
        prev = self._state.get(cluster, CIRCUIT_CLOSED)
        if prev == state:
            return
        # vet: ignore[guarded-by] _set is a helper invoked only under _lock (require_held-enforced at runtime)
        self._state[cluster] = state
        # vet: ignore[guarded-by] _set is a helper invoked only under _lock (require_held-enforced at runtime)
        self.transitions.append({"cluster": cluster, "from": prev,
                                 "to": state, "ts": self.clock()})
        CIRCUIT_STATE.set(_CIRCUIT_VALUE[state], cluster=cluster)
        CIRCUIT_TRANSITIONS.inc(to=state)

    def allow(self, cluster: str) -> bool:
        """May a call to this cluster's estimator proceed?  Handles the
        open->half-open transition; in half-open only one probe flies."""
        with self._lock:
            state = self._state.get(cluster, CIRCUIT_CLOSED)
            if state == CIRCUIT_CLOSED:
                return True
            if state == CIRCUIT_OPEN:
                if (self.clock() - self._opened_at.get(cluster, 0.0)
                        >= self.reset_timeout_s):
                    self._set(cluster, CIRCUIT_HALF_OPEN)
                    self._probing.add(cluster)
                    return True
                return False
            # half-open: exactly one in-flight probe
            if cluster in self._probing:
                return False
            self._probing.add(cluster)
            return True

    def record_success(self, cluster: str) -> None:
        with self._lock:
            self._probing.discard(cluster)
            self._failures[cluster] = 0
            self._set(cluster, CIRCUIT_CLOSED)

    def record_failure(self, cluster: str) -> None:
        with self._lock:
            self._probing.discard(cluster)
            state = self._state.get(cluster, CIRCUIT_CLOSED)
            if state in (CIRCUIT_HALF_OPEN, CIRCUIT_OPEN):
                # a failed probe re-opens for another full timeout
                self._opened_at[cluster] = self.clock()
                self._set(cluster, CIRCUIT_OPEN)
                return
            n = self._failures.get(cluster, 0) + 1
            self._failures[cluster] = n
            if n >= self.failure_threshold:
                self._opened_at[cluster] = self.clock()
                self._set(cluster, CIRCUIT_OPEN)

    def forget(self, cluster: str) -> None:
        with self._lock:
            self._state.pop(cluster, None)
            self._failures.pop(cluster, None)
            self._opened_at.pop(cluster, None)
            self._probing.discard(cluster)
        CIRCUIT_STATE.set(0.0, cluster=cluster)

    def state(self, cluster: str) -> str:
        with self._lock:
            return self._state.get(cluster, CIRCUIT_CLOSED)

    def states(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._state)

    def transition_log(self) -> List[dict]:
        with self._lock:
            return list(self.transitions)


def _rpc_span(cluster: str, method: str):
    """An "estimator.rpc" span under the ambient trace, or the no-op span
    when tracing is off OR no trace is active: a per-cluster RPC outside
    any cycle/reconcile (e.g. a periodic-hook fan-out) must not mint
    hundreds of single-span root traces and flood the bounded ring."""
    tracer = obs.TRACER
    if not tracer.enabled or tracer.current() is None:
        return obs.NOOP_SPAN
    return tracer.span(obs.SPAN_ESTIMATOR_RPC, cluster=cluster,
                       method=method)


def _traced_map(pool: ThreadPoolExecutor, fn, clusters: List[Cluster],
                method: str) -> list:
    """pool.map with flight-recorder spans: each per-cluster RPC runs
    under an "estimator.rpc" span, parented (across the pool's thread
    boundary) into whatever trace the calling thread was inside — the
    scheduler cycle, a descheduler reconcile.  Disabled tracing, or a
    call with no ambient trace, takes the plain pool.map path."""
    tracer = obs.TRACER
    parent = tracer.current() if tracer.enabled else None
    if parent is None:
        return list(pool.map(fn, clusters))

    def traced_one(cluster: Cluster):
        with tracer.attach(parent):
            with tracer.span(obs.SPAN_ESTIMATOR_RPC, cluster=cluster.name,
                             method=method):
                return fn(cluster)

    return list(pool.map(traced_one, clusters))


class AccurateEstimatorClient:
    """Per-cluster RPC fan-out (accurate.go): one transport per member.

    Every wire call runs through the hardened path: the per-cluster
    circuit breaker gates it (open circuits short-circuit to the
    sentinel without touching the network), transient failures retry
    with bounded full-jitter exponential backoff (`retry_attempts`
    total tries; full jitter de-synchronizes the per-cluster pool
    threads after a shared-dependency blip), and every failure is
    CLASSIFIED — unreachable / timeout / malformed — into
    karmada_estimator_errors_total before the UNAUTHENTIC sentinel
    keeps the solver's answer total.  `sleep`/`clock` are injectable so
    compressed-time soaks never wall-sleep and drive the breaker's
    open-window on the loadgen virtual clock."""

    def __init__(self, max_workers: int = 16,
                 timeout_replicas: int = UNAUTHENTIC_REPLICA,
                 breaker: Optional[CircuitBreaker] = None,
                 retry_attempts: int = 3,
                 retry_base_s: float = 0.02,
                 retry_cap_s: float = 0.25,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.transports: Dict[str, Transport] = {}
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._timeout_replicas = timeout_replicas
        self.breaker = (breaker if breaker is not None
                        else CircuitBreaker(clock=clock))
        self.retry_attempts = max(1, retry_attempts)
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        self._sleep = sleep
        # deterministic jitter stream (replayable soaks)
        self._retry_rng = random.Random(0xC1A05)
        self._memo_lock = VetLock("estimator.memo")
        # guarded-by: _memo_lock — per (method, cluster): the cluster
        # resourceVersion the memoized answers were observed at, and the
        # successful answers keyed by request signature.  A cluster whose
        # rv is unchanged since the last cycle re-serves the memo instead
        # of refetching (karmada_estimator_rpc_skipped_total); any rv
        # move drops the whole entry.  Only SUCCESSFUL responses memoize
        # — an unreachable estimator must be retried next call, not
        # pinned UNAUTHENTIC until the cluster happens to churn.  Each
        # entry holds at most _MEMO_CAP signatures (a stable cluster
        # with a diverse workload mix must not grow the scheduler
        # process unboundedly); overflow drops the oldest insertions.
        self._memo: Dict[Tuple[str, str], Tuple[int, Dict[str, int]]] = {}

    #: per-(method, cluster) signature cap for the rv-keyed RPC memo
    _MEMO_CAP = 256

    def register(self, cluster: str, transport: Transport) -> None:
        self.transports[cluster] = transport

    def deregister(self, cluster: str) -> None:
        t = self.transports.pop(cluster, None)
        if t is not None:
            t.close()
        self.breaker.forget(cluster)
        with self._memo_lock:
            for key in [k for k in self._memo if k[1] == cluster]:
                del self._memo[key]

    # -- the hardened wire path ----------------------------------------------
    def _transport_call(self, cluster: str, transport: Transport,
                        method: str, payload: dict) -> dict:
        """One raw attempt, with the chaos seam in front of the wire
        (error/timeout raise the transport's own failure shapes; slow
        delays; garbage substitutes an unparseable reply)."""
        if chaos.armed():
            f = chaos.fire(chaos.SITE_ESTIMATOR_RPC, cluster=cluster,
                           method=method)
            if f is not None:
                if f.mode == "error":
                    raise ConnectionError(
                        "chaos: estimator connection refused")
                if f.mode == "timeout":
                    raise TimeoutError("chaos: estimator call timed out")
                if f.mode == "slow":
                    self._sleep(f.delay)
                elif f.mode == "garbage":
                    # structurally unusable on every method's parse path
                    return {"maxReplicas": "garbage", "maxSets": "garbage",
                            "unschedulableReplicas": "garbage",
                            "nodeFree": 0, "nodeLabels": 0}
        return transport.call(method, payload)

    def _request(self, cluster: str, transport: Transport, method: str,
                 payload: dict, parse: Callable[[dict], object]) -> object:
        """One logical estimator call: breaker gate, bounded full-jitter
        retry, typed classification.  Returns parse(reply) or raises an
        EstimatorError whose kind is already counted."""
        if not self.breaker.allow(cluster):
            ESTIMATOR_ERRORS.inc(kind=EstimatorCircuitOpen.kind)
            raise EstimatorCircuitOpen(
                f"estimator circuit open for cluster {cluster!r}")
        err: EstimatorError = EstimatorUnreachable("no attempt made")
        for attempt in range(self.retry_attempts):
            if attempt:
                ESTIMATOR_RETRIES.inc(method=method)
                # full jitter: uniform over [0, min(cap, base * 2^k)] —
                # a deterministic stream, never a synchronized stampede
                self._sleep(self._retry_rng.uniform(
                    0.0, min(self.retry_cap_s,
                             self.retry_base_s * (2 ** (attempt - 1)))))
            try:
                value = parse(self._transport_call(
                    cluster, transport, method, payload))
            except Exception as exc:  # noqa: BLE001 — classified + counted
                err = classify_exception(exc)
                ESTIMATOR_ERRORS.inc(kind=err.kind)
                continue
            self.breaker.record_success(cluster)
            return value
        self.breaker.record_failure(cluster)
        raise err

    # -- rv-keyed RPC memo ---------------------------------------------------
    @staticmethod
    def _req_sig(payload: dict) -> str:
        return json.dumps(payload, sort_keys=True, default=str)

    def _memo_get(self, method: str, cluster: Cluster,
                  sig: str) -> Optional[int]:
        rv = cluster.metadata.resource_version
        with self._memo_lock:
            entry = self._memo.get((method, cluster.name))
            if entry is None or entry[0] != rv:
                return None
            answer = entry[1].get(sig)
        if answer is not None:
            RPC_SKIPPED.inc(method=method)
        return answer

    def _memo_put(self, method: str, cluster: Cluster, sig: str,
                  answer: int) -> None:
        rv = cluster.metadata.resource_version
        with self._memo_lock:
            entry = self._memo.get((method, cluster.name))
            if entry is None or entry[0] != rv:
                entry = (rv, {})
                self._memo[(method, cluster.name)] = entry
            answers = entry[1]
            while len(answers) >= self._MEMO_CAP:
                answers.pop(next(iter(answers)))  # oldest insertion
            answers[sig] = answer

    # -- ReplicaEstimator ----------------------------------------------------
    def max_available_replicas(
        self,
        clusters: List[Cluster],
        requirements: Optional[ReplicaRequirements],
    ) -> List[TargetCluster]:
        # the memo key already carries the cluster name, so the request
        # signature is computed ONCE per call from a name-free template
        # instead of one json.dumps per cluster on the fan-out hot path
        sig = self._req_sig(
            MaxAvailableReplicasRequest.from_requirements(
                "", requirements).to_json())

        def one(cluster: Cluster) -> TargetCluster:
            transport = self.transports.get(cluster.name)
            if transport is None:
                return TargetCluster(cluster.name, UNAUTHENTIC_REPLICA)
            req = MaxAvailableReplicasRequest.from_requirements(
                cluster.name, requirements
            )
            payload = req.to_json()
            cached = self._memo_get("MaxAvailableReplicas", cluster, sig)
            if cached is not None:
                return TargetCluster(cluster.name, cached)
            try:
                value = self._request(
                    cluster.name, transport, "MaxAvailableReplicas", payload,
                    lambda raw: MaxAvailableReplicasResponse.from_json(
                        raw).max_replicas)
            except EstimatorError:
                # typed + counted in _request; the sentinel keeps the
                # solver's min-merge total
                return TargetCluster(cluster.name, self._timeout_replicas)
            self._memo_put("MaxAvailableReplicas", cluster, sig, value)
            return TargetCluster(cluster.name, value)

        return _traced_map(self._pool, one, clusters,
                           "MaxAvailableReplicas")

    def max_available_component_sets(
        self, clusters: List[Cluster], components
    ) -> List[TargetCluster]:
        """MaxAvailableComponentSets fan-out (estimation.go:66-103 client
        side): unreachable/unregistered estimators answer UNAUTHENTIC."""
        from karmada_tpu.estimator.wire import (
            MaxAvailableComponentSetsRequest,
            MaxAvailableComponentSetsResponse,
        )

        # name-free signature computed once per call (see
        # max_available_replicas)
        sig = self._req_sig(
            MaxAvailableComponentSetsRequest.from_components(
                "", components).to_json())

        def one(cluster: Cluster) -> TargetCluster:
            transport = self.transports.get(cluster.name)
            if transport is None:
                return TargetCluster(cluster.name, UNAUTHENTIC_REPLICA)
            req = MaxAvailableComponentSetsRequest.from_components(
                cluster.name, components
            )
            payload = req.to_json()
            cached = self._memo_get("MaxAvailableComponentSets", cluster,
                                    sig)
            if cached is not None:
                return TargetCluster(cluster.name, cached)
            try:
                value = self._request(
                    cluster.name, transport, "MaxAvailableComponentSets",
                    payload,
                    lambda raw: MaxAvailableComponentSetsResponse.from_json(
                        raw).max_sets)
            except EstimatorError:
                return TargetCluster(cluster.name, self._timeout_replicas)
            self._memo_put("MaxAvailableComponentSets", cluster, sig, value)
            return TargetCluster(cluster.name, value)

        return _traced_map(self._pool, one, clusters,
                           "MaxAvailableComponentSets")

    # -- UnschedulableReplicaEstimator --------------------------------------
    def unschedulable_replicas(
        self, cluster: str, kind: str, namespace: str, name: str
    ) -> int:
        transport = self.transports.get(cluster)
        if transport is None:
            return UNAUTHENTIC_REPLICA
        req = UnschedulableReplicasRequest(
            cluster=cluster, resource_kind=kind, namespace=namespace, name=name
        )
        try:
            with _rpc_span(cluster, "GetUnschedulableReplicas"):
                return self._request(
                    cluster, transport, "GetUnschedulableReplicas",
                    req.to_json(),
                    lambda raw: UnschedulableReplicasResponse.from_json(
                        raw).unschedulable_replicas)
        except EstimatorError:
            # typed + counted in _request; UNAUTHENTIC keeps callers total
            return UNAUTHENTIC_REPLICA


class SnapshotEstimator:
    """Capacity-tensor shipping: refresh per-cluster node-free tables and
    answer MaxAvailableReplicas locally (no per-call RPC)."""

    def __init__(self, client: AccurateEstimatorClient,
                 refresh_interval_s: float = 5.0,
                 max_age_s: Optional[float] = None) -> None:
        self.client = client
        self.refresh_interval_s = refresh_interval_s
        # a snapshot older than this is stale: fall back to UNAUTHENTIC so a
        # dead/deregistered estimator cannot keep advertising capacity
        self.max_age_s = max_age_s if max_age_s is not None else 6 * refresh_interval_s
        self._snapshots: Dict[str, CapacitySnapshotResponse] = {}
        self._fetched_at: Dict[str, float] = {}
        self._lock = VetLock("estimator.capacity")

    def refresh(self, cluster: str, force: bool = False) -> None:
        transport = self.client.transports.get(cluster)
        if transport is None:
            return
        with self._lock:
            last = self._fetched_at.get(cluster, 0.0)
            if not force and time.time() - last < self.refresh_interval_s:
                return
        try:
            with _rpc_span(cluster, "CapacitySnapshot"):
                snap = self.client._request(  # noqa: SLF001 — same tier
                    cluster, transport, "CapacitySnapshot", {},
                    CapacitySnapshotResponse.from_json)
        except EstimatorError:
            # typed + counted in _request; the stale-age gate answers
            # UNAUTHENTIC for this cluster until a refresh succeeds
            return
        with self._lock:
            self._snapshots[cluster] = snap
            self._fetched_at[cluster] = time.time()

    def _fresh_snapshot(self, cluster_name: str) -> Optional[CapacitySnapshotResponse]:
        """The current snapshot, or None when it is absent/stale or the
        estimator endpoint is gone (callers answer UNAUTHENTIC)."""
        self.refresh(cluster_name)
        with self._lock:
            snap = self._snapshots.get(cluster_name)
            age = time.time() - self._fetched_at.get(cluster_name, 0.0)
        if cluster_name not in self.client.transports:
            return None
        if snap is None or age > self.max_age_s:
            return None
        return snap

    def max_available_replicas(
        self,
        clusters: List[Cluster],
        requirements: Optional[ReplicaRequirements],
    ) -> List[TargetCluster]:
        out: List[TargetCluster] = []
        for cluster in clusters:
            snap = self._fresh_snapshot(cluster.name)
            if snap is None:
                out.append(TargetCluster(cluster.name, UNAUTHENTIC_REPLICA))
                continue
            total = 0
            for i, f in enumerate(snap.node_free):
                labels = snap.node_labels[i] if i < len(snap.node_labels) else {}
                total += replicas_on_node(f, labels, requirements)
            out.append(TargetCluster(cluster.name, total))
        return out

    def max_available_component_sets(
        self, clusters: List[Cluster], components
    ) -> List[TargetCluster]:
        """Component-set capacity from the shipped free table (pool-level,
        same bound as AccurateEstimatorServer, via the shared
        wire.max_sets_from_free_table)."""
        from karmada_tpu.estimator.wire import max_sets_from_free_table

        out: List[TargetCluster] = []
        for cluster in clusters:
            snap = self._fresh_snapshot(cluster.name)
            if snap is None:
                out.append(TargetCluster(cluster.name, UNAUTHENTIC_REPLICA))
                continue
            out.append(TargetCluster(
                cluster.name, max_sets_from_free_table(snap.node_free, components)
            ))
        return out
