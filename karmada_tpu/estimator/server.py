"""Accurate estimator server: node-level capacity math per member cluster.

Mirrors reference pkg/estimator/server (server.go:92, estimate.go:31-93,
replica/replica.go:43, nodes/filter.go:35-74): per node,
maxAvailableReplicas = min over requested resources of
(allocatable - requested) / request, summed over nodes passing the node
selector; plus the unschedulable-replica count the descheduler consumes.
The plugin split (noderesource / resourcequota,
server/framework/plugins/registry.go:26-30) maps to the `plugins` hooks.

The server answers the wire methods of estimator/wire.py and additionally
ships its whole free-capacity table (CapacitySnapshot) so the batching
scheduler can evaluate any request class without per-binding RPCs.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from karmada_tpu.estimator.wire import (
    CapacitySnapshotResponse,
    MaxAvailableReplicasRequest,
    MaxAvailableReplicasResponse,
    UnschedulableReplicasRequest,
    UnschedulableReplicasResponse,
    replicas_on_node,
)
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.work import ReplicaRequirements

MAX_INT32 = (1 << 31) - 1


def _node_free(member: FakeMemberCluster) -> List[Dict[str, int]]:
    """Free (allocatable - admitted) capacity per node.

    The greedy admission plan charges nodes in order, mirroring how the
    reference estimator sees already-placed pods via its pod informer.
    """
    nodes = member.effective_nodes()
    free = [
        {"cpu": n.cpu_milli, "memory": n.memory_milli, "pods": n.pods,
         **n.extra_milli}
        for n in nodes
    ]
    # charge admitted workloads against nodes first-fit, like the plan
    plan = member.admission_plan()
    for (kind, ns, name), admitted in sorted(plan.items()):
        obj = member.get(kind, ns, name)
        if obj is None:
            continue
        req = member._workload_request(obj.manifest)  # noqa: SLF001
        for _ in range(admitted):
            for f in free:
                if f["pods"] > 0 and all(
                    f.get(r, 0) >= v for r, v in req.items()
                ):
                    for r, v in req.items():
                        if r in f:
                            f[r] -= v
                    f["pods"] -= 1
                    break
    return free


def resource_quota_plugin(member: FakeMemberCluster, gates=None):
    """The resourcequota estimator plugin
    (server/framework/plugins/resourcequota/resourcequota.go:95-130, behind
    the ResourceQuotaEstimate feature gate): replicas are additionally
    capped by the member namespace's ResourceQuota headroom
    floor((hard - used) / per-replica request), min over quotas."""
    from karmada_tpu.utils.features import GATES
    from karmada_tpu.models.meta import deep_get
    from karmada_tpu.utils.quantity import Quantity

    gates = gates or GATES

    def _headroom(rq_manifest, requirements: ReplicaRequirements) -> int:
        hard = deep_get(rq_manifest, "spec.hard", {}) or {}
        used = deep_get(rq_manifest, "status.used", {}) or {}
        allowed = MAX_INT32
        for name, qty in requirements.resource_request.items():
            req = qty.milli
            if req <= 0:
                continue
            raw = hard.get(name, hard.get(f"requests.{name}"))
            if raw is None:
                continue
            used_raw = used.get(name, used.get(f"requests.{name}", 0))
            free = Quantity.parse(raw).milli - Quantity.parse(used_raw).milli
            allowed = min(allowed, max(free, 0) // req)
        return allowed

    def plugin(requirements: Optional[ReplicaRequirements], estimate: int) -> int:
        if not gates.enabled("ResourceQuotaEstimate"):
            return estimate
        if requirements is None or not requirements.namespace:
            return estimate
        for rq in member.store.list("ResourceQuota", requirements.namespace):
            manifest = getattr(rq, "manifest", None)
            if manifest is None:
                continue
            estimate = min(estimate, _headroom(manifest, requirements))
        return estimate

    return plugin


class AccurateEstimatorServer:
    """One server per member cluster (cmd/scheduler-estimator)."""

    def __init__(self, member: FakeMemberCluster, gates=None) -> None:
        self.member = member
        # plugin hooks: each may cap the estimate; the in-tree set mirrors
        # server/framework/plugins/registry.go:26-30 (noderesource is the
        # base estimate; resourcequota caps it behind its feature gate)
        self.plugins: List[Callable[[Optional[ReplicaRequirements], int], int]] = [
            resource_quota_plugin(member, gates)
        ]

    # -- service methods ----------------------------------------------------
    def max_available_replicas(
        self, requirements: Optional[ReplicaRequirements]
    ) -> int:
        nodes = self.member.effective_nodes()
        free = _node_free(self.member)
        total = 0
        for node, f in zip(nodes, free):
            total += replicas_on_node(f, node.labels, requirements)
        total = min(total, MAX_INT32)
        for plugin in self.plugins:
            total = min(total, plugin(requirements, total))
        return total

    def max_available_component_sets(self, components) -> int:
        """Whole component SETS that fit this member's free capacity
        (wire.max_sets_from_free_table), capped by the quota-style plugins
        the reference runs (estimate.go:70-90).  Plugins see ONE SET's
        aggregate demand as the per-"replica" requirement, so quota
        headroom caps whole sets exactly like single-template replicas."""
        from karmada_tpu.estimator.wire import max_sets_from_free_table
        from karmada_tpu.estimator.general import per_set_requirement
        from karmada_tpu.utils.quantity import RESOURCE_CPU, Quantity

        total = max_sets_from_free_table(_node_free(self.member), components)
        namespace = next(
            (c.replica_requirements.namespace for c in components
             if c.replica_requirements is not None
             and c.replica_requirements.namespace),
            "",
        )
        # per_set_requirement units: cpu in milli, everything else in Value
        per_set = ReplicaRequirements(
            resource_request={
                name: (
                    Quantity.from_milli(v)
                    if name == RESOURCE_CPU
                    else Quantity.from_units(v)
                )
                for name, v in per_set_requirement(components).items()
            },
            namespace=namespace,
        )
        for plugin in self.plugins:
            total = min(total, plugin(per_set, total))
        return min(total, MAX_INT32)

    def unschedulable_replicas(self, kind: str, namespace: str, name: str) -> int:
        return self.member.unschedulable_replicas(kind, namespace, name)

    def capacity_snapshot(self) -> CapacitySnapshotResponse:
        return CapacitySnapshotResponse(
            cluster=self.member.name,
            node_free=_node_free(self.member),
            node_labels=[dict(n.labels) for n in self.member.effective_nodes()],
        )

    # -- wire dispatch -------------------------------------------------------
    def handle(self, method: str, body: dict) -> dict:
        if method == "MaxAvailableReplicas":
            req = MaxAvailableReplicasRequest.from_json(body)
            n = self.max_available_replicas(req.requirements())
            return MaxAvailableReplicasResponse(max_replicas=n).to_json()
        if method == "MaxAvailableComponentSets":
            from karmada_tpu.estimator.wire import (
                MaxAvailableComponentSetsRequest,
                MaxAvailableComponentSetsResponse,
            )

            req = MaxAvailableComponentSetsRequest.from_json(body)
            n = self.max_available_component_sets(req.typed_components())
            return MaxAvailableComponentSetsResponse(max_sets=n).to_json()
        if method == "GetUnschedulableReplicas":
            req = UnschedulableReplicasRequest.from_json(body)
            n = self.unschedulable_replicas(req.resource_kind, req.namespace, req.name)
            return UnschedulableReplicasResponse(unschedulable_replicas=n).to_json()
        if method == "CapacitySnapshot":
            return self.capacity_snapshot().to_json()
        raise ValueError(f"unknown method {method!r}")
