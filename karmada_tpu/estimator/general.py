"""General (in-process) capacity estimator.

Faithful port of reference pkg/estimator/client/general.go: computes the
maximum deployable replicas per cluster from `cluster.status.resourceSummary`
(available = allocatable - allocated - allocating; CPU in milli-units, other
resources in whole units rounded up) or, when resource models are populated,
from the AllocatableModelings histogram (general.go:336-387).

This math is already tensor-shaped — the TPU path (ops/solver.py) evaluates
the identical formula over dense (clusters x resources) arrays.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karmada_tpu.models.cluster import Cluster, ResourceSummary
from karmada_tpu.models.work import ReplicaRequirements, TargetCluster
from karmada_tpu.utils.quantity import (
    RESOURCE_CPU,
    RESOURCE_PODS,
    Quantity,
    resource_request_value,
)

# Sentinel meaning "this estimator cannot authenticate a value for the
# cluster" (client/interface.go:30); consumers skip it when min-merging.
UNAUTHENTIC_REPLICA = -1

MAX_INT32 = (1 << 31) - 1
MAX_INT64 = (1 << 63) - 1


def _available(summary: ResourceSummary, resource: str) -> int:
    """available milli-units of one resource (general.go:302-316)."""
    alloc = summary.allocatable.get(resource)
    if alloc is None:
        return -1  # missing allocatable: treated as "no capacity known"
    m = alloc.milli
    used = summary.allocated.get(resource)
    if used is not None:
        m -= used.milli
    ing = summary.allocating.get(resource)
    if ing is not None:
        m -= ing.milli
    return m


def produce_allocatable_modelings(member, resource_models):
    """The modeling PRODUCER (pkg/modeling/modeling.go:33-246
    AddToResourceSummary/getIndex): place each node's FREE capacity into
    the grade histogram.  A node's grade is the MINIMUM over the model's
    resource axes of the last grade whose lower bound the node still
    reaches (searchLastLessElement); nodes below grade 0 on any axis are
    dropped, exactly like the reference's index == -1 path.

    Uses the SAME _models_min_map (model-list order, Quantity units) the
    consumer indexes against, so producer and consumer cannot disagree on
    grade indices."""
    from karmada_tpu.estimator.server import _node_free
    from karmada_tpu.models.cluster import AllocatableModeling

    if not resource_models:
        return []
    min_map = _models_min_map(resource_models)
    counts = [0] * len(resource_models)
    for free in _node_free(member):
        index = None
        for name, mins in min_map.items():
            # _node_free units: milli for everything except the raw pod count
            have = (
                Quantity.from_units(free.get(name, 0))
                if name == RESOURCE_PODS
                else Quantity(free.get(name, 0))
            )
            last = -1
            for gi, lo in enumerate(mins):
                if have >= lo:
                    last = gi
            index = last if index is None else min(index, last)
        if index is None or index < 0:
            continue
        counts[index] += 1
    return [
        AllocatableModeling(grade=m.grade, count=counts[i])
        for i, m in enumerate(resource_models)
    ]


def allowed_pod_number(summary: ResourceSummary) -> int:
    """general.go:234-252."""
    allocatable = summary.allocatable.get(RESOURCE_PODS, Quantity(0)).value()
    allocated = summary.allocated.get(RESOURCE_PODS, Quantity(0)).value()
    allocating = summary.allocating.get(RESOURCE_PODS, Quantity(0)).value()
    allowed = allocatable - allocated - allocating
    return max(allowed, 0)


def max_replicas_from_summary(
    summary: ResourceSummary, requirements: Optional[ReplicaRequirements]
) -> int:
    """getMaximumReplicasBasedOnClusterSummary (general.go:294-334)."""
    maximum = MAX_INT64
    if requirements is None:
        return maximum
    for name, qty in requirements.resource_request.items():
        requested = resource_request_value(name, qty)
        if requested <= 0:
            continue
        avail_milli = _available(summary, name)
        if avail_milli < 0:
            return 0  # allocatable missing for a requested resource
        if name == RESOURCE_CPU:
            available = avail_milli
        else:
            available = -((-avail_milli) // 1000)  # Value(): ceil to units
        if available <= 0:
            return 0
        maximum = min(maximum, available // requested)
    return maximum


def _models_min_map(resource_models) -> Dict[str, List[Quantity]]:
    """convertToResourceModelsMinMap (general.go:254-262).  Model-LIST order:
    allocatable_modelings index positionally against this, so the producer
    below and the consumer share one mapping by construction."""
    out: Dict[str, List[Quantity]] = {}
    for model in resource_models:
        for rng in model.ranges:
            out.setdefault(rng.name, []).append(rng.min)
    return out


def _minimum_model_index(min_grades: List[Quantity], request: Quantity) -> int:
    """general.go:374-387: smallest grade whose min >= request."""
    for i, min_value in enumerate(min_grades):
        if min_value >= request:
            return i
    return -1


def _node_available_replicas(
    grade_index: int,
    requirements: ReplicaRequirements,
    min_map: Dict[str, List[Quantity]],
) -> int:
    """getNodeAvailableReplicas (general.go:270-292): how many replicas fit on
    one node of the given grade, assuming the node offers each resource at the
    grade's minimum boundary."""
    maximum_one_node = MAX_INT64
    for name, qty in requirements.resource_request.items():
        requested = resource_request_value(name, qty)
        if requested <= 0:
            continue
        grades = min_map.get(name)
        if grades is None or grade_index >= len(grades):
            continue
        available = resource_request_value(name, grades[grade_index])
        maximum_one_node = min(maximum_one_node, available // requested)
    # first suitable model counts as able to host at least one pod
    return 1 if maximum_one_node == 0 else maximum_one_node


def max_replicas_from_models(
    cluster: Cluster, requirements: ReplicaRequirements
) -> Optional[int]:
    """getMaximumReplicasBasedOnResourceModels (general.go:336-372).

    Returns None when models are inapplicable (missing resource) — caller
    falls back to summary math; returns an int otherwise.
    """
    min_map = _models_min_map(cluster.spec.resource_models)
    min_index = 0
    for name, qty in requirements.resource_request.items():
        if resource_request_value(name, qty) <= 0:
            continue
        grades = min_map.get(name)
        if grades is None:
            return None  # inapplicable: missing resource in models
        idx = _minimum_model_index(grades, qty)
        if idx == -1:
            return 0
        min_index = max(min_index, idx)

    summary = cluster.status.resource_summary
    total = 0
    for i in range(min_index, len(cluster.spec.resource_models)):
        modelings = summary.allocatable_modelings if summary else []
        count = modelings[i].count if i < len(modelings) else 0
        if count == 0:
            continue
        total += count * _node_available_replicas(i, requirements, min_map)
    return total


def per_set_requirement(components) -> Dict[str, int]:
    """perSetRequirement (general.go:181-195): aggregate demand of ONE set of
    components, in request units (cpu milli, others Value)."""
    out: Dict[str, int] = {}
    for c in components:
        rr = c.replica_requirements
        if rr is None or not rr.resource_request:
            continue
        for name, qty in rr.resource_request.items():
            out[name] = out.get(name, 0) + resource_request_value(name, qty) * c.replicas
    return out


def pods_in_set(components) -> int:
    """podsInSet (general.go:172-179)."""
    return sum(c.replicas for c in components)


def max_sets_from_models(cluster: Cluster, components) -> int:
    """getMaximumSetsBasedOnResourceModels (general.go:163-170): the
    reference leaves this as a placeholder that never reduces the bound."""
    return MAX_INT64


class GeneralEstimator:
    """Reference GeneralEstimator: pure math on cluster.status.resourceSummary."""

    def __init__(self, enable_resource_modeling: bool = True) -> None:
        self.enable_resource_modeling = enable_resource_modeling

    def max_available_replicas(
        self,
        clusters: List[Cluster],
        requirements: Optional[ReplicaRequirements],
    ) -> List[TargetCluster]:
        return [
            TargetCluster(name=c.name, replicas=self._max_for_cluster(c, requirements))
            for c in clusters
        ]

    def max_available_component_sets(
        self, clusters: List[Cluster], components
    ) -> List[TargetCluster]:
        """MaxAvailableComponentSets (general.go:96-104): how many full SETS
        of a multi-template workload's components fit per cluster."""
        return [
            TargetCluster(name=c.name, replicas=self._max_sets_for_cluster(c, components))
            for c in clusters
        ]

    def _max_sets_for_cluster(self, cluster: Cluster, components) -> int:
        """maxAvailableComponentSets (general.go:106-160)."""
        summary = cluster.status.resource_summary
        if summary is None:
            return 0
        allowed = allowed_pod_number(summary)
        if allowed <= 0:
            return 0
        pods_per_set = pods_in_set(components)
        if pods_per_set <= 0:
            return min(allowed, MAX_INT32)
        max_sets = allowed // pods_per_set
        per_set = per_set_requirement(components)
        if per_set and any(v > 0 for v in per_set.values()):
            for name, req in per_set.items():
                if req <= 0:
                    continue
                avail_milli = _available(summary, name)
                if name == RESOURCE_CPU:
                    available = avail_milli
                else:
                    available = -((-avail_milli) // 1000)
                if available <= 0:
                    return 0
                max_sets = min(max_sets, available // req)
        if self.enable_resource_modeling and summary.allocatable_modelings:
            max_sets = min(max_sets, max_sets_from_models(cluster, components))
        return min(max_sets, MAX_INT32)

    def _max_for_cluster(
        self, cluster: Cluster, requirements: Optional[ReplicaRequirements]
    ) -> int:
        """general.go:56-94 maxAvailableReplicas."""
        summary = cluster.status.resource_summary
        if summary is None:
            return 0
        maximum = allowed_pod_number(summary)
        if maximum <= 0:
            return 0
        if requirements is None:
            return min(maximum, MAX_INT32)
        if self.enable_resource_modeling and summary.allocatable_modelings:
            num = max_replicas_from_models(cluster, requirements)
            if num is not None:
                return min(min(num, maximum), MAX_INT32)
        num = max_replicas_from_summary(summary, requirements)
        return min(min(num, maximum), MAX_INT32)
