"""Estimator wire protocol + transports (the gRPC tier of the reference).

The reference scheduler/descheduler talk proto2 gRPC with mTLS to one
karmada-scheduler-estimator per member cluster
(pkg/estimator/service/service.proto, pkg/estimator/pb/generated.proto:
MaxAvailableReplicasRequest/Response, UnschedulableReplicasRequest/
Response; pkg/util/grpcconnection/{client,server}.go).  grpcio is not in
this image, so the same contract runs over two transports with identical
message schemas:

  * LocalTransport -- in-process dispatch (the fake-member E2E path and the
    default for the batching scheduler);
  * TcpTransport / serve_tcp -- stdlib socket server with length-prefixed
    JSON frames and optional TLS via ssl.SSLContext (the mTLS analogue),
    for running estimators as real sidecar processes.

Messages are dataclasses with explicit to/from_json so the wire format is
stable and transport-independent.

The same frame transport also carries the facade tier (karmada_tpu/facade):
`SelectClusters`/`AssignReplicas` are the scheduler-as-a-service contract —
a caller submits one small binding's requirements and gets a placement
back, the shape a Go scheduler running with
`--replica-scheduling-backend=tpu` would speak to this process.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.work import ReplicaRequirements
from karmada_tpu.utils.quantity import Quantity

UNAUTHENTIC_REPLICA = -1

#: hard bound on one frame's payload: a corrupt/hostile length prefix must
#: not become a multi-GiB allocation before the first payload byte arrives
MAX_FRAME_BYTES = 64 * 1024 * 1024


class FrameTooLarge(ValueError):
    """Length prefix exceeds MAX_FRAME_BYTES.  A ValueError on purpose:
    estimator.client.classify_exception maps ValueError to
    EstimatorMalformed (a protocol fault), where a ConnectionError would
    misreport it as EstimatorUnreachable and make the breaker retry a
    peer that is speaking garbage."""


# -- messages (pb/generated.proto equivalents) ------------------------------


@dataclass
class MaxAvailableReplicasRequest:
    cluster: str = ""
    resource_request: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"cluster": self.cluster, "resourceRequest": self.resource_request,
                "nodeSelector": self.node_selector}

    @staticmethod
    def from_json(d: dict) -> "MaxAvailableReplicasRequest":
        return MaxAvailableReplicasRequest(
            cluster=d.get("cluster", ""),
            resource_request=dict(d.get("resourceRequest", {})),
            node_selector=dict(d.get("nodeSelector", {})),
        )

    @staticmethod
    def from_requirements(
        cluster: str, requirements: Optional[ReplicaRequirements]
    ) -> "MaxAvailableReplicasRequest":
        req: Dict[str, str] = {}
        selector: Dict[str, str] = {}
        if requirements is not None:
            req = {k: str(v) for k, v in requirements.resource_request.items()}
            if requirements.node_claim is not None:
                selector = dict(requirements.node_claim.node_selector)
        return MaxAvailableReplicasRequest(
            cluster=cluster, resource_request=req, node_selector=selector
        )

    def requirements(self) -> Optional[ReplicaRequirements]:
        if not self.resource_request and not self.node_selector:
            return None
        from karmada_tpu.models.work import NodeClaim

        return ReplicaRequirements(
            resource_request={k: Quantity.parse(v)
                              for k, v in self.resource_request.items()},
            node_claim=NodeClaim(node_selector=dict(self.node_selector))
            if self.node_selector else None,
        )


@dataclass
class MaxAvailableReplicasResponse:
    max_replicas: int = 0

    def to_json(self) -> dict:
        return {"maxReplicas": self.max_replicas}

    @staticmethod
    def from_json(d: dict) -> "MaxAvailableReplicasResponse":
        return MaxAvailableReplicasResponse(max_replicas=int(d.get("maxReplicas", 0)))


@dataclass
class MaxAvailableComponentSetsRequest:
    """pb.MaxAvailableComponentSetsRequest (generated.proto Component):
    how many whole SETS of a multi-template workload's components fit."""

    cluster: str = ""
    # [{"name": ..., "replicas": n, "resourceRequest": {res: quantity-str}}]
    components: List[Dict] = field(default_factory=list)

    @staticmethod
    def from_components(cluster: str, components) -> "MaxAvailableComponentSetsRequest":
        rows = []
        for c in components:
            req = {}
            if c.replica_requirements is not None:
                req = {k: str(v)
                       for k, v in c.replica_requirements.resource_request.items()}
            rows.append({"name": c.name, "replicas": c.replicas,
                         "resourceRequest": req})
        return MaxAvailableComponentSetsRequest(cluster=cluster, components=rows)

    def to_json(self) -> dict:
        return {"cluster": self.cluster, "components": self.components}

    @staticmethod
    def from_json(d: dict) -> "MaxAvailableComponentSetsRequest":
        return MaxAvailableComponentSetsRequest(
            cluster=d.get("cluster", ""),
            components=list(d.get("components", [])),
        )

    def typed_components(self):
        from karmada_tpu.models.work import Component

        out = []
        for row in self.components:
            req = {k: Quantity.parse(v)
                   for k, v in (row.get("resourceRequest") or {}).items()}
            out.append(Component(
                name=row.get("name", ""), replicas=int(row.get("replicas", 0)),
                replica_requirements=ReplicaRequirements(resource_request=req)
                if req else None,
            ))
        return out


@dataclass
class MaxAvailableComponentSetsResponse:
    max_sets: int = 0

    def to_json(self) -> dict:
        return {"maxSets": self.max_sets}

    @staticmethod
    def from_json(d: dict) -> "MaxAvailableComponentSetsResponse":
        return MaxAvailableComponentSetsResponse(max_sets=int(d.get("maxSets", 0)))


@dataclass
class UnschedulableReplicasRequest:
    cluster: str = ""
    resource_kind: str = ""
    namespace: str = ""
    name: str = ""
    unschedulable_threshold_seconds: int = 60

    def to_json(self) -> dict:
        return {"cluster": self.cluster, "kind": self.resource_kind,
                "namespace": self.namespace, "name": self.name,
                "thresholdSeconds": self.unschedulable_threshold_seconds}

    @staticmethod
    def from_json(d: dict) -> "UnschedulableReplicasRequest":
        return UnschedulableReplicasRequest(
            cluster=d.get("cluster", ""), resource_kind=d.get("kind", ""),
            namespace=d.get("namespace", ""), name=d.get("name", ""),
            unschedulable_threshold_seconds=int(d.get("thresholdSeconds", 60)),
        )


@dataclass
class UnschedulableReplicasResponse:
    unschedulable_replicas: int = 0

    def to_json(self) -> dict:
        return {"unschedulableReplicas": self.unschedulable_replicas}

    @staticmethod
    def from_json(d: dict) -> "UnschedulableReplicasResponse":
        return UnschedulableReplicasResponse(
            unschedulable_replicas=int(d.get("unschedulableReplicas", 0)))


@dataclass
class CapacitySnapshotResponse:
    """Capacity-tensor shipping (the BASELINE.json pkg/estimator change):
    instead of one RPC per (binding, cluster), an estimator ships its whole
    per-node capacity table once per refresh; the scheduler's batched
    solver evaluates any request class against it locally."""

    cluster: str = ""
    # per node: free capacity, milli units for EVERY resource the node
    # exposes — {"cpu": milli, "memory": milli, "pods": n, <extended
    # resource e.g. "nvidia.com/gpu">: milli, ...}.  Estimator sidecars must
    # ship extended resources here or replicas_on_node reports 0 for them.
    node_free: List[Dict[str, int]] = field(default_factory=list)
    # per node: labels, aligned with node_free (node-selector evaluation)
    node_labels: List[Dict[str, str]] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"cluster": self.cluster, "nodeFree": self.node_free,
                "nodeLabels": self.node_labels}

    @staticmethod
    def from_json(d: dict) -> "CapacitySnapshotResponse":
        return CapacitySnapshotResponse(
            cluster=d.get("cluster", ""), node_free=list(d.get("nodeFree", [])),
            node_labels=list(d.get("nodeLabels", [])))


# -- facade messages (karmada_tpu/facade's scheduler-as-a-service tier) -----


@dataclass
class SelectClustersRequest:
    """Feasibility query (the reference's SelectClusters phase: group +
    filter): which member clusters can host this request class at all."""

    namespace: str = "default"
    name: str = ""
    resource_request: Dict[str, str] = field(default_factory=dict)
    cluster_names: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"namespace": self.namespace, "name": self.name,
                "resourceRequest": self.resource_request,
                "clusterNames": self.cluster_names}

    @staticmethod
    def from_json(d: dict) -> "SelectClustersRequest":
        return SelectClustersRequest(
            namespace=d.get("namespace", "default"),
            name=d.get("name", ""),
            resource_request=dict(d.get("resourceRequest", {})),
            cluster_names=list(d.get("clusterNames", [])),
        )


@dataclass
class SelectClustersResponse:
    clusters: List[str] = field(default_factory=list)
    # per filtered-out cluster: the filter diagnosis (FitError shape)
    excluded: Dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"clusters": self.clusters, "excluded": self.excluded}

    @staticmethod
    def from_json(d: dict) -> "SelectClustersResponse":
        return SelectClustersResponse(
            clusters=list(d.get("clusters", [])),
            excluded=dict(d.get("excluded", {})),
        )


@dataclass
class AssignReplicasRequest:
    """One small binding in, a placement out — the facade's core verb
    (the reference's core.AssignReplicas seam served over the wire).
    `divided` selects Divided+Aggregated packing; default is Duplicated
    across every feasible cluster.  `cluster_names` restricts the
    candidate set (a ClusterAffinity allowlist)."""

    namespace: str = "default"
    name: str = ""
    replicas: int = 1
    resource_request: Dict[str, str] = field(default_factory=dict)
    divided: bool = False
    cluster_names: List[str] = field(default_factory=list)
    # caller-side trace id: stitches the caller's timeline to the
    # server-side coalesced-batch flight records (obs/incidents)
    trace_id: str = ""

    def to_json(self) -> dict:
        d = {"namespace": self.namespace, "name": self.name,
             "replicas": self.replicas,
             "resourceRequest": self.resource_request,
             "divided": self.divided,
             "clusterNames": self.cluster_names}
        if self.trace_id:
            # emitted only when set: untraced callers keep the exact
            # frame shape older peers golden-test against
            d["traceId"] = self.trace_id
        return d

    @staticmethod
    def from_json(d: dict) -> "AssignReplicasRequest":
        return AssignReplicasRequest(
            namespace=d.get("namespace", "default"),
            name=d.get("name", ""),
            replicas=int(d.get("replicas", 1)),
            resource_request=dict(d.get("resourceRequest", {})),
            divided=bool(d.get("divided", False)),
            cluster_names=list(d.get("clusterNames", [])),
            trace_id=d.get("traceId", ""),
        )


@dataclass
class AssignReplicasResponse:
    """`assignments` is the TargetCluster list ([{cluster, replicas}]);
    `batch_id`/`batch_size` name the coalesced facade cycle this call
    shared, so a caller can see how many peers rode its device dispatch."""

    assignments: List[Dict] = field(default_factory=list)
    outcome: str = "scheduled"  # scheduled | unschedulable | error
    message: str = ""
    trace_id: str = ""
    batch_id: int = 0
    batch_size: int = 0

    def to_json(self) -> dict:
        return {"assignments": self.assignments, "outcome": self.outcome,
                "message": self.message, "traceId": self.trace_id,
                "batchId": self.batch_id, "batchSize": self.batch_size}

    @staticmethod
    def from_json(d: dict) -> "AssignReplicasResponse":
        return AssignReplicasResponse(
            assignments=list(d.get("assignments", [])),
            outcome=d.get("outcome", "scheduled"),
            message=d.get("message", ""),
            trace_id=d.get("traceId", ""),
            batch_id=int(d.get("batchId", 0)),
            batch_size=int(d.get("batchSize", 0)),
        )


def replicas_on_node(
    free: Dict[str, int],
    labels: Dict[str, str],
    requirements: Optional[ReplicaRequirements],
) -> int:
    """How many replicas of `requirements` fit on one node's free capacity.

    The single shared implementation of the per-node min-divide
    (pkg/estimator/server estimate.go:31-93 semantics): cpu in milli,
    memory Value() (ceil to units), pods; node-selector mismatch -> 0.
    """
    per_node = int(free.get("pods", 0))
    if requirements is None:
        return max(per_node, 0)
    if requirements.node_claim is not None:
        for k, v in requirements.node_claim.node_selector.items():
            if labels.get(k) != v:
                return 0
    from karmada_tpu.utils.quantity import RESOURCE_CPU, resource_request_value

    for rname, qty in requirements.resource_request.items():
        requested = resource_request_value(rname, qty)
        if requested <= 0:
            continue
        if rname == RESOURCE_CPU:
            avail = int(free.get("cpu", 0))
        elif rname == "pods":
            avail = int(free.get("pods", 0))
        else:
            # generic path (memory, ephemeral-storage, extended resources
            # such as GPUs): the free table carries milli units for every
            # resource the node exposes; request values use Value(), so
            # convert milli -> value with k8s away-from-zero rounding.  A
            # resource the node does not expose is genuinely 0 here.
            avail = -((-int(free.get(rname, 0))) // 1000)
        per_node = min(per_node, avail // requested)
    return max(per_node, 0)


def _pool_sets_bound(free: List[Dict[str, int]], components) -> int:
    """Pool-level upper bound on whole component sets: summed free
    capacity divided by one set's aggregate demand (the reference's
    quota-style view)."""
    from karmada_tpu.estimator.general import per_set_requirement, pods_in_set
    from karmada_tpu.utils.quantity import RESOURCE_CPU, RESOURCE_PODS

    MAX_INT32 = (1 << 31) - 1
    pods_free = sum(int(f.get("pods", 0)) for f in free)
    if pods_free <= 0:
        return 0
    pods_per_set = pods_in_set(components)
    if pods_per_set <= 0:
        return min(pods_free, MAX_INT32)
    total = pods_free // pods_per_set
    for rname, req in per_set_requirement(components).items():
        if req <= 0:
            continue
        pool = sum(int(f.get(rname, 0)) for f in free)
        if rname in (RESOURCE_CPU, RESOURCE_PODS):
            avail = pool
        else:
            avail = -((-pool) // 1000)
        if avail <= 0:
            return 0
        total = min(total, avail // req)
    return min(total, MAX_INT32)


def _per_replica_needs(components) -> List[Tuple[int, Dict[str, int]]]:
    """(replicas, per-replica need in table units) per component: cpu in
    milli, every other resource milli (request Value x 1000).  The 'pods'
    axis is implicit — one pod per replica — so an explicit 'pods'
    request is skipped here (it is already counted by pods_in_set)."""
    from karmada_tpu.utils.quantity import (
        RESOURCE_CPU,
        RESOURCE_PODS,
        resource_request_value,
    )

    needs: List[Tuple[int, Dict[str, int]]] = []
    for c in components:
        req: Dict[str, int] = {}
        rr = c.replica_requirements
        if rr is not None:
            for rname, qty in rr.resource_request.items():
                if rname == RESOURCE_PODS:
                    continue
                v = resource_request_value(rname, qty)
                if v <= 0:
                    continue
                req[rname] = v if rname == RESOURCE_CPU else v * 1000
        needs.append((max(int(c.replicas), 0), req))
    return needs


def max_sets_from_free_table(free: List[Dict[str, int]], components) -> int:
    """Whole component SETS that fit a free-capacity table, packed NODE
    BY NODE.

    The single implementation behind AccurateEstimatorServer and
    SnapshotEstimator component-set answers.  The reference estimator
    server leaves node-level set packing as a TODO (estimate.go:70-90
    runs only quota-style pool plugins); this resolves it: each component
    replica of each candidate set is placed first-fit onto a node that
    still fits its whole per-replica request, so a fragmented pool can no
    longer overreport (two 1-cpu nodes pack ZERO sets of a 2-cpu pod,
    where the pool bound said one).  First-fit in table order is greedy,
    not optimal bin packing (that is NP-hard) — it can only UNDER-report
    relative to a perfect packing, the safe direction for an estimator.
    Workloads with no per-replica resource requests keep the exact pool
    answer (pods spread freely, so pool == packing).  Node selectors are
    out of scope here, as in the reference's pool plugins.

    Units follow the table convention: 'pods' is a raw count, cpu is
    milli, everything else milli -> Value.
    """
    upper = _pool_sets_bound(free, components)
    if upper <= 0:
        return 0
    needs = _per_replica_needs(components)
    if not any(req for _, req in needs):
        return upper  # pods-only demand: the pool bound is exact
    nodes = [dict(f) for f in free]
    # per-component candidate lists in first-fit (table) order: node
    # capacity only decreases, so a node that cannot fit component k's
    # per-replica request NOW never can again — prune it permanently.
    # That keeps the first-fit outcome bit-identical to a full rescan
    # while making the whole pack amortized O(placements + components x
    # nodes) instead of O(placements x nodes).
    cand = [list(range(len(nodes))) for _ in needs]
    sets = 0
    while sets < upper:
        placed_all = True
        for k, (n_replicas, req) in enumerate(needs):
            lst = cand[k]
            for _ in range(n_replicas):
                node = None
                while lst:
                    nd = nodes[lst[0]]
                    if int(nd.get("pods", 0)) > 0 and all(
                            int(nd.get(r, 0)) >= v
                            for r, v in req.items()):
                        node = nd
                        break
                    lst.pop(0)  # exhausted for this component forever
                if node is None:
                    placed_all = False
                    break
                node["pods"] = int(node.get("pods", 0)) - 1
                for r, v in req.items():
                    node[r] = int(node.get(r, 0)) - v
            if not placed_all:
                break
        if not placed_all:
            break
        sets += 1
    return sets


_METHODS = {
    "MaxAvailableReplicas": MaxAvailableReplicasRequest,
    "MaxAvailableComponentSets": MaxAvailableComponentSetsRequest,
    "GetUnschedulableReplicas": UnschedulableReplicasRequest,
    "CapacitySnapshot": None,  # empty request body
}


# -- transports --------------------------------------------------------------


class Transport:
    """One estimator endpoint: call(method, request_json) -> response_json."""

    def call(self, method: str, request: dict) -> dict:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalTransport(Transport):
    def __init__(self, handler: Callable[[str, dict], dict]) -> None:
        self.handler = handler

    def call(self, method: str, request: dict) -> dict:
        return self.handler(method, request)


def _send_frame(sock: socket.socket, payload: dict) -> None:
    raw = json.dumps(payload).encode("utf-8")
    sock.sendall(struct.pack(">I", len(raw)) + raw)


def _recv_frame(sock: socket.socket) -> dict:
    header = _recv_exact(sock, 4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME_BYTES:
        raise FrameTooLarge(
            f"frame length {length} exceeds {MAX_FRAME_BYTES} bytes")
    return json.loads(_recv_exact(sock, length).decode("utf-8"))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


class TcpTransport(Transport):
    """Length-prefixed JSON frames over TCP, optionally TLS-wrapped."""

    def __init__(self, host: str, port: int, ssl_context=None,
                 timeout: float = 5.0) -> None:
        self.addr = (host, port)
        self.ssl_context = ssl_context
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(sock, server_hostname=self.addr[0])
        # create_connection's timeout bounds only the CONNECT; re-arm it on
        # the (possibly TLS-wrapped) socket so every recv is bounded too —
        # a stalled peer surfaces as socket.timeout (a TimeoutError, i.e.
        # EstimatorTimeout through classify_exception), not a hang
        sock.settimeout(self.timeout)
        return sock

    def call(self, method: str, request: dict) -> dict:
        # _lock held across the round trip BY DESIGN: it serializes use
        # of the single persistent socket — releasing it mid-exchange
        # would let a second caller interleave frames and desync the
        # length-prefixed stream.  Every socket op below is bounded by
        # self.timeout (settimeout in _connect), so the hold time is
        # bounded too; callers queue behind the breaker, never hang.
        with self._lock:
            if self._sock is None:
                # vet: ignore[lock-blocking-call] _lock IS the per-connection frame serialization; connect is timeout-bounded
                self._sock = self._connect()
            try:
                # vet: ignore[lock-blocking-call] _lock IS the per-connection frame serialization; send is timeout-bounded
                _send_frame(self._sock, {"method": method, "body": request})
                # vet: ignore[lock-blocking-call] _lock IS the per-connection frame serialization; recv is timeout-bounded
                resp = _recv_frame(self._sock)
            except (FrameTooLarge, socket.timeout):
                # protocol desync / stalled peer: the stream cannot be
                # trusted (a partial frame may still be in flight), and a
                # blind resend could double-execute the call — drop the
                # connection and surface the typed fault to the breaker
                self._sock.close()
                self._sock = None
                raise
            except (ConnectionError, OSError):
                # one reconnect attempt (sidecar restarts are routine)
                self._sock.close()
                # vet: ignore[lock-blocking-call] reconnect under the same serialization lock; timeout-bounded
                self._sock = self._connect()
                # vet: ignore[lock-blocking-call] resend under the same serialization lock; timeout-bounded
                _send_frame(self._sock, {"method": method, "body": request})
                # vet: ignore[lock-blocking-call] recv under the same serialization lock; timeout-bounded
                resp = _recv_frame(self._sock)
        if "error" in resp:
            raise RuntimeError(f"estimator error: {resp['error']}")
        return resp.get("body", {})

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        while True:
            try:
                frame = _recv_frame(self.request)
            except (FrameTooLarge, ConnectionError, OSError):
                # an oversize prefix means the peer is desynced or hostile:
                # there is no way to resync a length-prefixed stream, so
                # the only safe response is dropping the connection
                return
            try:
                body = self.server.dispatch(  # type: ignore[attr-defined]
                    frame.get("method", ""), frame.get("body", {}))
                _send_frame(self.request, {"body": body})
            # vet: ignore[exception-hygiene] serialized back to the peer as an error frame
            except Exception as e:  # noqa: BLE001 -- serialize server errors
                _send_frame(self.request, {"error": str(e)})


class EstimatorTcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler: Callable[[str, dict], dict],
                 ssl_context=None) -> None:
        super().__init__(addr, _Handler)
        self._dispatch = handler
        self._ssl_context = ssl_context

    def get_request(self):
        sock, addr = super().get_request()
        if self._ssl_context is not None:
            sock = self._ssl_context.wrap_socket(sock, server_side=True)
        return sock, addr

    def dispatch(self, method: str, body: dict) -> dict:
        return self._dispatch(method, body)


def serve_tcp(handler: Callable[[str, dict], dict], host: str = "127.0.0.1",
              port: int = 0, ssl_context=None) -> EstimatorTcpServer:
    """Start a daemon estimator server; returns it (server_address has the
    bound port)."""
    server = EstimatorTcpServer((host, port), handler, ssl_context)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server
