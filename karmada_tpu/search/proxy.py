"""Cluster proxy + unified auth.

Reference: the aggregated apiserver's `clusters/{name}/proxy` passthrough
(pkg/registry/cluster/storage/proxy.go:73 Connect) forwards requests to the
member API server, and the unified-auth controller
(pkg/controllers/unifiedauth/unified_auth_controller.go:69) syncs RBAC into
every member so control-plane subjects are authorized there.

Here the proxy hands out a per-cluster handle over the member's store with
the same verbs (get/list/apply/delete), gated by the subjects unified-auth
has synced into that member.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.store.store import Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

# the RBAC object unified-auth maintains inside each member cluster
IMPERSONATION_RBAC_NAME = "karmada-impersonator"


class ProxyDenied(Exception):
    """Subject not authorized on the target cluster (no synced RBAC)."""


class UnifiedAuthController:
    """Syncs the impersonation ClusterRole/Binding into every member
    (unified_auth_controller.go:69): subjects granted cluster-proxy access
    on the control plane become usable through the proxy on every cluster."""

    def __init__(self, store: ObjectStore, runtime: Runtime, members) -> None:
        self.store = store
        self.members = members
        self.subjects: List[str] = ["system:admin"]
        self.worker = runtime.register(AsyncWorker("unified-auth", self._reconcile))
        store.bus.subscribe(self._on_cluster, kind=Cluster.KIND)
        # resync every round: members rebuilt out-of-band (restart
        # rehydration) must regain the impersonation RBAC without waiting
        # for a Cluster event
        runtime.register_periodic(self._resync, name="unified-auth")

    def _resync(self) -> None:
        for c in self.store.list(Cluster.KIND):
            self.worker.enqueue(c.metadata.name)

    def grant(self, subject: str) -> None:
        if subject not in self.subjects:
            self.subjects.append(subject)
        for c in self.store.list(Cluster.KIND):
            self.worker.enqueue(c.name)

    def _on_cluster(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def _reconcile(self, cluster_name: str) -> None:
        member = self.members.get(cluster_name)
        if member is None:
            return
        existing = member.get("ClusterRoleBinding", "", IMPERSONATION_RBAC_NAME)
        if existing is not None:
            have = [s.get("name") for s in existing.manifest.get("subjects") or []]
            if have == list(self.subjects):
                return  # converged: the periodic resync must not churn writes
        member.apply({
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": IMPERSONATION_RBAC_NAME, "namespace": ""},
            "subjects": [{"kind": "User", "name": s} for s in self.subjects],
            "roleRef": {"kind": "ClusterRole", "name": IMPERSONATION_RBAC_NAME},
        })


class ClusterProxy:
    """`ControlPlane.proxy(cluster)`-style handle (proxy.go:73 Connect)."""

    def __init__(self, store: ObjectStore, members, auth: Optional[UnifiedAuthController] = None) -> None:
        self.store = store
        self.members = members
        self.auth = auth

    def connect(self, cluster: str, subject: str = "system:admin") -> "ProxyHandle":
        if self.store.try_get(Cluster.KIND, "", cluster) is None:
            raise ProxyDenied(f"unknown cluster {cluster!r}")
        member = self.members.get(cluster)
        if member is None:
            raise ProxyDenied(f"cluster {cluster!r} has no reachable endpoint")
        if self.auth is not None:
            rbac = member.get("ClusterRoleBinding", "", IMPERSONATION_RBAC_NAME)
            allowed = [
                s.get("name")
                for s in (rbac.manifest.get("subjects") or [])
            ] if rbac is not None else []
            if subject not in allowed:
                raise ProxyDenied(
                    f"subject {subject!r} not authorized on {cluster!r} "
                    "(unified auth not synced)"
                )
        return ProxyHandle(cluster, member)


class ProxyHandle:
    """The member's API surface, reached through the control plane."""

    def __init__(self, cluster: str, member) -> None:
        self.cluster = cluster
        self._member = member

    def get(self, kind: str, namespace: str, name: str) -> Optional[Unstructured]:
        return self._member.get(kind, namespace, name)

    def list(self, kind: str, namespace: Optional[str] = None) -> List[Unstructured]:
        return [
            o for o in self._member.store.list(kind, namespace)
            if isinstance(o, Unstructured)
        ]

    def apply(self, manifest: Dict[str, Any]) -> Unstructured:
        return self._member.apply(manifest)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._member.delete(kind, namespace, name)

    # pod subresources (reference: pods/{log,exec,attach} through the
    # aggregated proxy — pkg/karmadactl/{logs,exec,attach})
    def pods(self, namespace: Optional[str] = None) -> List[Dict[str, Any]]:
        return self._member.list_pods(namespace)

    def logs(self, namespace: str, pod: str,
             tail: Optional[int] = None) -> List[str]:
        return self._member.pod_logs(namespace, pod, tail=tail)

    def exec(self, namespace: str, pod: str, command: List[str]) -> tuple:
        return self._member.pod_exec(namespace, pod, command)
