"""Search-proxy plugin framework (chain-of-responsibility over resources).

Reference: pkg/search/proxy/framework/interface.go (Plugin = Connect +
Order + SupportRequest) with the registry/chain wired in
pkg/search/proxy/controller.go:79-248 — ordered plugins, ONE plugin
handles each request: the first (smallest Order) whose SupportRequest
says yes.  In-tree plugins live in proxy/framework/plugins/{cache,
cluster,karmada}: serve from the multi-cluster cache, forward to a member
cluster, fall back to the karmada control plane.

Same shape as the scheduler's out-of-tree registry
(scheduler/plugins.py): named registration, `*,-Name` enablement, and an
interposition seam — an out-of-tree plugin with a smaller order sees the
request before any in-tree plugin.  Handlers return `(code, payload)`
directly (the repo's query plane speaks JSON-over-HTTP, not http.Handler).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

Handler = Callable[[], Tuple[int, object]]


@dataclass
class ProxyRequest:
    """What the chain routes on (framework.ProxyRequest: GVR + verb +
    request parts)."""

    verb: str                 # get | list
    kind: str = ""
    namespace: str = ""
    name: str = ""
    cluster: str = ""         # "" = not member-scoped
    query: Dict[str, str] = field(default_factory=dict)


class ProxyPlugin:
    """Base plugin: subclass or duck-type (name/order/support/connect)."""

    name = ""
    order = 1000

    def support(self, req: ProxyRequest) -> bool:  # pragma: no cover
        raise NotImplementedError

    def connect(self, req: ProxyRequest) -> Handler:  # pragma: no cover
        raise NotImplementedError


class ProxyPluginRegistry:
    """Ordered plugin chain with `*,-Name` enablement (same flag grammar as
    scheduler --plugins)."""

    def __init__(self) -> None:
        self._plugins: Dict[str, ProxyPlugin] = {}
        self._star = True
        self._on: set = set()
        self._off: set = set()
        self._lock = threading.Lock()

    def register(self, plugin: ProxyPlugin) -> None:
        if not plugin.name:
            raise ValueError("plugin needs a name")
        with self._lock:
            self._plugins[plugin.name] = plugin

    def unregister(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def set_enablement(self, spec: str) -> None:
        star, on, off = False, set(), set()
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if part == "*":
                star = True
            elif part.startswith("-"):
                off.add(part[1:])
            else:
                on.add(part)
        with self._lock:
            self._star, self._on, self._off = star, on, off

    def _enabled(self, name: str) -> bool:
        if name in self._off:
            return False
        return self._star or name in self._on

    def chain(self) -> List[ProxyPlugin]:
        with self._lock:
            enabled = [p for n, p in self._plugins.items() if self._enabled(n)]
        return sorted(enabled, key=lambda p: (p.order, p.name))

    def route(self, req: ProxyRequest) -> Optional[Handler]:
        """First supporting plugin in order wins (controller.go's connect
        walk); None when the chain is exhausted."""
        for plugin in self.chain():
            if plugin.support(req):
                return plugin.connect(req)
        return None


# -- in-tree plugins (proxy/framework/plugins/{cache,cluster,karmada}) ------


class CachePlugin(ProxyPlugin):
    """Serve control-plane-scoped reads of CACHED kinds from the
    multi-cluster cache (plugins/cache: order 0)."""

    name = "Cache"
    order = 0

    def __init__(self, search_cache) -> None:
        self.cache = search_cache

    def support(self, req: ProxyRequest) -> bool:
        return (self.cache is not None and not req.cluster
                and req.verb in ("get", "list")
                and self.cache.has_kind(req.kind))

    def connect(self, req: ProxyRequest) -> Handler:
        def handler():
            cluster = req.query.get("cluster") or None
            if req.verb == "list":
                objs = self.cache.list(req.kind, namespace=req.namespace or None,
                                       cluster=cluster)
                return 200, [o.to_manifest() for o in objs]
            obj = self.cache.get(req.kind, req.namespace, req.name,
                                 cluster=cluster)
            if obj is None:
                return 404, {"error": "not found"}
            return 200, obj.to_manifest()
        return handler


class ClusterPlugin(ProxyPlugin):
    """Forward member-scoped requests to that member through the
    authenticated cluster proxy (plugins/cluster: order 1000)."""

    name = "Cluster"
    order = 1000

    def __init__(self, cluster_proxy) -> None:
        self.proxy = cluster_proxy

    def support(self, req: ProxyRequest) -> bool:
        return bool(req.cluster) and req.verb in ("get", "list")

    def connect(self, req: ProxyRequest) -> Handler:
        def handler():
            from karmada_tpu.search.proxy import ProxyDenied

            try:
                handle = self.proxy.connect(
                    req.cluster, subject=req.query.get("subject",
                                                       "system:admin"))
            except ProxyDenied as e:
                return 403, {"error": str(e)}
            if req.verb == "list":
                return 200, [o.to_manifest()
                             for o in handle.list(req.kind,
                                                  req.namespace or None)]
            obj = handle.get(req.kind, req.namespace, req.name)
            if obj is None:
                return 404, {"error": "not found"}
            return 200, obj.to_manifest()
        return handler


class KarmadaPlugin(ProxyPlugin):
    """Terminal fallback: the karmada control plane's own store
    (plugins/karmada: the largest order, supports everything
    control-plane-scoped)."""

    name = "Karmada"
    order = 2000

    def __init__(self, store) -> None:
        self.store = store

    def support(self, req: ProxyRequest) -> bool:
        return not req.cluster and req.verb in ("get", "list")

    def connect(self, req: ProxyRequest) -> Handler:
        def handler():
            from karmada_tpu.search.httpapi import _manifest_of

            if req.verb == "list":
                objs = self.store.list(req.kind, req.namespace or None)
                return 200, [_manifest_of(o) for o in objs]
            o = self.store.try_get(req.kind, req.namespace, req.name)
            if o is None:
                return 404, {"error": "not found"}
            return 200, _manifest_of(o)
        return handler


def default_registry(store, cluster_proxy, search_cache) -> ProxyPluginRegistry:
    """The in-tree chain the aggregated query plane runs."""
    reg = ProxyPluginRegistry()
    reg.register(CachePlugin(search_cache))
    reg.register(ClusterPlugin(cluster_proxy))
    reg.register(KarmadaPlugin(store))
    return reg
