"""karmada-search: ResourceRegistry-driven multi-cluster resource cache.

Reference: pkg/search/proxy/store/multi_cluster_cache.go (fan-in cache) +
pkg/search/controller.go:79-248 (registry controller building per-cluster
informers for the selected GVKs).

Design: each ResourceRegistry selects (clusters x kinds); the cache
subscribes to every selected member store's watch bus (the framework's
informer equivalent) and maintains a fan-in index keyed by
(kind, cluster, namespace, name).  get/list/watch answer from the index
without touching members; entries carry the origin cluster in the
`resource.karmada.io/cached-from-cluster` annotation exactly like the
reference proxy does.
"""

from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.search import ResourceRegistry
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.store.store import DELETED, Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

CACHED_FROM_ANNOTATION = "resource.karmada.io/cached-from-cluster"


class MultiClusterCache:
    """Fan-in cache + the registry controller driving it."""

    def __init__(self, store: ObjectStore, runtime: Runtime, members) -> None:
        self.store = store
        self.members = members  # name -> FakeMemberCluster
        # (kind, cluster, namespace, name) -> Unstructured (deep copies)
        self._index: Dict[Tuple[str, str, str, str], Unstructured] = {}
        self._lock = threading.Lock()
        # (cluster, kind) -> refcount of registries selecting it
        self._selected: Dict[Tuple[str, str], int] = {}
        self._synced: set = set()  # pairs whose initial list completed
        self._subscribed: set = set()  # clusters whose bus we watch
        self._watchers: List[Callable[[str, Unstructured, str], None]] = []
        # registry name -> (config signature, BackendStore, selected pairs).
        # Instances persist across reconciles (an external client may hold
        # connections/buffers) and each backend only sees ITS registry's
        # (cluster, kind) selections.
        self._backends: Dict[str, Tuple[str, object, set]] = {}
        self.worker = runtime.register(AsyncWorker("search-cache", self._reconcile))
        store.bus.subscribe(self._on_event, kind=ResourceRegistry.KIND)
        store.bus.subscribe(self._on_cluster_event, kind=Cluster.KIND)

    # -- registry reconciliation -------------------------------------------
    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(("sync",))

    def _on_cluster_event(self, event: Event) -> None:
        self.worker.enqueue(("sync",))

    def _reconcile(self, key) -> None:
        """Recompute the (cluster, kind) selection set from all registries
        and (re)build the index for newly selected pairs."""
        from karmada_tpu.search.backend import make_backend

        clusters = self.store.list(Cluster.KIND)
        selected: Dict[Tuple[str, str], int] = {}
        new_backends: Dict[str, Tuple[str, object, set]] = {}
        replay: List[Tuple[object, set]] = []
        for reg in self.store.list(ResourceRegistry.KIND):
            if reg.metadata.deleting:
                continue
            targets = [
                c.name for c in clusters
                if reg.spec.target_cluster.matches(c)
            ]
            pairs = set()
            for sel in reg.spec.resource_selectors:
                for cname in targets:
                    k = (cname, sel.kind)
                    selected[k] = selected.get(k, 0) + 1
                    pairs.add(k)
            sig = repr(reg.spec.backend_store)
            prev = self._backends.get(reg.metadata.name)
            if prev is not None and prev[0] == sig:
                backend = prev[1]
                added_pairs = pairs - prev[2]
            else:
                try:
                    backend = make_backend(reg.spec.backend_store)
                except ValueError:
                    continue  # unknown external backend: cache still serves
                added_pairs = set(pairs)
            new_backends[reg.metadata.name] = (sig, backend, pairs)
            if added_pairs:
                # a backend gaining pairs must receive the EXISTING cached
                # objects for them, like the informer's initial list — not
                # just future deltas
                replay.append((backend, added_pairs))
        self._backends = new_backends
        if replay:
            with self._lock:
                entries = list(self._index.items())
            for (backend, pairs) in replay:
                for (kind, cname, _, _), obj in entries:
                    if (cname, kind) in pairs:
                        backend.upsert(cname, copy.deepcopy(obj))
        with self._lock:
            dropped = set(self._selected) - set(selected)
            self._selected = selected
            self._synced -= dropped
            # purge entries for no-longer-selected pairs
            for (cname, kind) in dropped:
                for ikey in [k for k in self._index
                             if k[0] == kind and k[1] == cname]:
                    del self._index[ikey]
            pending = set(selected) - self._synced
        # subscribe to member buses (once per cluster) + resync only pairs
        # not yet synced — already-watched pairs stay current through the
        # bus, and re-upserting them would fire phantom watch events.
        # (pairs whose member is unreachable stay pending and retry on the
        # next cluster event)
        for (cname, kind) in pending:
            member = self.members.get(cname)
            if member is None:
                continue
            if cname not in self._subscribed:
                self._subscribed.add(cname)
                member.store.bus.subscribe(self._member_event(cname))
            for obj in member.store.list(kind):
                self._upsert(cname, obj)
            with self._lock:
                self._synced.add((cname, kind))

    # -- member informers ---------------------------------------------------
    def _member_event(self, cname: str):
        def handler(event: Event) -> None:
            obj = event.obj
            if not isinstance(obj, Unstructured):
                return
            with self._lock:
                if (cname, obj.KIND) not in self._selected:
                    return
            if event.type == DELETED:
                self._remove(cname, obj)
            else:
                self._upsert(cname, obj)
        return handler

    def _upsert(self, cname: str, obj) -> None:
        if not isinstance(obj, Unstructured):
            return
        cached = copy.deepcopy(obj)
        cached.metadata.annotations[CACHED_FROM_ANNOTATION] = cname
        cached.manifest.setdefault("metadata", {}).setdefault(
            "annotations", {}
        )[CACHED_FROM_ANNOTATION] = cname
        with self._lock:
            self._index[(obj.KIND, cname, obj.namespace, obj.name)] = cached
        for (_, backend, pairs) in list(self._backends.values()):
            if (cname, obj.KIND) in pairs:
                backend.upsert(cname, cached)
        for w in list(self._watchers):
            w("UPSERT", cached, cname)

    def _remove(self, cname: str, obj) -> None:
        with self._lock:
            self._index.pop((obj.KIND, cname, obj.namespace, obj.name), None)
        for (_, backend, pairs) in list(self._backends.values()):
            if (cname, obj.KIND) in pairs:
                backend.delete(cname, obj)
        for w in list(self._watchers):
            w("DELETE", obj, cname)

    # -- query surface (get/list/watch fan-in) ------------------------------
    def get(self, kind: str, namespace: str, name: str,
            cluster: Optional[str] = None) -> Optional[Unstructured]:
        """First match across clusters (or the named cluster's entry)."""
        with self._lock:
            if cluster is not None:
                return copy.deepcopy(self._index.get((kind, cluster, namespace, name)))
            for (k, c, ns, n), obj in sorted(self._index.items()):
                if k == kind and ns == namespace and n == name:
                    return copy.deepcopy(obj)
        return None

    def list(self, kind: str, namespace: Optional[str] = None,
             cluster: Optional[str] = None) -> List[Unstructured]:
        with self._lock:
            return [
                copy.deepcopy(o)
                for (k, c, ns, _), o in sorted(self._index.items())
                if k == kind
                and (namespace is None or ns == namespace)
                and (cluster is None or c == cluster)
            ]

    def has_kind(self, kind: str) -> bool:
        """Whether any registry currently selects this kind (the proxy
        cache plugin's SupportRequest: cached GVRs are served from here,
        everything else falls through the chain)."""
        with self._lock:
            return any(k == kind for (_, k) in self._selected)

    def watch(self, handler: Callable[[str, Unstructured, str], None]) -> None:
        """handler(event_type, obj, cluster) on every cached change."""
        self._watchers.append(handler)

    def unwatch(self, handler: Callable[[str, Unstructured, str], None]) -> None:
        """Detach a watch handler (HTTP watch requests come and go)."""
        try:
            self._watchers.remove(handler)
        except ValueError:
            pass

    def backend_of(self, registry_name: str):
        """The named registry's backend sink (None when absent) — the
        query surface for external backends (e.g. SqliteFTS full-text)."""
        entry = self._backends.get(registry_name)
        return entry[1] if entry is not None else None
