"""sqlite-FTS external search backend.

Reference: pkg/search/backendstore/opensearch.go:127-193 — an external
engine receiving every cached upsert/delete for offboard indexing and
serving full-text queries.  OpenSearch itself is a network service; the
TPU-native framework ships an embedded equivalent with the same sink
contract: one sqlite file per registry, FTS5 when the interpreter's
sqlite has it, plain LIKE matching otherwise.

Config: `BackendStoreConfig(kind="SqliteFTS", addresses=[path])`; the
first address is the database file (":memory:" for ephemeral).
"""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Dict, List, Optional

from karmada_tpu.models.search import BackendStoreConfig
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.search.backend import BackendStore, register_backend_factory


def _flatten_text(value) -> List[str]:
    """Every string in the manifest tree (keys and values) — the indexed
    document body."""
    out: List[str] = []
    if isinstance(value, dict):
        for k, v in value.items():
            out.append(str(k))
            out.extend(_flatten_text(v))
    elif isinstance(value, (list, tuple)):
        for v in value:
            out.extend(_flatten_text(v))
    elif isinstance(value, str):
        out.append(value)
    else:
        out.append(str(value))
    return out


class SqliteFTSBackend(BackendStore):
    """Embedded full-text sink + query engine."""

    def __init__(self, path: str = ":memory:") -> None:
        self.path = path
        # the cache worker thread writes, API threads query
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS docs ("
            " cluster TEXT, kind TEXT, namespace TEXT, name TEXT,"
            " body TEXT, manifest TEXT,"
            " PRIMARY KEY (cluster, kind, namespace, name))")
        self._fts = False
        try:
            self._conn.execute(
                "CREATE VIRTUAL TABLE IF NOT EXISTS docs_fts USING fts5("
                " cluster UNINDEXED, kind UNINDEXED, namespace UNINDEXED,"
                " name UNINDEXED, body)")
            self._fts = True
        except sqlite3.OperationalError:
            pass  # no FTS5 in this sqlite build: LIKE fallback below
        self._conn.commit()

    # -- sink contract ------------------------------------------------------
    def upsert(self, cluster: str, obj: Unstructured) -> None:
        manifest = obj.to_manifest()
        body = " ".join(_flatten_text(manifest))
        key = (cluster, obj.KIND, obj.namespace, obj.name)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO docs VALUES (?,?,?,?,?,?)",
                key + (body, json.dumps(manifest, default=str)))
            if self._fts:
                self._conn.execute(
                    "DELETE FROM docs_fts WHERE cluster=? AND kind=?"
                    " AND namespace=? AND name=?", key)
                self._conn.execute(
                    "INSERT INTO docs_fts VALUES (?,?,?,?,?)", key + (body,))
            self._conn.commit()

    def delete(self, cluster: str, obj: Unstructured) -> None:
        key = (cluster, obj.KIND, obj.namespace, obj.name)
        with self._lock:
            self._conn.execute(
                "DELETE FROM docs WHERE cluster=? AND kind=?"
                " AND namespace=? AND name=?", key)
            if self._fts:
                self._conn.execute(
                    "DELETE FROM docs_fts WHERE cluster=? AND kind=?"
                    " AND namespace=? AND name=?", key)
            self._conn.commit()

    # -- query surface ------------------------------------------------------
    def query(self, text: str, kind: Optional[str] = None,
              cluster: Optional[str] = None, limit: int = 50) -> List[Dict]:
        """Full-text hits: [{cluster, kind, namespace, name, manifest}]."""
        filters, params = [], []
        if kind:
            filters.append("kind = ?")
            params.append(kind)
        if cluster:
            filters.append("cluster = ?")
            params.append(cluster)
        with self._lock:
            if self._fts:
                where = " AND ".join(
                    ["docs_fts MATCH ?"] + [f"d.{f}" for f in filters])
                # quote the user text so FTS5 operators can't inject syntax
                quoted = " ".join(
                    '"' + t.replace('"', '""') + '"' for t in text.split())
                rows = self._conn.execute(
                    "SELECT d.cluster, d.kind, d.namespace, d.name,"
                    " d.manifest FROM docs_fts f"
                    " JOIN docs d ON d.cluster=f.cluster AND d.kind=f.kind"
                    "  AND d.namespace=f.namespace AND d.name=f.name"
                    f" WHERE {where} LIMIT ?",
                    [quoted, *params, limit]).fetchall()
            else:
                like_terms = [f"%{t}%" for t in text.split()]
                where = " AND ".join(
                    ["body LIKE ?"] * len(like_terms) + filters)
                rows = self._conn.execute(
                    "SELECT cluster, kind, namespace, name, manifest"
                    f" FROM docs WHERE {where} LIMIT ?",
                    [*like_terms, *params, limit]).fetchall()
        return [
            {"cluster": c, "kind": k, "namespace": ns, "name": n,
             "object": json.loads(m)}
            for c, k, ns, n, m in rows
        ]

    def count(self) -> int:
        with self._lock:
            return self._conn.execute("SELECT COUNT(*) FROM docs").fetchone()[0]

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def _factory(cfg: BackendStoreConfig) -> SqliteFTSBackend:
    path = cfg.addresses[0] if cfg.addresses else ":memory:"
    return SqliteFTSBackend(path)


register_backend_factory("SqliteFTS", _factory)
