"""Networked external search sink (the OpenSearch-shaped backend).

Reference: pkg/search/backendstore/opensearch.go:127-193 — an OFFBOARD
engine behind a real network protocol receiving every cached
upsert/delete and answering queries.  The repo's framed-TCP transport
(estimator/wire.py: length-prefixed JSON frames, optional TLS) plays the
role of the OpenSearch REST client; any BackendStore (typically the
sqlite-FTS engine, search/fts.py) can be served remotely.

Config: ``BackendStoreConfig(kind="RemoteTCP",
addresses=["host:port", ...])`` — first reachable address wins, like the
reference's multi-address OpenSearch client config.

Server side: ``serve_backend(backend)`` exposes upsert/delete/query/count
as wire methods; run it in the search process or a standalone sidecar.
"""

from __future__ import annotations

from typing import List, Optional

from karmada_tpu.estimator.wire import TcpTransport, serve_tcp
from karmada_tpu.models.search import BackendStoreConfig
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.search.backend import BackendStore, register_backend_factory


def serve_backend(backend: BackendStore, host: str = "127.0.0.1",
                  port: int = 0, ssl_context=None):
    """Serve a local BackendStore over framed TCP; returns the server
    (``server_address`` carries the bound port; ``shutdown()`` stops it)."""

    def dispatch(method: str, body: dict) -> dict:
        if method == "upsert":
            backend.upsert(body["cluster"],
                           Unstructured.from_manifest(body["object"]))
            return {"ok": True}
        if method == "delete":
            backend.delete(body["cluster"],
                           Unstructured.from_manifest(body["object"]))
            return {"ok": True}
        if method == "query":
            if not hasattr(backend, "query"):
                raise RuntimeError("backend is not queryable")
            return {"hits": backend.query(body.get("q", ""),
                                          kind=body.get("kind"),
                                          cluster=body.get("cluster"))}
        if method == "count":
            return {"count": backend.count()
                    if hasattr(backend, "count") else -1}
        raise RuntimeError(f"unknown method {method!r}")

    return serve_tcp(dispatch, host=host, port=port, ssl_context=ssl_context)


class RemoteTcpBackend(BackendStore):
    """Client half: a BackendStore whose sink lives across a socket.

    Delivery is at-least-once per process lifetime with one reconnect
    attempt per call (TcpTransport); a sink outage raises out of
    upsert/delete and the cache logs-and-continues exactly as it would for
    a down OpenSearch."""

    def __init__(self, addresses: List[str], ssl_context=None,
                 timeout: float = 5.0) -> None:
        if not addresses:
            raise ValueError("RemoteTCP backend needs at least one address")
        last: Optional[Exception] = None
        self.transport = None
        for addr in addresses:
            host, _, port = addr.rpartition(":")
            t = TcpTransport(host or "127.0.0.1", int(port),
                             ssl_context=ssl_context, timeout=timeout)
            try:
                t.call("count", {})  # reachability probe
            # vet: ignore[exception-hygiene] kept as the last error; the next address is tried
            except Exception as e:  # noqa: BLE001 — try the next address
                last = e
                continue
            self.transport = t
            break
        if self.transport is None:
            raise ConnectionError(
                f"no reachable sink among {addresses}: {last}")

    def upsert(self, cluster: str, obj: Unstructured) -> None:
        self.transport.call("upsert", {"cluster": cluster,
                                       "object": obj.to_manifest()})

    def delete(self, cluster: str, obj: Unstructured) -> None:
        self.transport.call("delete", {"cluster": cluster,
                                       "object": obj.to_manifest()})

    def query(self, text: str, kind: Optional[str] = None,
              cluster: Optional[str] = None) -> List[dict]:
        return self.transport.call(
            "query", {"q": text, "kind": kind, "cluster": cluster})["hits"]

    def count(self) -> int:
        return int(self.transport.call("count", {})["count"])

    def close(self) -> None:
        self.transport.close()


def _factory(cfg: BackendStoreConfig) -> RemoteTcpBackend:
    return RemoteTcpBackend(cfg.addresses)


register_backend_factory("RemoteTCP", _factory)
