"""Pluggable search backend stores.

Reference: pkg/search/backendstore/{defaultstore,opensearch}.go:127-193 —
each ResourceRegistry may name a backend sink; the default store is the
in-memory cache itself, and external backends (the reference ships an
OpenSearch client) receive every cached upsert/delete for offboard
indexing.  External engines are not bundled here; the seam is the point:
`register_backend_factory("OpenSearch", ...)` plugs one in without
touching the cache.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.models.search import BackendStoreConfig
from karmada_tpu.models.unstructured import Unstructured


class BackendStore:
    """One registry's sink (backendstore.BackendStore)."""

    def upsert(self, cluster: str, obj: Unstructured) -> None:  # pragma: no cover
        raise NotImplementedError

    def delete(self, cluster: str, obj: Unstructured) -> None:  # pragma: no cover
        raise NotImplementedError


class DefaultBackend(BackendStore):
    """The in-memory default (defaultstore.go): the cache IS the store, so
    the sink only needs to exist as a no-op landing point."""

    def upsert(self, cluster: str, obj: Unstructured) -> None:
        pass

    def delete(self, cluster: str, obj: Unstructured) -> None:
        pass


_FACTORIES: Dict[str, Callable[[BackendStoreConfig], BackendStore]] = {
    "Default": lambda cfg: DefaultBackend(),
}


def register_backend_factory(
    kind: str, factory: Callable[[BackendStoreConfig], BackendStore]
) -> None:
    _FACTORIES[kind] = factory


def make_backend(cfg: Optional[BackendStoreConfig]) -> BackendStore:
    cfg = cfg or BackendStoreConfig()
    factory = _FACTORIES.get(cfg.kind)
    if factory is None:
        raise ValueError(
            f"unknown backend store kind {cfg.kind!r} "
            f"(registered: {sorted(_FACTORIES)})"
        )
    return factory(cfg)
