from karmada_tpu.search.cache import CACHED_FROM_ANNOTATION, MultiClusterCache
from karmada_tpu.search.proxy import ClusterProxy, ProxyDenied, UnifiedAuthController
from karmada_tpu.search.metrics_adapter import MultiClusterMetricsProvider
from karmada_tpu.search import fts as _fts  # registers the SqliteFTS factory
from karmada_tpu.search import remote as _remote  # registers RemoteTCP

__all__ = [
    "CACHED_FROM_ANNOTATION",
    "MultiClusterCache",
    "ClusterProxy",
    "ProxyDenied",
    "UnifiedAuthController",
    "MultiClusterMetricsProvider",
]
