"""The query plane served over HTTP.

Reference: the cluster proxy and karmada-search are REAL aggregated HTTP
APIs in the reference (pkg/registry/cluster/storage/proxy.go:73 Connect
forwards `clusters/{name}/proxy/...` to the member apiserver;
pkg/search/proxy serves cache GET/LIST/WATCH; pkg/metricsadapter serves the
custom/external metrics APIs).  This module puts the same surfaces on a TCP
port so external clients (karmadactl --server, curl) can use the plane
without importing it.

Routes (JSON bodies; subject via the `X-Karmada-User` header, default
`system:admin`, checked against the unified-auth synced RBAC exactly like
in-process ClusterProxy.connect):

  GET    /clusters                                   cluster names
  GET    /clusters/{c}/proxy/pods[?namespace=]       member pod plane
  GET    /clusters/{c}/proxy/logs/{ns}/{pod}[?tail=] pod logs
  POST   /clusters/{c}/proxy/exec/{ns}/{pod}         {"command": [...]}
  POST   /clusters/{c}/proxy/apply                   manifest
  GET    /clusters/{c}/proxy/{kind}[?namespace=]     list manifests
  GET    /clusters/{c}/proxy/{kind}/{ns}/{name}      one manifest
  DELETE /clusters/{c}/proxy/{kind}/{ns}/{name}

  GET    /search/cache/{kind}[?namespace=&cluster=]  fan-in list
  GET    /search/cache/{kind}/{ns}/{name}[?cluster=] fan-in get
  GET    /search/watch[?timeout=]                    JSON-lines event stream

  Despite the name, /search/cache serves the FULL proxy plugin chain
  (reference pkg/search/proxy framework semantics): the cache plugin
  answers for kinds a ResourceRegistry selects; anything else falls
  through the chain — cluster-proxy interposers, then the control-plane
  store (KarmadaPlugin) — instead of returning an empty cache miss.
  Clients that must distinguish a member-cluster cache hit from a
  control-plane fallback check the `resource.karmada.io/cached-from-
  cluster` annotation (search.CACHED_FROM_ANNOTATION): cache-served
  objects carry it (naming the member cluster), store-served objects
  never do.

  GET    /metrics-adapter/pods/{kind}/{ns}/{name}    merged PodMetrics
  GET    /metrics-adapter/external/{name}            scalar sample

  GET    /apis                                       API discovery: kinds ->
                                                     storage/served versions
  GET    /api/{kind}[?namespace=&version=]           control-plane manifests
  GET    /api/{kind}[/{ns}]/{name}[?version=]        (at any served version)
  GET    /api-watch/{kind}[?timeout=&version=]       JSON-lines store watch
  POST   /convert                                    {desiredAPIVersion,
                                                     objects[]} (CRD
                                                     conversion-webhook verb)
  POST   /api/apply                                  manifest (typed codec +
                                                     admission; subject-gated,
                                                     403 when served read-only)
  DELETE /api/{kind}[/{ns}]/{name}                   subject-gated
  GET    /api-table/{kind}[?namespace=]              printer table (the
                                                     karmadactl get view)
  GET    /healthz /metrics                           liveness / Prometheus
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Optional
from urllib.parse import parse_qs, urlparse

from karmada_tpu.search.proxy import ProxyDenied


def _manifest_of(obj, version: Optional[str] = None) -> dict:
    """Encode an object for the wire; `version` re-encodes typed models at
    a served API version (models/conversion.py) — the read half of
    multi-version serving."""
    from karmada_tpu.models.codec import registered_kind, to_manifest_typed

    if registered_kind(getattr(obj, "KIND", None)) and not hasattr(
            obj, "to_manifest"):
        return to_manifest_typed(obj, version=version)
    if hasattr(obj, "to_manifest"):
        return obj.to_manifest()
    return json.loads(json.dumps(obj.__dict__, default=str))


class QueryPlaneServer:
    """One ThreadingHTTPServer for the whole query plane."""

    def __init__(self, store, members, cluster_proxy, search_cache=None,
                 metrics_provider=None, registry=None, apply_fn=None,
                 auth=None, proxy_plugins=None) -> None:
        from karmada_tpu.search.proxyframework import default_registry
        from karmada_tpu.utils.metrics import REGISTRY

        self.store = store
        self.members = members
        self.cluster_proxy = cluster_proxy
        self.search_cache = search_cache
        self.metrics_provider = metrics_provider
        self.registry = registry if registry is not None else REGISTRY
        # resource reads route through the proxy plugin chain (cache ->
        # cluster -> karmada, out-of-tree plugins interpose by order);
        # pass a ProxyPluginRegistry to customize
        self.proxy_plugins = (proxy_plugins if proxy_plugins is not None
                              else default_registry(store, cluster_proxy,
                                                    search_cache))
        # control-plane writes (karmadactl --server apply/delete): the
        # plane's apply entry (typed codec + admission); None = read-only.
        # `auth` (UnifiedAuthController) gates writes by the X-Karmada-User
        # subject, same trust root as the cluster-proxy verbs.
        self.apply_fn = apply_fn
        self.auth = auth
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def _write_denied(self, subject: str) -> Optional[str]:
        if self.apply_fn is None:
            return "this plane is served read-only"
        if self.auth is not None and subject not in self.auth.subjects:
            return (f"subject {subject!r} not authorized for control-plane "
                    "writes (unified auth)")
        return None

    # -- request handling ---------------------------------------------------
    def _handle(self, method: str, path: str, query: dict, body: Optional[dict],
                subject: str, stream):
        """Returns (code, payload) or ('stream', generator) for watch."""
        parts = [p for p in path.split("/") if p]

        if method == "GET" and path == "/healthz":
            return 200, {"status": "ok"}
        if method == "GET" and path == "/metrics":
            return 200, self.registry.dump()

        if parts[:1] == ["clusters"] and len(parts) == 1 and method == "GET":
            from karmada_tpu.models.cluster import Cluster

            return 200, [c.name for c in self.store.list(Cluster.KIND)]

        if parts[:1] == ["clusters"] and len(parts) >= 3 and parts[2] == "proxy":
            return self._handle_proxy(method, parts[1], parts[3:], query,
                                      body, subject)

        if parts[:2] == ["search", "cache"] and self.search_cache is not None:
            # resource reads run the proxy plugin chain: the cache plugin
            # serves registry-cached kinds, everything else falls through
            # (cluster / karmada / out-of-tree interposers, by order) — so
            # the cache-named endpoint can legitimately return control-
            # plane store objects; cache hits are distinguishable by the
            # CACHED_FROM_ANNOTATION on each returned object (see module
            # docstring)
            from karmada_tpu.search.proxyframework import ProxyRequest

            flat = {k: v[0] for k, v in query.items()}
            if len(parts) == 3 and method == "GET":
                handler = self.proxy_plugins.route(ProxyRequest(
                    verb="list", kind=parts[2],
                    namespace=flat.get("namespace", ""), query=flat))
                if handler is None:
                    return 404, {"error": "no proxy plugin supports this "
                                          "request"}
                return handler()
            if len(parts) == 5 and method == "GET":
                handler = self.proxy_plugins.route(ProxyRequest(
                    verb="get", kind=parts[2], namespace=parts[3],
                    name=parts[4], query=flat))
                if handler is None:
                    return 404, {"error": "no proxy plugin supports this "
                                          "request"}
                return handler()

        if parts[:2] == ["search", "watch"] and self.search_cache is not None:
            timeout = float((query.get("timeout") or ["5"])[0])
            return "stream", self._watch_stream(timeout)

        if parts[:2] == ["search", "query"] and self.search_cache is not None:
            # full-text query against a registry's external backend
            # (pkg/search REST over the opensearch backendstore)
            reg = (query.get("registry") or [None])[0]
            text = (query.get("q") or [""])[0]
            if not reg or not text:
                return 400, {"error": "registry= and q= required"}
            backend = self.search_cache.backend_of(reg)
            if backend is None or not hasattr(backend, "query"):
                return 404, {"error": f"registry {reg!r} has no queryable "
                                      "backend"}
            return 200, backend.query(
                text,
                kind=(query.get("kind") or [None])[0],
                cluster=(query.get("cluster") or [None])[0])

        if parts[:2] == ["metrics-adapter", "pods"] and len(parts) in (4, 5) \
                and self.metrics_provider is not None:
            # len 4: no workload name -> all of the kind in the namespace
            return 200, self.metrics_provider.pod_metrics(
                parts[2], parts[3], parts[4] if len(parts) == 5 else "")
        if parts[:2] == ["metrics-adapter", "nodes"] and len(parts) == 2 \
                and self.metrics_provider is not None:
            return 200, self.metrics_provider.node_metrics()
        if parts[:2] == ["metrics-adapter", "custom-list"] \
                and self.metrics_provider is not None:
            return 200, self.metrics_provider.list_all_metrics()
        if parts[:2] == ["metrics-adapter", "custom"] and len(parts) == 6 \
                and self.metrics_provider is not None:
            out = self.metrics_provider.custom_metric_by_name(
                parts[2], parts[3], parts[4], parts[5])
            if out is None:
                return 404, {"error": "no such metric"}
            return 200, out
        if parts[:2] == ["metrics-adapter", "custom-selector"] \
                and len(parts) == 5 and self.metrics_provider is not None:
            selector = {
                k: v[0] for k, v in query.items()
                if k not in ("namespace",)
            }
            return 200, self.metrics_provider.custom_metric_by_selector(
                parts[2], parts[3], selector or None, parts[4])
        if parts[:2] == ["metrics-adapter", "external"] and len(parts) == 3 \
                and self.metrics_provider is not None:
            selector = {k: v[0] for k, v in query.items()}
            values = self.metrics_provider.external_metric_values(
                parts[2], selector or None)
            if not values:
                return 404, {"error": "no such metric (or selector matched "
                                      "no samples)"}
            # the scalar aggregate is the sum over the FILTERED samples
            total = sum(float(s.get("value", 0)) for s in values)
            return 200, {"name": parts[2], "value": total, "values": values}

        if parts[:1] == ["api"] and method == "POST" and len(parts) == 2 \
                and parts[1] == "apply":
            denied = self._write_denied(subject)
            if denied:
                return 403, {"error": denied}
            if (not body or "kind" not in body
                    or not (body.get("metadata") or {}).get("name")):
                return 400, {"error": "manifest with kind and metadata.name "
                                      "required"}
            from karmada_tpu.store.store import ConflictError

            last = None
            for _ in range(4):
                # serve mode: controller threads mutate concurrently; a
                # read-modify-write conflict is retryable, not an error
                try:
                    return 200, _manifest_of(self.apply_fn(body))
                except ConflictError as e:
                    last = e
                # vet: ignore[exception-hygiene] admission denial answered as the HTTP error body
                except Exception as e:  # noqa: BLE001 — admission denials
                    return 422, {"error": str(e)}
            return 409, {"error": f"conflict persisted across retries: {last}"}

        if parts[:1] == ["api"] and method == "DELETE" and len(parts) in (3, 4):
            denied = self._write_denied(subject)
            if denied:
                return 403, {"error": denied}
            ns = parts[2] if len(parts) == 4 else ""
            try:
                self.store.delete(parts[1], ns, parts[-1])
            except KeyError:
                return 404, {"error": "not found"}
            # vet: ignore[exception-hygiene] answered as the HTTP error body
            except Exception as e:  # noqa: BLE001
                return 422, {"error": str(e)}
            return 200, {"deleted": True}

        if path == "/apis" and method == "GET":
            # API discovery (the aggregated apiserver's group/version root):
            # every registered kind with its served versions, storage first
            from karmada_tpu.models.codec import model_registry
            from karmada_tpu.models.conversion import REGISTRY as conv

            return 200, {
                kind: {"storageVersion": cls.API_VERSION,
                       "servedVersions": conv.served_versions(kind)}
                for kind, cls in sorted(model_registry().items())
            }

        if parts[:1] == ["api"] and method == "GET" and len(parts) >= 2:
            ns = (query.get("namespace") or [None])[0]
            # ?version= serves any registered API version of the kind
            # (multi-version read; models/conversion.py)
            version = (query.get("version") or [None])[0]
            if version is not None:
                from karmada_tpu.models.conversion import REGISTRY as conv

                if not conv.served(parts[1], version):
                    return 400, {"error": f"{parts[1]} is not served at "
                                          f"{version!r}; served: "
                                          f"{conv.served_versions(parts[1])}"}
            if len(parts) == 2:
                objs = self.store.list(parts[1], ns)
                return 200, [_manifest_of(o, version) for o in objs]
            if len(parts) in (3, 4):
                # len 3: cluster-scoped get (empty namespace)
                get_ns = parts[2] if len(parts) == 4 else ""
                o = self.store.try_get(parts[1], get_ns, parts[-1])
                if o is None:
                    return 404, {"error": "not found"}
                return 200, _manifest_of(o, version)

        if parts[:1] == ["api-watch"] and len(parts) == 2 and method == "GET":
            # control-plane store WATCH, servable at any registered version.
            # Validate the version HERE: the watch handler runs on store
            # writer threads, where a conversion KeyError would break
            # control-plane writes, not just this request.
            timeout = float((query.get("timeout") or ["5"])[0])
            version = (query.get("version") or [None])[0]
            if version is not None:
                from karmada_tpu.models.conversion import REGISTRY as conv

                if not conv.served(parts[1], version):
                    return 400, {"error": f"{parts[1]} is not served at "
                                          f"{version!r}; served: "
                                          f"{conv.served_versions(parts[1])}"}
            return "stream", self._store_watch_stream(
                parts[1], timeout, version)

        if path == "/convert" and method == "POST":
            # the CRD conversion-webhook verb (ConversionReview equivalent:
            # desiredAPIVersion + objects in, converted objects out)
            from karmada_tpu.models.conversion import REGISTRY as conv

            desired = (body or {}).get("desiredAPIVersion")
            objs = (body or {}).get("objects")
            if not desired or not isinstance(objs, list):
                return 400, {"error": "desiredAPIVersion and objects[] "
                                      "required"}
            converted = []
            for m in objs:
                try:
                    converted.append(conv.convert(m, desired))
                except KeyError as e:
                    return 422, {"error": str(e)}
            return 200, {"objects": converted}

        if parts[:1] == ["api-table"] and len(parts) == 2 and method == "GET":
            from karmada_tpu.printers import table_for

            ns = (query.get("namespace") or [None])[0]
            objs = self.store.list(parts[1], ns)
            headers, rows = table_for(parts[1], objs)
            return 200, {"headers": headers,
                         "rows": [[str(c) for c in r] for r in rows]}

        return 404, {"error": f"no route for {method} {path}"}

    def _handle_proxy(self, method, cluster, rest, query, body, subject):
        ns = (query.get("namespace") or [None])[0]
        # resource GETs run the proxy plugin chain (the ClusterPlugin does
        # its own authenticated connect); the chain exhausting means no
        # plugin — in-tree or interposed — claimed the request
        if method == "GET" and len(rest) in (1, 2, 3) and rest[:1] not in (
                ["pods"], ["logs"]):
            from karmada_tpu.search.proxyframework import ProxyRequest

            if len(rest) == 1:
                req = ProxyRequest(verb="list", kind=rest[0],
                                   namespace=ns or "", cluster=cluster,
                                   query={"subject": subject})
            else:
                # len 2: cluster-scoped get (empty namespace)
                req = ProxyRequest(verb="get", kind=rest[0],
                                   namespace=rest[1] if len(rest) == 3 else "",
                                   name=rest[-1], cluster=cluster,
                                   query={"subject": subject})
            handler_fn = self.proxy_plugins.route(req)
            if handler_fn is None:
                return 404, {"error": "no proxy plugin supports this request"}
            return handler_fn()
        try:
            handle = self.cluster_proxy.connect(cluster, subject=subject)
        except ProxyDenied as e:
            return 403, {"error": str(e)}
        if method == "GET" and rest[:1] == ["pods"]:
            return 200, handle.pods(ns)
        if method == "GET" and rest[:1] == ["logs"] and len(rest) == 3:
            tail = query.get("tail")
            try:
                lines = handle.logs(rest[1], rest[2],
                                    tail=int(tail[0]) if tail else None)
            # vet: ignore[exception-hygiene] answered as the HTTP error body
            except Exception as e:  # noqa: BLE001 — pod not found
                return 404, {"error": str(e)}
            return 200, {"lines": lines}
        if method == "POST" and rest[:1] == ["exec"] and len(rest) == 3:
            command = (body or {}).get("command") or []
            try:
                rc, out = handle.exec(rest[1], rest[2], command)
            # vet: ignore[exception-hygiene] answered as the HTTP error body
            except Exception as e:  # noqa: BLE001
                return 404, {"error": str(e)}
            return 200, {"rc": rc, "output": out}
        if method == "POST" and rest[:1] == ["apply"]:
            if not body:
                return 400, {"error": "manifest body required"}
            obj = handle.apply(body)
            return 200, obj.to_manifest()
        if method == "DELETE" and len(rest) in (2, 3):
            handle.delete(rest[0], rest[1] if len(rest) == 3 else "",
                          rest[-1])
            return 200, {"deleted": True}
        return 404, {"error": f"no proxy route for {method} /{'/'.join(rest)}"}

    def _store_watch_stream(self, kind: str, timeout: float,
                            version: Optional[str]):
        """JSON-lines watch over control-plane store events for one kind,
        each object encoded at the requested served version."""
        q: "queue.Queue" = queue.Queue()

        def handler(event) -> None:
            if event.kind == kind:
                q.put({"type": event.type,
                       "object": _manifest_of(event.obj, version)})

        self.store.bus.subscribe(handler)

        def gen():
            deadline = time.monotonic() + timeout
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    try:
                        item = q.get(timeout=min(remaining, 0.25))
                    except queue.Empty:
                        continue
                    yield (json.dumps(item, default=str) + "\n").encode()
            finally:
                self.store.bus.unsubscribe(handler)

        return gen()

    def _watch_stream(self, timeout: float):
        """JSON-lines generator over cache events for up to `timeout` s
        (the aggregated-API WATCH verb, chunked)."""
        q: "queue.Queue" = queue.Queue()

        def handler(event_type, obj, cluster):
            q.put({"type": event_type, "cluster": cluster,
                   "object": obj.to_manifest()})

        self.search_cache.watch(handler)

        def gen():
            deadline = time.monotonic() + timeout
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return
                    try:
                        item = q.get(timeout=min(remaining, 0.25))
                    except queue.Empty:
                        continue
                    yield (json.dumps(item) + "\n").encode()
            finally:
                self.search_cache.unwatch(handler)

        return gen()

    # -- server lifecycle ---------------------------------------------------
    def start(self, port: int = 0, host: str = "127.0.0.1") -> str:
        import http.server

        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _run(self, method):
                u = urlparse(self.path)
                query = parse_qs(u.query)
                subject = self.headers.get("X-Karmada-User", "system:admin")
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    try:
                        body = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError:
                        self._send(400, {"error": "invalid JSON body"})
                        return
                try:
                    result = outer._handle(method, u.path, query, body,
                                           subject, self)
                # vet: ignore[exception-hygiene] surfaced as a watch error frame to the client
                except Exception as e:  # noqa: BLE001 — surface, don't die
                    self._send(500, {"error": repr(e)})
                    return
                if result[0] == "stream":
                    self.send_response(200)
                    self.send_header("Content-Type", "application/jsonlines")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    for chunk in result[1]:
                        self.wfile.write(
                            f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                    return
                self._send(*result)

            def _send(self, code, payload):
                if isinstance(payload, str):
                    body = payload.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = json.dumps(payload, default=str).encode()
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                self._run("GET")

            def do_POST(self):  # noqa: N802
                self._run("POST")

            def do_DELETE(self):  # noqa: N802
                self._run("DELETE")

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        h, p = self._httpd.server_address
        return f"http://{h}:{p}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
