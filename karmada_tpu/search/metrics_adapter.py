"""Multi-cluster metrics provider (karmada-metrics-adapter).

Reference: pkg/metricsadapter/provider/{resourcemetrics,custommetrics,
externalmetrics}.go — implements metrics.k8s.io / custom.metrics.k8s.io /
external.metrics.k8s.io by querying every relevant member cluster and
merging.  The FederatedHPA controller consumes this exact surface.

Here the provider fans out to the member simulators' pod-metrics endpoints
and merges, keeping the reference's shape: a list of per-pod samples with
usage + request, tagged with the origin cluster.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class MultiClusterMetricsProvider:
    def __init__(self, members) -> None:
        self.members = members  # name -> FakeMemberCluster
        # external metric series: name -> value (pluggable for tests)
        self.external: Dict[str, float] = {}

    def pod_metrics(
        self,
        kind: str,
        namespace: str,
        name: str,
        clusters: Optional[List[str]] = None,
    ) -> List[dict]:
        """Merged PodMetrics for a workload across `clusters` (default all):
        [{"name", "cluster", "usage": {res: milli}, "request": {res: milli}}]
        (resourcemetrics.go GetPodMetrics fan-out + merge)."""
        out: List[dict] = []
        targets = clusters if clusters is not None else list(self.members)
        for cname in targets:
            member = self.members.get(cname)
            if member is None or not member.healthy:
                continue
            for pm in member.pod_metrics(kind, namespace, name):
                sample = dict(pm)
                sample["cluster"] = cname
                out.append(sample)
        return out

    def external_metric(self, name: str) -> Optional[float]:
        """externalmetrics.go GetExternalMetric (test-pluggable series)."""
        return self.external.get(name)
