"""Multi-cluster metrics provider (karmada-metrics-adapter).

Reference: pkg/metricsadapter/provider/{resourcemetrics,custommetrics,
externalmetrics}.go — implements metrics.k8s.io / custom.metrics.k8s.io /
external.metrics.k8s.io by querying every relevant member cluster and
merging.  The FederatedHPA controller consumes this exact surface.

All three provider families fan out to the member simulators and merge,
keeping the reference's shapes:
  * resource metrics: per-pod usage+request samples and per-node usage,
    tagged with the origin cluster (resourcemetrics.go GetPodMetrics /
    GetNodeMetrics);
  * custom metrics: object-scoped series queried by name or by label
    selector across members, merged with per-cluster samples plus the
    summed value (custommetrics.go GetMetricByName/GetMetricBySelector/
    ListAllMetrics);
  * external metrics: labeled series filtered by a metric selector
    (externalmetrics.go GetExternalMetric).
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _labels_match(selector: Optional[Dict[str, str]],
                  labels: Dict[str, str]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class MultiClusterMetricsProvider:
    def __init__(self, members) -> None:
        self.members = members  # name -> FakeMemberCluster
        # external metric series: name -> scalar (back-compat) OR a list of
        # {"labels": {...}, "value": float} samples (pluggable for tests)
        self.external: Dict[str, object] = {}

    def pod_metrics(
        self,
        kind: str,
        namespace: str,
        name: str,
        clusters: Optional[List[str]] = None,
    ) -> List[dict]:
        """Merged PodMetrics for a workload across `clusters` (default all):
        [{"name", "cluster", "usage": {res: milli}, "request": {res: milli}}]
        (resourcemetrics.go GetPodMetrics fan-out + merge)."""
        out: List[dict] = []
        targets = clusters if clusters is not None else list(self.members)
        for cname in targets:
            member = self.members.get(cname)
            if member is None or not member.healthy:
                continue
            for pm in member.pod_metrics(kind, namespace, name):
                sample = dict(pm)
                sample["cluster"] = cname
                out.append(sample)
        return out

    def node_metrics(self, clusters: Optional[List[str]] = None) -> List[dict]:
        """Merged NodeMetrics across members (resourcemetrics.go
        GetNodeMetrics): usage apportioned over each member's nodes by
        their share of the member's cpu capacity."""
        out: List[dict] = []
        targets = clusters if clusters is not None else list(self.members)
        for cname in targets:
            member = self.members.get(cname)
            if member is None or not member.healthy:
                continue
            used = member.used_milli()
            nodes = member.effective_nodes()
            total_cpu = max(sum(n.cpu_milli for n in nodes), 1)
            for n in nodes:
                share = n.cpu_milli / total_cpu
                out.append({
                    "name": n.name, "cluster": cname,
                    "usage": {res: int(v * share) for res, v in used.items()},
                    "allocatable": {"cpu": n.cpu_milli,
                                    "memory": n.memory_milli,
                                    "pods": n.pods},
                })
        return out

    # -- custom.metrics.k8s.io ----------------------------------------------
    def custom_metric_by_name(self, kind: str, namespace: str, name: str,
                              metric: str,
                              clusters: Optional[List[str]] = None) -> Optional[dict]:
        """custommetrics.go GetMetricByName: query every member for the
        object's series and merge — per-cluster samples plus the summed
        value (the reference returns the multi-cluster aggregate)."""
        samples = []
        targets = clusters if clusters is not None else list(self.members)
        for cname in targets:
            member = self.members.get(cname)
            if member is None or not member.healthy:
                continue
            v = member.custom_metrics.get((kind, namespace, name, metric))
            if v is not None:
                samples.append({"cluster": cname, "value": float(v)})
        if not samples:
            return None
        return {"metric": metric, "kind": kind, "namespace": namespace,
                "name": name, "value": sum(s["value"] for s in samples),
                "samples": samples}

    def custom_metric_by_selector(self, kind: str, namespace: str,
                                  selector: Optional[Dict[str, str]],
                                  metric: str) -> List[dict]:
        """custommetrics.go GetMetricBySelector: objects of `kind` in
        `namespace` matching the label selector, across all members."""
        out: List[dict] = []
        seen = set()
        for cname, member in self.members.items():
            if not member.healthy:
                continue
            for (k, ns, name, m), _v in member.custom_metrics.items():
                if k != kind or ns != namespace or m != metric:
                    continue
                obj = member.get(kind, ns, name)
                labels = (obj.metadata.labels if obj is not None else {})
                if not _labels_match(selector, labels):
                    continue
                if (ns, name) in seen:
                    continue
                seen.add((ns, name))
                merged = self.custom_metric_by_name(kind, ns, name, metric)
                if merged is not None:
                    out.append(merged)
        return out

    def list_all_metrics(self) -> List[str]:
        """custommetrics.go ListAllMetrics: every metric name any member
        serves, deduplicated."""
        names = set()
        for member in self.members.values():
            for (_k, _ns, _n, metric) in member.custom_metrics:
                names.add(metric)
        return sorted(names)

    # -- external.metrics.k8s.io --------------------------------------------
    def external_metric(self, name: str) -> Optional[float]:
        """externalmetrics.go GetExternalMetric, scalar view (sums labeled
        samples; back-compat for scalar series)."""
        series = self.external.get(name)
        if series is None:
            return None
        if isinstance(series, (int, float)):
            return float(series)
        return sum(float(s.get("value", 0)) for s in series)

    def external_metric_values(self, name: str,
                               selector: Optional[Dict[str, str]] = None) -> List[dict]:
        """Labeled external samples filtered by the metric selector."""
        series = self.external.get(name)
        if series is None:
            return []
        if isinstance(series, (int, float)):
            samples = [{"labels": {}, "value": float(series)}]
        else:
            samples = [dict(s) for s in series]
        return [s for s in samples
                if _labels_match(selector, s.get("labels") or {})]
