"""Cluster lease heartbeats + staleness monitor.

Reference: the cluster-status controller renews a coordination.k8s.io Lease
per cluster in the karmada-cluster namespace (cluster_status_controller.go:399
initLeaseController), and the control plane monitors lease freshness —
conditions tell you the MEMBER's health, the lease tells you the
COLLECTOR's liveness (a dead karmada-agent or status controller must not
leave a stale "Ready" cluster schedulable forever).

When a lease goes stale past `grace_multiplier x lease_duration`, the
monitor flips the cluster's Ready condition to Unknown
(ClusterStatusUnknown), which the condition-driven taint machinery
(controllers/failover.py TaintClusterByCondition) turns into a NoExecute
NotReady taint exactly as for an observed failure.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from karmada_tpu.models.cluster import COND_CLUSTER_READY, Cluster
from karmada_tpu.models.meta import (
    Condition,
    ObjectMeta,
    TypedObject,
    get_condition,
    set_condition,
)
from karmada_tpu.store.store import NotFoundError, ObjectStore

LEASE_NAMESPACE = "karmada-cluster"


@dataclass
class Lease(TypedObject):
    """coordination.k8s.io/v1 Lease, trimmed to the fields the cluster
    heartbeat uses."""

    KIND = "Lease"
    API_VERSION = "coordination.k8s.io/v1"

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration_s: float = 10.0


def renew_cluster_lease(
    store: ObjectStore,
    cluster_name: str,
    holder: str = "cluster-status-controller",
    lease_duration_s: float = 10.0,
    clock: Callable[[], float] = time.time,
) -> None:
    """Create-or-renew the cluster's lease (the collector's heartbeat)."""
    from karmada_tpu import chaos

    if chaos.armed():
        f = chaos.fire(chaos.SITE_LEASE_HEARTBEAT, cluster=cluster_name)
        if f is not None and f.mode == "drop":
            # a suppressed heartbeat is indistinguishable from a dead
            # collector: the lease ages out, the monitor flips Ready to
            # Unknown, and the taint/eviction chain takes over — exactly
            # the failure path the chaos soak exists to exercise
            return
    now = clock()
    try:
        def bump(lease: Lease) -> None:
            lease.holder = holder
            lease.renew_time = now
            lease.lease_duration_s = lease_duration_s
        store.mutate(Lease.KIND, LEASE_NAMESPACE, cluster_name, bump)
    except NotFoundError:
        store.create(Lease(
            metadata=ObjectMeta(namespace=LEASE_NAMESPACE, name=cluster_name),
            holder=holder,
            renew_time=now,
            lease_duration_s=lease_duration_s,
        ))


class ClusterLeaseMonitor:
    """Periodic staleness check: no renewal within grace -> Ready Unknown.

    Mirrors the reference's clusterMonitorGracePeriod behavior: the monitor
    only DEGRADES (Ready -> Unknown); recovery is owned by the status
    collector's next successful heartbeat, which also renews the lease."""

    def __init__(
        self,
        store: ObjectStore,
        runtime,
        grace_multiplier: float = 4.0,
        clock: Callable[[], float] = time.time,
        recorder=None,
    ) -> None:
        from karmada_tpu.utils.events import EventRecorder

        self.store = store
        self.runtime = runtime
        self.grace_multiplier = grace_multiplier
        self.clock = clock
        self.recorder = recorder if recorder is not None else EventRecorder()
        runtime.register_periodic(self.check_all, name="cluster-lease")

    def check_all(self) -> None:
        from karmada_tpu.utils import events as ev

        now = self.clock()
        # renewals happen once per periodic round: a sync period longer
        # than the lease duration must widen the grace window, or a slow
        # but healthy collector would flap its clusters to Unknown
        interval = getattr(self.runtime, "_periodic_interval_s", 0.0)
        for cluster in self.store.list(Cluster.KIND):
            name = cluster.metadata.name
            lease = self.store.try_get(Lease.KIND, LEASE_NAMESPACE, name)
            if lease is None:
                continue  # no collector has ever reported; nothing to age out
            window = self.grace_multiplier * max(lease.lease_duration_s, interval)
            if now - lease.renew_time <= window:
                continue
            cond = get_condition(cluster.status.conditions, COND_CLUSTER_READY)
            if cond is not None and cond.status == "Unknown":
                continue

            def degrade(c: Cluster) -> None:
                set_condition(c.status.conditions, Condition(
                    type=COND_CLUSTER_READY,
                    status="Unknown",
                    reason="ClusterStatusUnknown",
                    message="cluster status collector stopped heartbeating",
                ))
            try:
                stored = self.store.mutate(Cluster.KIND, "", name, degrade)
            except NotFoundError:
                continue
            self.recorder.event(
                stored, ev.TYPE_WARNING, ev.REASON_CLUSTER_STATUS_UNKNOWN,
                f"lease for cluster {name} not renewed within grace period",
                origin="cluster-lease",
            )
