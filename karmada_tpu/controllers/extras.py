"""Auxiliary controllers: rebalancer, condition-driven taints, remedy, quota.

* WorkloadRebalancerController -- pkg/controllers/workloadrebalancer/
  workloadrebalancer_controller.go:78: stamps rescheduleTriggeredAt on each
  listed workload's binding so the scheduler runs a Fresh re-assignment.
* ClusterTaintPolicyController -- pkg/controllers/taint/
  clustertaintpolicy_controller.go:60: condition-matched taint add/remove.
* RemedyController -- pkg/controllers/remediation/remedy_controller.go:51:
  Remedy x cluster conditions -> cluster.status.remedyActions.
* FederatedResourceQuotaController -- pkg/controllers/federatedresourcequota/
  *.go:65-68: static per-cluster quota split rendered into per-cluster
  ResourceQuota Works + usage aggregation into the FRQ status.
"""

from __future__ import annotations

import time
from typing import Dict, List

from karmada_tpu.controllers.binding import execution_namespace
from karmada_tpu.controllers.detector import binding_name
from karmada_tpu.models.cluster import Cluster, Taint
from karmada_tpu.models.extras import (
    ClusterQuotaStatus,
    ClusterTaintPolicy,
    FederatedResourceQuota,
    MatchCondition,
    ObservedWorkload,
    Remedy,
    WorkloadRebalancer,
)
from karmada_tpu.models.meta import get_condition
from karmada_tpu.models.work import ResourceBinding, Work, WorkSpec
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime
from karmada_tpu.utils.quantity import Quantity


class WorkloadRebalancerController:
    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("rebalancer", self._reconcile))
        store.bus.subscribe(self._on_event, kind=WorkloadRebalancer.KIND)

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def _reconcile(self, name) -> None:
        wr = self.store.try_get(WorkloadRebalancer.KIND, "", name)
        if wr is None or wr.status.finish_time is not None:
            return
        observed: List[ObservedWorkload] = []
        now = time.time()
        for ref in wr.spec.workloads:
            rb_name = binding_name(ref.kind, ref.name)
            rb = self.store.try_get(ResourceBinding.KIND, ref.namespace, rb_name)
            if rb is None:
                observed.append(ObservedWorkload(workload=ref, result="NotFound"))
                continue

            def trigger(obj: ResourceBinding) -> None:
                obj.spec.reschedule_triggered_at = now

            try:
                self.store.mutate(ResourceBinding.KIND, ref.namespace, rb_name, trigger)
                observed.append(ObservedWorkload(workload=ref, result="Successful"))
            except NotFoundError:
                observed.append(ObservedWorkload(workload=ref, result="NotFound"))

        def finish(obj: WorkloadRebalancer) -> None:
            obj.status.observed_workloads = observed
            obj.status.finish_time = now

        self.store.mutate(WorkloadRebalancer.KIND, "", name, finish)


def _condition_matches(cluster: Cluster, matches: List[MatchCondition]) -> bool:
    """All matchConditions must hold (clustertaintpolicy semantics)."""
    if not matches:
        return False
    for m in matches:
        cond = get_condition(cluster.status.conditions, m.condition_type)
        status = cond.status if cond is not None else "Unknown"
        if m.operator == "In" and status not in m.status_values:
            return False
        if m.operator == "NotIn" and status in m.status_values:
            return False
    return True


class ClusterTaintPolicyController:
    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("taint-policy", self._reconcile))
        store.bus.subscribe(self._on_event)

    def _on_event(self, event: Event) -> None:
        if event.kind == Cluster.KIND:
            self.worker.enqueue(event.obj.name)
        elif event.kind == ClusterTaintPolicy.KIND:
            for c in self.store.list(Cluster.KIND):
                self.worker.enqueue(c.name)

    def _reconcile(self, cluster_name) -> None:
        cluster = self.store.try_get(Cluster.KIND, "", cluster_name)
        if cluster is None:
            return
        add: Dict[tuple, Taint] = {}
        remove: set = set()
        for policy in self.store.list(ClusterTaintPolicy.KIND):
            spec = policy.spec
            if spec.target_clusters is not None and not spec.target_clusters.matches(
                cluster
            ):
                continue
            for t in spec.taints:
                key = (t.key, t.effect)
                if _condition_matches(cluster, spec.add_on_conditions):
                    add[key] = Taint(key=t.key, value=t.value, effect=t.effect,
                                     time_added=time.time())
                elif _condition_matches(cluster, spec.remove_on_conditions):
                    remove.add(key)
        if not add and not remove:
            return

        def update(c: Cluster) -> None:
            existing = {(t.key, t.effect): t for t in c.spec.taints}
            for key, taint in add.items():
                if key not in existing:
                    existing[key] = taint
            for key in remove:
                if key not in add:
                    existing.pop(key, None)
            c.spec.taints = sorted(existing.values(), key=lambda t: (t.key, t.effect))

        self.store.mutate(Cluster.KIND, "", cluster_name, update)


class RemedyController:
    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("remedy", self._reconcile))
        store.bus.subscribe(self._on_event)

    def _on_event(self, event: Event) -> None:
        if event.kind == Cluster.KIND:
            self.worker.enqueue(event.obj.name)
        elif event.kind == Remedy.KIND:
            for c in self.store.list(Cluster.KIND):
                self.worker.enqueue(c.name)

    def _reconcile(self, cluster_name) -> None:
        cluster = self.store.try_get(Cluster.KIND, "", cluster_name)
        if cluster is None:
            return
        actions: set = set()
        for remedy in self.store.list(Remedy.KIND):
            spec = remedy.spec
            if spec.cluster_affinity is not None and not spec.cluster_affinity.matches(
                cluster
            ):
                continue
            if not spec.decision_matches:
                actions.update(spec.actions)  # unconditional remedy
                continue
            for dm in spec.decision_matches:
                cond = get_condition(
                    cluster.status.conditions, dm.cluster_condition_type
                )
                if cond is not None and cond.status == dm.cluster_condition_status:
                    actions.update(spec.actions)
                    break
        wanted = sorted(actions)
        if cluster.status.remedy_actions == wanted:
            return

        def update(c: Cluster) -> None:
            c.status.remedy_actions = wanted

        self.store.mutate(Cluster.KIND, "", cluster_name, update)


class FederatedResourceQuotaController:
    """Static split -> per-cluster ResourceQuota Works + usage aggregation.

    Overall-only quotas (no static assignments) follow the reference's
    enforcement controller instead (federated_resource_quota_enforcement_
    controller.go:239 collectQuotaStatus): status.overallUsed is
    recalculated from the namespace's ResourceBindings, reconciling on FRQ
    changes and on every binding change in the namespace."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("frq", self._reconcile))
        store.bus.subscribe(self._on_event, kind=FederatedResourceQuota.KIND)
        store.bus.subscribe(self._on_binding_event, kind="ResourceBinding")

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _on_binding_event(self, event: Event) -> None:
        ns = event.obj.namespace
        for frq in self.store.list(FederatedResourceQuota.KIND, ns):
            self.worker.enqueue((ns, frq.metadata.name))

    def _work_id(self, ns: str, name: str) -> str:
        return f"resourcequota-{ns}-{name}"

    def _reconcile(self, key) -> None:
        ns, name = key
        frq = self.store.try_get(FederatedResourceQuota.KIND, ns, name)
        if frq is None or frq.metadata.deleting:
            for c in self.store.list(Cluster.KIND):
                try:
                    self.store.delete(
                        Work.KIND, execution_namespace(c.name), self._work_id(ns, name)
                    )
                except NotFoundError:
                    pass
            return
        assigned_clusters = {a.cluster_name for a in frq.spec.static_assignments}
        # drop Works for clusters no longer in the static assignment list
        for c in self.store.list(Cluster.KIND):
            if c.name in assigned_clusters:
                continue
            try:
                self.store.delete(
                    Work.KIND, execution_namespace(c.name), self._work_id(ns, name)
                )
            except NotFoundError:
                pass
        for assignment in frq.spec.static_assignments:
            manifest = {
                "apiVersion": "v1",
                "kind": "ResourceQuota",
                "metadata": {"name": name, "namespace": ns},
                "spec": {"hard": {k: str(v) for k, v in assignment.hard.items()}},
            }
            wns = execution_namespace(assignment.cluster_name)
            wid = self._work_id(ns, name)
            existing = self.store.try_get(Work.KIND, wns, wid)
            if existing is None:
                w = Work()
                w.metadata.namespace = wns
                w.metadata.name = wid
                w.spec = WorkSpec(workload=[manifest])
                self.store.create(w)
            else:
                def update(w: Work) -> None:
                    w.spec.workload = [manifest]
                self.store.mutate(Work.KIND, wns, wid, update)

        # overall-only quota: recalculate overallUsed from the namespace's
        # ResourceBindings (collectQuotaStatus), the same usage math the
        # admission gate applies — the two converge on the same number
        if not frq.spec.static_assignments:
            from karmada_tpu.webhook.builtin import calculate_rb_usage

            overall_used = {}
            for rb in self.store.list("ResourceBinding", ns):
                for k, milli in calculate_rb_usage(rb).items():
                    overall_used[k] = Quantity(
                        overall_used.get(k, Quantity(0)).milli + milli
                    )

            def set_overall(obj: FederatedResourceQuota) -> None:
                obj.status.overall = dict(obj.spec.overall)
                obj.status.overall_used = overall_used
                obj.status.aggregated_status = []

            self.store.mutate(FederatedResourceQuota.KIND, ns, name, set_overall)
            return

        # aggregate usage from the member-side ResourceQuota statuses
        agg: List = []
        overall_used: Dict[str, Quantity] = {}
        for assignment in frq.spec.static_assignments:
            w = self.store.try_get(
                Work.KIND, execution_namespace(assignment.cluster_name),
                self._work_id(ns, name),
            )
            used: Dict[str, Quantity] = {}
            if w is not None:
                for ms in w.status.manifest_statuses:
                    for k, v in ((ms.status or {}).get("used") or {}).items():
                        used[k] = Quantity.parse(v)
            agg.append(ClusterQuotaStatus(
                cluster_name=assignment.cluster_name,
                hard=dict(assignment.hard), used=used,
            ))
            for k, v in used.items():
                overall_used[k] = overall_used.get(k, Quantity(0)) + v

        def set_status(obj: FederatedResourceQuota) -> None:
            obj.status.overall = dict(obj.spec.overall)
            obj.status.overall_used = overall_used
            obj.status.aggregated_status = agg

        self.store.mutate(FederatedResourceQuota.KIND, ns, name, set_status)
