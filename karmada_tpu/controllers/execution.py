"""Execution controller + object watcher: apply Work to member clusters.

Mirrors reference pkg/controllers/execution/execution_controller.go:82-160
(gate on cluster Ready + dispatch suspension, then sync manifests) and
pkg/util/objectwatcher/objectwatcher.go:57-330 (create/update with retained
member-side fields and ConflictResolution overwrite/abort).  The member
"API server" here is a FakeMemberCluster; real clients slot in behind the
same apply interface.
"""

from __future__ import annotations

import time

from typing import Dict, Optional

from karmada_tpu.controllers.binding import (
    EXECUTION_NS_PREFIX,
    WORK_BINDING_LABEL,
    execution_namespace,
)
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import Condition, deep_get, set_condition
from karmada_tpu.models.work import COND_WORK_APPLIED, Work
from karmada_tpu.store.store import DELETED, Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime
from karmada_tpu.utils import events as ev
from karmada_tpu.utils.metrics import REGISTRY, exponential_buckets

# execution_controller.go:154 metrics.ObserveSyncWorkloadLatency
SYNC_WORKLOAD_LATENCY = REGISTRY.histogram(
    "karmada_work_sync_workload_duration_seconds",
    "Duration in seconds to sync a Work's manifests to its member cluster",
    ("result",),
    buckets=exponential_buckets(0.001, 2, 12),
)

# annotation carrying the conflict policy down to the apply engine
CONFLICT_ANNOTATION = "work.karmada.io/conflict-resolution"


class ObjectWatcher:
    """Apply engine for one member cluster (objectwatcher.go:57)."""

    def __init__(self, interpreter: ResourceInterpreter) -> None:
        self.interpreter = interpreter
        # version records: (cluster, kind, ns, name) -> member resourceVersion
        self._versions: Dict[tuple, int] = {}

    def create_or_update(
        self, member: FakeMemberCluster, manifest: Dict, conflict_resolution: str
    ) -> None:
        kind = manifest.get("kind", "")
        ns = deep_get(manifest, "metadata.namespace", "")
        name = deep_get(manifest, "metadata.name", "")
        observed = member.get(kind, ns, name)
        if observed is None:
            member.apply(manifest)
        else:
            rec = self._versions.get((member.name, kind, ns, name))
            managed = deep_get(
                observed.manifest, "metadata.annotations", {}
            ).get("work.karmada.io/managed") == "true"
            if rec is None and not managed and conflict_resolution != "Overwrite":
                raise RuntimeError(
                    f"conflict: {kind} {ns}/{name} exists in {member.name} "
                    f"and ConflictResolution is Abort"
                )
            desired = self.interpreter.retain(manifest, observed.manifest)
            member.apply(desired)
        applied = member.get(kind, ns, name)
        if applied is not None:
            self._versions[(member.name, kind, ns, name)] = (
                applied.metadata.resource_version
            )

    def delete(self, member: FakeMemberCluster, manifest: Dict) -> None:
        kind = manifest.get("kind", "")
        ns = deep_get(manifest, "metadata.namespace", "")
        name = deep_get(manifest, "metadata.name", "")
        member.delete(kind, ns, name)
        self._versions.pop((member.name, kind, ns, name), None)


def _mark_managed(manifest: Dict) -> Dict:
    import copy

    out = copy.deepcopy(manifest)
    out.setdefault("metadata", {}).setdefault("annotations", {})[
        "work.karmada.io/managed"
    ] = "true"
    return out


class ExecutionController:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        members: Dict[str, FakeMemberCluster],
        interpreter: Optional[ResourceInterpreter] = None,
        recorder: Optional[ev.EventRecorder] = None,
    ) -> None:
        self.store = store
        self.members = members
        self.recorder = recorder if recorder is not None else ev.EventRecorder()
        self.watcher = ObjectWatcher(interpreter or ResourceInterpreter())
        self._deleted: Dict[tuple, list] = {}
        self.worker = runtime.register(AsyncWorker("execution", self._reconcile))
        store.bus.subscribe(self._on_event, kind=Work.KIND)
        store.bus.subscribe(self._on_cluster_event, kind=Cluster.KIND)

    def _on_cluster_event(self, event: Event) -> None:
        # a cluster turning Ready must replay its pending Works (the retry
        # budget may have been exhausted while it was down)
        if event.obj.ready:  # type: ignore[union-attr]
            ns = execution_namespace(event.obj.name)
            for w in self.store.list(Work.KIND, ns):
                self.worker.enqueue((ns, w.name, False))

    def _on_event(self, event: Event) -> None:
        if event.type == DELETED:
            # the Work is gone from the store; carry its manifests for teardown
            self._deleted[(event.obj.namespace, event.obj.name)] = list(
                event.obj.spec.workload
            )
        self.worker.enqueue(
            (event.obj.namespace, event.obj.name, event.type == DELETED)
        )

    def _cluster_ready(self, name: str) -> bool:
        c = self.store.try_get(Cluster.KIND, "", name)
        return c is not None and c.ready  # type: ignore[union-attr]

    def _reconcile(self, key) -> Optional[bool]:
        ns, name, deleted = key
        cluster_name = ns[len(EXECUTION_NS_PREFIX):]
        member = self.members.get(cluster_name)
        work = None if deleted else self.store.try_get(Work.KIND, ns, name)
        if work is None or work.metadata.deleting:
            # Work removed: tear the manifests down in the member cluster
            manifests = self._deleted.pop((ns, name), None)
            if manifests is None and work is not None:
                manifests = work.spec.workload
            if member is not None:
                for manifest in manifests or []:
                    self.watcher.delete(member, manifest)
            return None
        if member is None:
            return None
        if work.spec.suspend_dispatching:
            return None
        if not self._cluster_ready(cluster_name):
            return False  # requeue until the cluster turns Ready
        sync_start = time.perf_counter()
        errors = []
        from karmada_tpu.models.work import ResourceBinding  # local import cycle guard

        conflict = "Abort"
        label = work.metadata.labels.get(WORK_BINDING_LABEL, "")
        if label and "." in label:
            rb_ns, rb_name = label.split(".", 1)
            rb = self.store.try_get(ResourceBinding.KIND, rb_ns, rb_name)
            if rb is not None:
                conflict = rb.spec.conflict_resolution
        for manifest in work.spec.workload:
            try:
                self.watcher.create_or_update(member, _mark_managed(manifest), conflict)
            # vet: ignore[exception-hygiene] surfaced in the Work's Applied=False condition message
            except Exception as e:  # noqa: BLE001
                errors.append(str(e))

        def set_applied(w: Work) -> None:
            ok = not errors
            set_condition(w.status.conditions, Condition(
                type=COND_WORK_APPLIED,
                status="True" if ok else "False",
                reason="AppliedSuccessful" if ok else "AppliedFailed",
                message="; ".join(errors),
            ))

        self.store.mutate(Work.KIND, ns, name, set_applied)
        SYNC_WORKLOAD_LATENCY.observe(
            time.perf_counter() - sync_start,
            result="error" if errors else "success",
        )
        if errors:
            self.recorder.event(work, ev.TYPE_WARNING,
                                ev.REASON_SYNC_WORKLOAD_FAILED, "; ".join(errors))
        else:
            self.recorder.event(
                work, ev.TYPE_NORMAL, ev.REASON_SYNC_WORKLOAD_SUCCEED,
                f"Successfully applied manifests to cluster {cluster_name}.",
            )
        return None if not errors else False
