"""FederatedHPA + CronFederatedHPA controllers.

Reference: pkg/controllers/federatedhpa/federatedhpa_controller.go:141-995
(the k8s autoscaling/v2 HPA algorithm lifted and evaluated against pods
gathered from ALL the workload's target clusters via the metrics adapter),
replica_calculator.go (utilization / average-value math, 10% tolerance),
cronfederatedhpa/cronfederatedhpa_controller.go:58 (cron rules scaling
workloads or the FHPA's min/max), hpascaletargetmarker (labels HPA targets
so replica sync is skipped) and deploymentreplicassyncer (aggregated member
replicas synced back to the template when HPA-controlled).

Scaling acts on the TEMPLATE's spec.replicas: the detector refreshes the
binding, the scheduler redistributes — the same closed loop as the
reference (scale target -> karmada-apiserver -> detector -> scheduler).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Tuple

from karmada_tpu.controllers.detector import binding_name
from karmada_tpu.models.autoscaling import (
    POLICY_PERCENT,
    POLICY_PODS,
    SELECT_DISABLED,
    SELECT_MAX,
    SELECT_MIN,
    TARGET_AVERAGE_VALUE,
    TARGET_UTILIZATION,
    TARGET_VALUE,
    CronFederatedHPA,
    ExecutionHistory,
    FederatedHPA,
    HPAScalingPolicy,
    HPAScalingRules,
    MetricStatusValue,
)
from karmada_tpu.models.meta import deep_get
from karmada_tpu.webhook.admission import AdmissionDenied
from karmada_tpu.models.work import ResourceBinding
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

from karmada_tpu.utils.constants import RETAIN_REPLICAS_LABEL

TOLERANCE = 0.1  # replica_calculator.go tolerance

# k8s default behavior (autoscaling/v2 defaults the reference inherits)
DEFAULT_SCALE_UP = HPAScalingRules(
    stabilization_window_seconds=0,
    select_policy=SELECT_MAX,
    policies=[
        HPAScalingPolicy(type=POLICY_PERCENT, value=100, period_seconds=15),
        HPAScalingPolicy(type=POLICY_PODS, value=4, period_seconds=15),
    ],
)
DEFAULT_SCALE_DOWN = HPAScalingRules(
    stabilization_window_seconds=300,
    select_policy=SELECT_MAX,
    policies=[HPAScalingPolicy(type=POLICY_PERCENT, value=100, period_seconds=15)],
)


class ReplicaCalculator:
    """replica_calculator.go — per-metric desired replicas over the merged
    multi-cluster pod samples."""

    def desired_for_metric(self, metric, samples: List[dict],
                           current_replicas: int) -> Tuple[int, MetricStatusValue]:
        res = metric.resource
        name = res.name
        ready = len(samples)
        if ready == 0:
            # no pods yet: keep current (the reference errors and retries)
            return current_replicas, MetricStatusValue(name=name)
        usage = sum(s["usage"].get(name, 0) for s in samples)
        if res.target.type == TARGET_UTILIZATION:
            requests = sum(s["request"].get(name, 0) for s in samples)
            if requests <= 0:
                return current_replicas, MetricStatusValue(name=name)
            current_util = int(round(100.0 * usage / requests))
            target = max(res.target.average_utilization or 0, 1)
            ratio = (usage / requests) / (target / 100.0)
            status = MetricStatusValue(name=name, current_utilization=current_util)
        else:  # AverageValue
            target = max(res.target.average_value or 0, 1)
            avg = usage / ready
            ratio = avg / target
            status = MetricStatusValue(name=name, current_average_value=int(avg))
        if abs(ratio - 1.0) <= TOLERANCE:
            return current_replicas, status
        return int(math.ceil(ratio * ready)), status


def _replicas_change_in_period(events: List[Tuple[float, int, int]],
                               now: float, period: int, up: bool) -> int:
    """Sum of replica increases (or decreases) within the trailing period
    (the k8s getReplicasChangePerPeriod over scaleEvents)."""
    total = 0
    for (t, old, new) in events:
        if now - t > period:
            continue
        d = new - old
        total += max(d, 0) if up else max(-d, 0)
    return total


def _rule_limit(rules: HPAScalingRules, current: int, up: bool,
                events: List[Tuple[float, int, int]], now: float) -> Optional[int]:
    """Max replicas reachable under the scaling policies, accounting for
    changes already made inside each policy's period
    (k8s calculateScaleUpLimitWithScalingRules)."""
    if rules.select_policy == SELECT_DISABLED:
        return current
    limits = []
    for p in rules.policies:
        changed = _replicas_change_in_period(events, now, p.period_seconds, up)
        base = current - changed if up else current + changed
        if p.type == POLICY_PODS:
            limits.append(base + p.value if up else base - p.value)
        else:  # Percent
            if up:
                limits.append(int(math.ceil(base * (1.0 + p.value / 100.0))))
            else:
                limits.append(int(math.floor(base * (1.0 - p.value / 100.0))))
    if not limits:
        return None
    if rules.select_policy == SELECT_MIN:
        return min(limits) if up else max(limits)
    return max(limits) if up else min(limits)


class FederatedHPAController:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        metrics,  # search.MultiClusterMetricsProvider
        clock: Callable[[], float] = time.time,
        # autoscale fast path (rebalance plane, ISSUE 10): called as
        # fast_path(ns, scale_target_ref, desired) right after a scale
        # mutate, so the control plane can refresh the binding and
        # priority-push it into the scheduler queue in the SAME round
        # instead of waiting for the next detector resolve.  None keeps
        # the legacy detector-paced loop.
        fast_path: Optional[Callable] = None,
    ) -> None:
        self.store = store
        self.metrics = metrics
        self.clock = clock
        self.fast_path = fast_path
        self.calc = ReplicaCalculator()
        # per-HPA recommendation history for stabilization windows:
        # (ns, name) -> [(timestamp, recommendation)]
        self._recommendations: Dict[Tuple[str, str], List[Tuple[float, int]]] = {}
        # per-HPA scale events for behavior rate limits:
        # (ns, name) -> [(timestamp, old_replicas, new_replicas)]
        self._scale_events: Dict[Tuple[str, str], List[Tuple[float, int, int]]] = {}
        self.worker = runtime.register(AsyncWorker("federatedhpa", self._reconcile))
        runtime.register_periodic(self.run_once, name="federatedhpa")
        store.bus.subscribe(self._on_event, kind=FederatedHPA.KIND)

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def run_once(self) -> None:
        for hpa in self.store.list(FederatedHPA.KIND):
            self.worker.enqueue((hpa.namespace, hpa.name))

    # -- target plumbing ----------------------------------------------------
    def _target_clusters(self, ns: str, ref) -> List[str]:
        rb = self.store.try_get(
            ResourceBinding.KIND, ns, binding_name(ref.kind, ref.name)
        )
        if rb is None:
            return []
        return [tc.name for tc in rb.spec.clusters]

    def _reconcile(self, key) -> None:
        ns, name = key
        hpa = self.store.try_get(FederatedHPA.KIND, ns, name)
        if hpa is None or hpa.metadata.deleting:
            self._recommendations.pop((ns, name), None)
            self._scale_events.pop((ns, name), None)
            return
        ref = hpa.spec.scale_target_ref
        target = self.store.try_get(ref.kind, ns, ref.name)
        if target is None:
            return
        current = int(deep_get(target.manifest, "spec.replicas", 0) or 0)
        if current == 0:
            return  # scaled to zero: HPA disabled (k8s semantics)

        clusters = self._target_clusters(ns, ref)
        samples = self.metrics.pod_metrics(ref.kind, ns, ref.name, clusters or None)

        # k8s: every metric proposes a replica count; the max wins
        statuses: List[MetricStatusValue] = []
        proposals: List[int] = []
        ready = max(len(samples), 1)
        for metric in hpa.spec.metrics:
            if metric.resource is not None:
                d, st = self.calc.desired_for_metric(metric, samples, current)
            elif metric.pods is not None:
                d, st = self._desired_for_pods(metric.pods, ref, ns,
                                               current, ready)
            elif metric.object is not None:
                d, st = self._desired_for_object(metric.object, ns,
                                                 current, ready)
            elif metric.external is not None:
                d, st = self._desired_for_external(metric.external,
                                                   current, ready)
            else:
                continue
            statuses.append(st)
            proposals.append(d)
        desired = max(proposals) if proposals else current

        desired = self._stabilize(ns, name, hpa, current, desired)
        desired = self._apply_behavior(ns, name, hpa, current, desired)
        desired = max(hpa.spec.min_replicas, min(desired, hpa.spec.max_replicas))

        if desired != current:
            def scale(obj) -> None:
                obj.manifest.setdefault("spec", {})["replicas"] = desired
            self.store.mutate(ref.kind, ns, ref.name, scale)
            events = self._scale_events.setdefault((ns, name), [])
            events.append((self.clock(), current, desired))
            horizon = 3600.0
            events[:] = [e for e in events if self.clock() - e[0] <= horizon]
            if self.fast_path is not None:
                # the detector will reconcile the template event too, but
                # only on its own worker cadence; the fast path closes the
                # autoscale -> re-place loop in one scheduling cycle
                self.fast_path(ns, ref, desired)

        def set_status(obj: FederatedHPA) -> None:
            obj.status.current_replicas = current
            obj.status.desired_replicas = desired
            obj.status.current_metrics = statuses
            if desired != current:
                obj.status.last_scale_time = self.clock()
        self.store.mutate(FederatedHPA.KIND, ns, name, set_status)

    # -- stabilization + behavior ------------------------------------------
    # -- non-resource metric sources (replica_calculator.go Get*Replicas) ---
    def _desired_for_pods(self, src, ref, ns: str, current: int,
                          ready: int) -> Tuple[int, MetricStatusValue]:
        """Pods metric: the workload's per-pod custom series summed across
        clusters; AverageValue semantics — desired = ceil(total / target)."""
        got = self.metrics.custom_metric_by_name(ref.kind, ns, ref.name,
                                                 src.metric)
        if got is None or src.target.average_value is None:
            # no samples, or a misconfigured target (Pods metrics are
            # AverageValue-only in autoscaling/v2): hold, never explode
            return current, MetricStatusValue(name=src.metric)
        target = max(src.target.average_value, 1)
        desired = int(math.ceil(got["value"] / target))
        return desired, MetricStatusValue(
            name=src.metric,
            current_average_value=int(got["value"] / ready))

    def _desired_for_object(self, src, ns: str, current: int,
                            ready: int) -> Tuple[int, MetricStatusValue]:
        """Object metric: one described object's merged value.  Value
        target scales the ready count by value/target; AverageValue divides
        the value across pods."""
        obj = src.described_object
        got = self.metrics.custom_metric_by_name(obj.kind, ns, obj.name,
                                                 src.metric)
        if got is None:
            return current, MetricStatusValue(name=src.metric)
        value = got["value"]
        status = MetricStatusValue(name=src.metric,
                                   current_average_value=int(value / ready))
        if (src.target.type == TARGET_AVERAGE_VALUE
                and src.target.average_value is not None):
            desired = int(math.ceil(value / max(src.target.average_value, 1)))
        elif src.target.type == TARGET_VALUE and src.target.value is not None:
            ratio = value / max(src.target.value, 1)
            desired = current if abs(ratio - 1.0) <= TOLERANCE else int(
                math.ceil(ratio * ready))
        else:
            # misconfigured target (e.g. the Utilization default, or the
            # matching value field unset): hold current
            return current, status
        return desired, status

    def _desired_for_external(self, src, current: int,
                              ready: int) -> Tuple[int, MetricStatusValue]:
        """External metric: selector-filtered labeled series summed.  Value
        target scales ready by total/target; AverageValue divides."""
        values = self.metrics.external_metric_values(
            src.metric, src.selector or None)
        if not values:
            return current, MetricStatusValue(name=src.metric)
        total = sum(float(v.get("value", 0)) for v in values)
        status = MetricStatusValue(name=src.metric,
                                   current_average_value=int(total / ready))
        if (src.target.type == TARGET_AVERAGE_VALUE
                and src.target.average_value is not None):
            desired = int(math.ceil(total / max(src.target.average_value, 1)))
        elif src.target.type == TARGET_VALUE and src.target.value is not None:
            ratio = total / max(src.target.value, 1)
            desired = current if abs(ratio - 1.0) <= TOLERANCE else int(
                math.ceil(ratio * ready))
        else:
            return current, status  # misconfigured target: hold current
        return desired, status

    def _stabilize(self, ns: str, name: str, hpa: FederatedHPA,
                   current: int, desired: int) -> int:
        """Record the recommendation; within the stabilization window the
        scale-down floor is the MAX recent recommendation and the scale-up
        ceiling the MIN (the k8s stabilizeRecommendationWithBehaviors)."""
        now = self.clock()
        behavior = hpa.spec.behavior
        up = (behavior.scale_up if behavior else None) or DEFAULT_SCALE_UP
        down = (behavior.scale_down if behavior else None) or DEFAULT_SCALE_DOWN
        up_w = up.stabilization_window_seconds or 0
        down_w = (
            down.stabilization_window_seconds
            if down.stabilization_window_seconds is not None else 300
        )
        hist = self._recommendations.setdefault((ns, name), [])
        hist.append((now, desired))
        horizon = max(up_w, down_w)
        hist[:] = [(t, r) for (t, r) in hist if now - t <= horizon]
        out = desired
        if desired < current and down_w > 0:
            out = max(r for (t, r) in hist if now - t <= down_w)
            out = min(out, current)
        elif desired > current and up_w > 0:
            out = min(r for (t, r) in hist if now - t <= up_w)
            out = max(out, current)
        return out

    def _apply_behavior(self, ns: str, name: str, hpa: FederatedHPA,
                        current: int, desired: int) -> int:
        behavior = hpa.spec.behavior
        events = self._scale_events.get((ns, name), [])
        now = self.clock()
        if desired > current:
            rules = (behavior.scale_up if behavior else None) or DEFAULT_SCALE_UP
            limit = _rule_limit(rules, current, True, events, now)
            if limit is not None:
                desired = min(desired, max(limit, current))
        elif desired < current:
            rules = (behavior.scale_down if behavior else None) or DEFAULT_SCALE_DOWN
            limit = _rule_limit(rules, current, False, events, now)
            if limit is not None:
                desired = max(desired, min(limit, current))
        return desired


# -- CronFederatedHPA --------------------------------------------------------


def _cron_field_matches(field_spec: str, value: int, lo: int, hi: int) -> bool:
    for part in field_spec.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            rng = range(lo, hi + 1)
        elif "-" in part:
            a, b = part.split("-", 1)
            rng = range(int(a), int(b) + 1)
        else:
            rng = range(int(part), int(part) + 1)
        if value in rng and (value - rng.start) % step == 0:
            return True
    return False


def cron_matches(expr: str, ts: float) -> bool:
    """Standard 5-field cron match for the minute containing `ts`."""
    parts = expr.split()
    if len(parts) != 5:
        return False
    tm = time.localtime(ts)
    cron_dow = (tm.tm_wday + 1) % 7  # python Mon=0..Sun=6 -> cron Sun=0..Sat=6
    if not (
        _cron_field_matches(parts[0], tm.tm_min, 0, 59)
        and _cron_field_matches(parts[1], tm.tm_hour, 0, 23)
        and _cron_field_matches(parts[3], tm.tm_mon, 1, 12)
    ):
        return False
    dom_ok = _cron_field_matches(parts[2], tm.tm_mday, 1, 31)
    dow_ok = _cron_field_matches(parts[4], cron_dow, 0, 6)
    # vixie/robfig cron (the reference's parser): when BOTH day fields are
    # restricted, a time matches if EITHER does; otherwise both must match
    # (the unrestricted one is always true)
    if parts[2] != "*" and parts[4] != "*":
        return dom_ok or dow_ok
    return dom_ok and dow_ok


class CronFederatedHPAController:
    """cronfederatedhpa_controller.go:58 — each sync, fire any rule whose
    schedule matches a minute since the last check; targets either a
    workload's spec.replicas or a FederatedHPA's min/max."""

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 clock: Callable[[], float] = time.time) -> None:
        self.store = store
        self.clock = clock
        self._last_check: Dict[Tuple[str, str], float] = {}
        runtime.register_periodic(self.run_once, name="cronfederatedhpa")

    def run_once(self) -> None:
        now = self.clock()
        for cron in self.store.list(CronFederatedHPA.KIND):
            self._sync(cron, now)

    def _sync(self, cron: CronFederatedHPA, now: float) -> None:
        key = (cron.namespace, cron.name)
        last = self._last_check.get(key)
        self._last_check[key] = now
        if last is None:
            # first observation: schedule only FUTURE fire times (the
            # reference's cron library never fires slots that predate
            # registration)
            return
        fired: Dict[str, Tuple[float, str, str]] = {}
        for rule in cron.spec.rules:
            if rule.suspend:
                continue
            # check each whole minute in (last, now]
            t = (int(last) // 60 + 1) * 60
            while t <= now:
                if cron_matches(rule.schedule, t):
                    result, msg = self._fire(cron, rule)
                    fired[rule.name] = (float(t), result, msg)
                t += 60
        if not fired:
            return

        def set_status(obj: CronFederatedHPA) -> None:
            hist = {h.rule_name: h for h in obj.status.execution_histories}
            for rname, (t, result, msg) in fired.items():
                h = hist.get(rname)
                if h is None:
                    h = ExecutionHistory(rule_name=rname)
                    obj.status.execution_histories.append(h)
                    hist[rname] = h
                h.last_execution_time = t
                h.last_result = result
                h.message = msg
        self.store.mutate(CronFederatedHPA.KIND, cron.namespace, cron.name, set_status)

    def _fire(self, cron: CronFederatedHPA, rule) -> Tuple[str, str]:
        ref = cron.spec.scale_target_ref
        ns = cron.namespace
        try:
            if ref.kind == FederatedHPA.KIND:
                def upd(hpa: FederatedHPA) -> None:
                    if rule.target_min_replicas is not None:
                        hpa.spec.min_replicas = rule.target_min_replicas
                    if rule.target_max_replicas is not None:
                        hpa.spec.max_replicas = rule.target_max_replicas
                self.store.mutate(FederatedHPA.KIND, ns, ref.name, upd)
            else:
                if rule.target_replicas is None:
                    return "Failed", "rule has no targetReplicas"

                def scale(obj) -> None:
                    obj.manifest.setdefault("spec", {})["replicas"] = (
                        rule.target_replicas
                    )
                self.store.mutate(ref.kind, ns, ref.name, scale)
            return "Succeed", ""
        except NotFoundError:
            return "Failed", f"target {ref.kind}/{ref.name} not found"
        except AdmissionDenied as e:
            # a rule pushing the FHPA into an invalid shape (e.g.
            # targetMinReplicas above maxReplicas) is a FAILED execution in
            # the history, never a crashed controller round
            return "Failed", f"admission rejected the scale: {e}"


# -- HpaScaleTargetMarker + DeploymentReplicasSyncer -------------------------


class HpaScaleTargetMarker:
    """hpascaletargetmarker: watches NATIVE HorizontalPodAutoscaler
    templates (the propagate-an-HPA-to-members flow, hpa_scale_target_
    marker_controller.go:60 — NOT FederatedHPA) and labels their scale
    target with retain-replicas, so the apply engine keeps each member's
    own replica count (retain.go:145 retainWorkloadReplicas) and the
    member-side HPAs stay in control."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("hpa-marker", self._reconcile))
        store.bus.subscribe(self._on_event, kind="HorizontalPodAutoscaler")

    @staticmethod
    def _ref_of(hpa) -> Optional[Tuple[str, str]]:
        ref = deep_get(hpa.manifest, "spec.scaleTargetRef", {}) or {}
        if not ref.get("kind") or not ref.get("name"):
            return None
        return (ref["kind"], ref["name"])

    def _on_event(self, event: Event) -> None:
        hpa = event.obj
        if event.type == "DELETED":
            refs = {self._ref_of(hpa)}
        else:
            # retargeting an HPA must also UNMARK the previous target, or
            # the stale label keeps member replicas authoritative with no
            # HPA left in control
            refs = {self._ref_of(hpa),
                    self._ref_of(event.old) if event.old is not None else None}
        for ref in refs:
            if ref is not None:
                self.worker.enqueue((hpa.namespace,) + ref)

    def _still_targeted(self, ns: str, kind: str, name: str) -> bool:
        for hpa in self.store.list("HorizontalPodAutoscaler", ns):
            if hpa.metadata.deleting:
                continue
            if self._ref_of(hpa) == (kind, name):
                return True
        return False

    def _reconcile(self, key) -> None:
        ns, kind, name = key
        obj = self.store.try_get(kind, ns, name)
        if obj is None:
            return
        # the label reflects whether ANY live HPA targets the object —
        # deleting one of two HPAs sharing a target must not unmark it
        want = self._still_targeted(ns, kind, name)

        def mark(o) -> None:
            labels = o.manifest.setdefault("metadata", {}).setdefault("labels", {})
            if want:
                labels[RETAIN_REPLICAS_LABEL] = "true"
                o.metadata.labels[RETAIN_REPLICAS_LABEL] = "true"
            else:
                labels.pop(RETAIN_REPLICAS_LABEL, None)
                o.metadata.labels.pop(RETAIN_REPLICAS_LABEL, None)
        self.store.mutate(kind, ns, name, mark)


class DeploymentReplicasSyncer:
    """deploymentreplicassyncer: for HPA-controlled targets, sync the sum of
    member-reported replicas back into the template's spec.replicas so the
    control plane view follows what HPA actually achieved."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        runtime.register_periodic(self.run_once, name="replicas-syncer")

    def run_once(self) -> None:
        for rb in self.store.list(ResourceBinding.KIND):
            ref = rb.spec.resource
            tmpl = self.store.try_get(ref.kind, ref.namespace, ref.name)
            if tmpl is None:
                continue
            if tmpl.metadata.labels.get(RETAIN_REPLICAS_LABEL) != "true":
                continue
            cur = int(deep_get(tmpl.manifest, "spec.replicas", 0) or 0)
            # guards (deployment_replicas_syncer_controller.go:146-190): the
            # spec change must have fully propagated — binding caught up,
            # scheduler observed the latest generation, every target
            # cluster's status collected — before status drives spec, or
            # this controller would fight an in-flight HPA scale
            if rb.spec.replicas != cur:
                continue
            if rb.metadata.generation != rb.status.scheduler_observed_generation:
                continue
            if len(rb.status.aggregated_status) != len(rb.spec.clusters):
                continue
            total = 0
            seen = False
            for agg in rb.status.aggregated_status:
                st = agg.status or {}
                if "replicas" in st:
                    total += int(st.get("replicas") or 0)
                    seen = True
            if not seen:
                continue
            if total > 0 and total != cur:
                def sync(o) -> None:
                    o.manifest.setdefault("spec", {})["replicas"] = total
                self.store.mutate(ref.kind, ref.namespace, ref.name, sync)
