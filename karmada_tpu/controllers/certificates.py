"""Agent CSR auto-approval + certificate rotation.

Reference: pkg/controllers/certificate/agent_csr_approving.go:59 (approve
CSRs whose signer/subject match the karmada-agent identity) and
cert_rotation_controller.go:89 (renew a credential once the remaining
lifetime falls below --certificate-rotation-threshold, default 0.8 of the
ttl elapsed).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from karmada_tpu.models.certs import (
    AGENT_SIGNER,
    AGENT_USER_PREFIX,
    CertificateSigningRequest,
    ClusterCredential,
)
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.store.store import AlreadyExistsError, Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime


class AgentCsrApprover:
    """Auto-approve agent bootstrap CSRs; issue the 'certificate' and
    materialize/refresh the cluster's credential."""

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 clock: Callable[[], float] = time.time) -> None:
        self.store = store
        self.clock = clock
        self.worker = runtime.register(AsyncWorker("csr-approver", self._reconcile))
        store.bus.subscribe(self._on_event, kind=CertificateSigningRequest.KIND)

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def _reconcile(self, name: str) -> None:
        csr = self.store.try_get(CertificateSigningRequest.KIND, "", name)
        if csr is None or csr.status.approved or csr.status.denied_reason:
            return
        expected_user = AGENT_USER_PREFIX + csr.spec.cluster

        def decide(c: CertificateSigningRequest) -> None:
            if (
                c.spec.signer_name != AGENT_SIGNER
                or c.spec.username != expected_user
                or not c.spec.cluster
            ):
                c.status.denied_reason = (
                    "subject does not match the karmada-agent identity"
                )
                return
            now = self.clock()
            c.status.approved = True
            c.status.issued_at = now
            c.status.expires_at = now + c.spec.ttl_seconds
        approved = self.store.mutate(CertificateSigningRequest.KIND, "", name, decide)
        if not approved.status.approved:
            return

        cred_name = csr.spec.cluster
        cred = self.store.try_get(ClusterCredential.KIND, "", cred_name)
        if cred is None:
            cred = ClusterCredential()
            cred.metadata.name = cred_name
            cred.status.issued_at = approved.status.issued_at
            cred.status.expires_at = approved.status.expires_at
            try:
                self.store.create(cred)
            except AlreadyExistsError:
                pass
            return

        def refresh(c: ClusterCredential) -> None:
            c.status.issued_at = approved.status.issued_at
            c.status.expires_at = approved.status.expires_at
            c.status.rotations += 1
        self.store.mutate(ClusterCredential.KIND, "", cred_name, refresh)


class CertRotationController:
    """Renew credentials approaching expiry by posting a fresh agent CSR
    (which the approver then honors).

    In the reference this loop runs INSIDE each karmada-agent for its own
    credential (cmd/agent/app/agent.go registers
    cert_rotation_controller.go); pass `cluster` to scope an instance to
    one agent's identity — KarmadaAgent does."""

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 rotation_threshold: float = 0.8,
                 ttl_seconds: int = 30 * 24 * 3600,
                 clock: Callable[[], float] = time.time,
                 cluster: Optional[str] = None) -> None:
        self.store = store
        self.threshold = rotation_threshold
        self.ttl_seconds = ttl_seconds
        self.clock = clock
        self.cluster = cluster
        self._seq = 0
        runtime.register_periodic(self.run_once, name="cert-rotation")

    def run_once(self) -> None:
        now = self.clock()
        if self.cluster is not None:
            # agent-scoped: fetch only its own identity (N agents must not
            # each scan all N credentials every round)
            cred = self.store.try_get(ClusterCredential.KIND, "", self.cluster)
            creds = [cred] if cred is not None else []
        else:
            creds = self.store.list(ClusterCredential.KIND)
        for cred in creds:
            issued = cred.status.issued_at or now
            expires = cred.status.expires_at
            if expires is None:
                continue
            lifetime = max(expires - issued, 1.0)
            if (now - issued) / lifetime < self.threshold:
                continue
            if self.store.try_get(Cluster.KIND, "", cred.metadata.name) is None:
                continue  # unjoined cluster: nothing to rotate for
            self._seq += 1
            csr = CertificateSigningRequest()
            csr.metadata.name = f"rotate-{cred.metadata.name}-{self._seq}"
            csr.spec.cluster = cred.metadata.name
            csr.spec.username = AGENT_USER_PREFIX + cred.metadata.name
            csr.spec.ttl_seconds = self.ttl_seconds
            try:
                self.store.create(csr)
            except AlreadyExistsError:
                pass


def bootstrap_agent_csr(store: ObjectStore, cluster: str,
                        ttl_seconds: int = 30 * 24 * 3600) -> None:
    """The agent's register step (karmadactl register): post the initial
    bootstrap CSR for its identity."""
    csr = CertificateSigningRequest()
    csr.metadata.name = f"bootstrap-{cluster}"
    csr.spec.cluster = cluster
    csr.spec.username = AGENT_USER_PREFIX + cluster
    csr.spec.ttl_seconds = ttl_seconds
    try:
        store.create(csr)
    except AlreadyExistsError:
        pass
