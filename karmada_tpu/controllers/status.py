"""Status controllers: the "backward pass" of the propagation loop.

* WorkStatusController -- mirrors pkg/controllers/status/
  work_status_controller.go:84-438: watches applied objects in member
  clusters (per-member informers), reflects status+health into
  work.status.manifestStatuses via the interpreter, and recreates
  desired-but-deleted member objects (:310).
* BindingStatusController -- rb_status_controller.go:60: aggregates Work
  statuses into binding.status.aggregatedStatus, sets FullyApplied, and
  writes the template's aggregated status via the interpreter.
* ClusterStatusController -- cluster_status_controller.go:127-680: the
  per-cluster heartbeat; collects health, APIEnablements, and the
  ResourceSummary capacity tensor source from the member simulator.
"""

from __future__ import annotations

from typing import Dict, Optional

from karmada_tpu.controllers.binding import (
    EXECUTION_NS_PREFIX,
    WORK_BINDING_LABEL,
    execution_namespace,
    work_name,
)
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.cluster import (
    COND_CLUSTER_READY,
    COND_COMPLETE_API_ENABLEMENTS,
    Cluster,
)
from karmada_tpu.models.meta import Condition, deep_get, set_condition
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import (
    COND_FULLY_APPLIED,
    COND_WORK_APPLIED,
    AggregatedStatusItem,
    ManifestStatus,
    ResourceBinding,
    Work,
)
from karmada_tpu.store.store import DELETED, Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime


class WorkStatusController:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        members: Dict[str, FakeMemberCluster],
        interpreter: Optional[ResourceInterpreter] = None,
    ) -> None:
        self.store = store
        self.members = members
        self.interpreter = interpreter or ResourceInterpreter()
        self.worker = runtime.register(AsyncWorker("work-status", self._reconcile))
        # per-member informers (buildResourceInformers :128)
        for name, member in members.items():
            member.store.bus.subscribe(self._member_event(name))

    def _member_event(self, cluster: str):
        def handler(event: Event) -> None:
            obj = event.obj
            self.worker.enqueue(
                (cluster, obj.KIND, obj.namespace, obj.name, event.type == DELETED)
            )

        return handler

    def _reconcile(self, key) -> None:
        cluster, kind, ns, name, deleted = key
        member = self.members.get(cluster)
        if member is None:
            return
        # find the Work desiring this object
        work = self._work_for(cluster, kind, ns, name)
        if work is None:
            return
        if deleted or member.get(kind, ns, name) is None:
            # desired object vanished from the member: recreate (:310) --
            # through the same managed-marking the execution path uses
            from karmada_tpu.controllers.execution import _mark_managed

            if not work.metadata.deleting and not work.spec.suspend_dispatching:
                for manifest in work.spec.workload:
                    if (
                        manifest.get("kind") == kind
                        and deep_get(manifest, "metadata.name") == name
                    ):
                        member.apply(_mark_managed(manifest))
            return
        observed = member.get(kind, ns, name)
        status = self.interpreter.reflect_status(observed.manifest)
        health = self.interpreter.interpret_health(observed.manifest)
        ms = ManifestStatus(
            identifier={"kind": kind, "namespace": ns, "name": name},
            status=status,
            health=health,
        )

        def update(w: Work) -> None:
            rest = [
                m for m in w.status.manifest_statuses
                if m.identifier != ms.identifier
            ]
            w.status.manifest_statuses = rest + [ms]

        try:
            self.store.mutate(Work.KIND, work.metadata.namespace, work.name, update)
        except NotFoundError:
            pass

    def _work_for(self, cluster: str, kind: str, ns: str, name: str) -> Optional[Work]:
        for w in self.store.list(Work.KIND, execution_namespace(cluster)):
            for manifest in w.spec.workload:
                if (
                    manifest.get("kind") == kind
                    and deep_get(manifest, "metadata.namespace", "") == ns
                    and deep_get(manifest, "metadata.name") == name
                ):
                    return w
        return None


class BindingStatusController:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        interpreter: Optional[ResourceInterpreter] = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter or ResourceInterpreter()
        self.worker = runtime.register(AsyncWorker("binding-status", self._reconcile))
        store.bus.subscribe(self._on_event, kind=Work.KIND)

    def _on_event(self, event: Event) -> None:
        label = event.obj.metadata.labels.get(WORK_BINDING_LABEL, "")
        if label and "." in label:
            ns, name = label.split(".", 1)
            self.worker.enqueue((ns, name))

    def _reconcile(self, key) -> None:
        ns, name = key
        rb = self.store.try_get(ResourceBinding.KIND, ns, name)
        if rb is None:
            return
        items = []
        applied_all = bool(rb.spec.clusters)
        wname = work_name(rb)
        for target in rb.spec.clusters:
            w = self.store.try_get(Work.KIND, execution_namespace(target.name), wname)
            if w is None:
                applied_all = False
                continue
            applied = any(
                c.type == COND_WORK_APPLIED and c.status == "True"
                for c in w.status.conditions
            )
            applied_all = applied_all and applied
            status = None
            health = "Unknown"
            for m in w.status.manifest_statuses:
                status = m.status
                health = m.health
            items.append(AggregatedStatusItem(
                cluster_name=target.name, status=status, applied=applied,
                health=health,
            ))

        def update(obj: ResourceBinding) -> None:
            obj.status.aggregated_status = items
            set_condition(obj.status.conditions, Condition(
                type=COND_FULLY_APPLIED,
                status="True" if applied_all else "False",
                reason="FullyAppliedSuccess" if applied_all else "FullyAppliedFailed",
            ))

        self.store.mutate(ResourceBinding.KIND, ns, name, update)

        # reflect the aggregate onto the template (AggregateStatus)
        resource = rb.spec.resource
        template = self.store.try_get(resource.kind, resource.namespace, resource.name)
        if template is not None and isinstance(template, Unstructured) and items:
            merged = self.interpreter.aggregate_status(template.to_manifest(), items)
            if merged.get("status") != template.manifest.get("status"):
                def set_status(t: Unstructured) -> None:
                    t.manifest["status"] = merged.get("status")
                try:
                    self.store.mutate(
                        resource.kind, resource.namespace, resource.name, set_status
                    )
                except NotFoundError:
                    pass


from karmada_tpu.utils.metrics import REGISTRY as _REGISTRY

CLUSTER_READY_STATE = _REGISTRY.gauge(
    "karmada_cluster_ready_state", "State of the cluster (1 ready, 0 not)",
    ("cluster_name",))
CLUSTER_CPU_ALLOCATABLE = _REGISTRY.gauge(
    "karmada_cluster_cpu_allocatable_number", "Allocatable cluster CPU cores",
    ("cluster_name",))
CLUSTER_CPU_ALLOCATED = _REGISTRY.gauge(
    "karmada_cluster_cpu_allocated_number", "Allocated cluster CPU cores",
    ("cluster_name",))
CLUSTER_MEMORY_ALLOCATABLE = _REGISTRY.gauge(
    "karmada_cluster_memory_allocatable_bytes", "Allocatable cluster memory",
    ("cluster_name",))
CLUSTER_MEMORY_ALLOCATED = _REGISTRY.gauge(
    "karmada_cluster_memory_allocated_bytes", "Allocated cluster memory",
    ("cluster_name",))
CLUSTER_POD_ALLOCATABLE = _REGISTRY.gauge(
    "karmada_cluster_pod_allocatable_number", "Allocatable cluster pod slots",
    ("cluster_name",))
CLUSTER_POD_ALLOCATED = _REGISTRY.gauge(
    "karmada_cluster_pod_allocated_number", "Allocated cluster pod slots",
    ("cluster_name",))


class ClusterStatusController:
    """Periodic heartbeat: member telemetry -> Cluster.status.

    Also maintains the karmada_cluster_* capacity gauges
    (pkg/metrics/cluster.go:57-132) and emits ClusterReady /
    ClusterNotReady events on transitions."""

    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        members: Dict[str, FakeMemberCluster],
        recorder=None,
    ) -> None:
        from karmada_tpu.utils.events import EventRecorder

        self.store = store
        self.members = members
        self.recorder = recorder if recorder is not None else EventRecorder()
        self._last_ready: Dict[str, bool] = {}
        runtime.register_periodic(self.collect_all, name="cluster-status")

    def collect_all(self) -> None:
        from karmada_tpu.controllers.lease import renew_cluster_lease
        from karmada_tpu.utils import events as ev

        for name, member in self.members.items():
            cluster = self.store.try_get(Cluster.KIND, "", name)
            if cluster is None:
                continue

            def update(c: Cluster, member=member) -> None:
                online = member.healthy
                set_condition(c.status.conditions, Condition(
                    type=COND_CLUSTER_READY,
                    status="True" if online else "False",
                    reason="ClusterReady" if online else "ClusterNotReachable",
                ))
                if online:
                    c.status.api_enablements = list(member.api_enablements)
                    set_condition(c.status.conditions, Condition(
                        type=COND_COMPLETE_API_ENABLEMENTS, status="True",
                        reason="CollectionSucceed",
                    ))
                    c.status.resource_summary = member.resource_summary()
                    if c.spec.resource_models:
                        # feature CustomizedClusterResourceModeling
                        # (cluster_status_controller.go:282 -> modeling.go)
                        from karmada_tpu.estimator.general import (
                            produce_allocatable_modelings,
                        )

                        c.status.resource_summary.allocatable_modelings = (
                            produce_allocatable_modelings(
                                member, c.spec.resource_models
                            )
                        )

            stored = self.store.mutate(Cluster.KIND, "", name, update)
            # heartbeat lease: proves THIS collector is alive, independent
            # of the member's own health (cluster_status_controller.go:399)
            renew_cluster_lease(self.store, name)
            self._export_gauges(stored)
            ready = member.healthy
            if self._last_ready.get(name) != ready:
                self._last_ready[name] = ready
                if ready:
                    self.recorder.event(
                        stored, ev.TYPE_NORMAL, ev.REASON_CLUSTER_READY,
                        f"cluster {name} readiness is now True",
                        origin="cluster-status")
                else:
                    self.recorder.event(
                        stored, ev.TYPE_WARNING, ev.REASON_CLUSTER_NOT_READY,
                        f"cluster {name} readiness is now False",
                        origin="cluster-status")

    @staticmethod
    def _export_gauges(cluster: Cluster) -> None:
        """karmada_cluster_* gauges (pkg/metrics/cluster.go:57-132)."""
        CLUSTER_READY_STATE.set(1.0 if cluster.ready else 0.0,
                                cluster_name=cluster.name)
        summary = cluster.status.resource_summary
        if summary is None:
            return
        for res, gauge_alloc, gauge_used in (
            ("cpu", CLUSTER_CPU_ALLOCATABLE, CLUSTER_CPU_ALLOCATED),
            ("memory", CLUSTER_MEMORY_ALLOCATABLE, CLUSTER_MEMORY_ALLOCATED),
            ("pods", CLUSTER_POD_ALLOCATABLE, CLUSTER_POD_ALLOCATED),
        ):
            alloc = summary.allocatable.get(res)
            used = summary.allocated.get(res)
            if alloc is not None:
                gauge_alloc.set(alloc.milli / 1000.0, cluster_name=cluster.name)
            if used is not None:
                gauge_used.set(used.milli / 1000.0, cluster_name=cluster.name)
