"""Failure detection and elastic recovery: the reference's failover loop.

Mirrors SURVEY.md section 3.5:
* ClusterTaintController -- pkg/controllers/cluster/cluster_controller.go:156
  taintClusterByCondition: Ready=False adds the not-ready NoExecute taint;
  recovery removes it (grace periods collapsed to immediate for the
  deterministic runtime; the serve-mode wrapper can delay enqueues).
* NoExecuteTaintManager -- pkg/controllers/cluster/taint_manager.go:101:
  bindings targeting a NoExecute-tainted cluster are evicted once the
  matching toleration's tolerationSeconds expire (untolerated taints evict
  immediately; a taint cleared before the deadline cancels the pending
  eviction).
* GracefulEvictionController -- pkg/controllers/gracefuleviction/
  evictiontask.go:38-116: an eviction task drains only once the binding's
  *other* clusters report healthy replacement (or the grace period lapses);
  SuppressDeletion pins the task for manual intervention.
* ApplicationFailoverController -- pkg/controllers/applicationfailover/
  rb_application_failover_controller.go:61: workloads unhealthy past
  spec.failover.tolerationSeconds are evicted and rescheduled.

Eviction itself mirrors binding_types.go GracefulEvict: the cluster leaves
.spec.clusters and a GracefulEvictionTask is appended, so the scheduler
re-places the lost replicas while the stale Work survives until the task
drains (the binding controller keeps evicting clusters' Works alive).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from karmada_tpu.models.cluster import (
    COND_CLUSTER_READY,
    Cluster,
    EFFECT_NO_EXECUTE,
    Taint,
)
from karmada_tpu.models.meta import is_condition_true
from karmada_tpu.models.work import (
    GracefulEvictionTask,
    ResourceBinding,
    TargetCluster,
)
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime
from karmada_tpu.utils import events as ev

TAINT_NOT_READY = "cluster.karmada.io/not-ready"
DEFAULT_GRACE_PERIOD_S = 600
DEFAULT_TOLERATION_S = 300

PURGE_IMMEDIATELY = "Immediately"
PURGE_GRACIOUSLY = "Graciously"
PURGE_NEVER = "Never"


def parse_json_path(status, path: str) -> str:
    """Evaluate a k8s-jsonpath-style expression against a collected status
    dict (helper/failover.go:47-62 parseJSONValue with AllowMissingKeys
    false).  Supports the subset state-preservation rules use in practice:
    `{.a.b[0].c}` / `.a.b` / `a.b` — dotted fields with integer indexing.
    Raises KeyError/IndexError on a missing segment."""
    expr = path.strip()
    if expr.startswith("{") and expr.endswith("}"):
        expr = expr[1:-1].strip()
    expr = expr.lstrip(".")
    cur = status
    if expr:
        for part in expr.split("."):
            fieldname, _, idxpart = part.partition("[")
            indices = ([s.rstrip("]") for s in idxpart.split("[")]
                       if idxpart else [])
            if fieldname:
                if not isinstance(cur, dict) or fieldname not in cur:
                    raise KeyError(
                        f"jsonpath {path!r}: missing field {fieldname!r}")
                cur = cur[fieldname]
            for idx in indices:
                if not isinstance(cur, (list, tuple)):
                    raise KeyError(f"jsonpath {path!r}: {fieldname!r} "
                                   "is not an array")
                i = int(idx)
                if i < 0 or i >= len(cur):
                    # k8s jsonpath rejects negative indices; silently
                    # resolving them would build payloads the reference
                    # never would
                    raise KeyError(f"jsonpath {path!r}: index {i} out of "
                                   f"range")
                cur = cur[i]
    if isinstance(cur, bool):
        return "true" if cur else "false"
    if isinstance(cur, str):
        return cur
    if isinstance(cur, (int, float)):
        return str(cur)
    import json

    return json.dumps(cur, sort_keys=True)


def build_preserved_label_state(rules, status) -> Dict[str, str]:
    """helper/failover.go:30-45 BuildPreservedLabelState: every rule must
    resolve (a missing path aborts the whole build)."""
    out: Dict[str, str] = {}
    for rule in rules:
        out[rule.alias_label_name] = parse_json_path(status, rule.json_path)
    return out


def evict_cluster(
    rb: ResourceBinding,
    cluster: str,
    reason: str,
    producer: str,
    grace_period_seconds: Optional[int] = None,
    suppress_deletion: Optional[bool] = None,
    now: Optional[float] = None,
    purge_mode: str = "",
    preserved_label_state: Optional[Dict[str, str]] = None,
    clusters_before_failover: Optional[list] = None,
) -> bool:
    """binding_types.go GracefulEvict semantics; returns True if changed."""
    target = next((t for t in rb.spec.clusters if t.name == cluster), None)
    if target is None:
        return False
    rb.spec.clusters = [t for t in rb.spec.clusters if t.name != cluster]
    if any(t.from_cluster == cluster for t in rb.spec.graceful_eviction_tasks):
        return True
    rb.spec.graceful_eviction_tasks.append(GracefulEvictionTask(
        from_cluster=cluster,
        replicas=target.replicas,
        reason=reason,
        producer=producer,
        grace_period_seconds=grace_period_seconds,
        suppress_deletion=suppress_deletion,
        creation_timestamp=now if now is not None else time.time(),
        purge_mode=purge_mode,
        preserved_label_state=dict(preserved_label_state or {}),
        clusters_before_failover=list(clusters_before_failover or []),
    ))
    return True


class ClusterTaintController:
    """Ready=False <-> not-ready NoExecute taint."""

    def __init__(self, store: ObjectStore, runtime: Runtime, clock=None) -> None:
        self.store = store
        self.clock = clock if clock is not None else time.time
        self.worker = runtime.register(AsyncWorker("cluster-taint", self._reconcile))
        store.bus.subscribe(self._on_event, kind=Cluster.KIND)

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def _reconcile(self, name) -> None:
        cluster = self.store.try_get(Cluster.KIND, "", name)
        if cluster is None:
            return
        ready = is_condition_true(cluster.status.conditions, COND_CLUSTER_READY)
        has = any(t.key == TAINT_NOT_READY for t in cluster.spec.taints)
        if ready and has:
            def rm(c: Cluster) -> None:
                c.spec.taints = [t for t in c.spec.taints if t.key != TAINT_NOT_READY]
            self.store.mutate(Cluster.KIND, "", name, rm)
            ev.emit(ev.ObjectRef(kind=Cluster.KIND, name=name),
                    ev.TYPE_NORMAL, ev.REASON_UNTAINT_CLUSTER_SUCCEED,
                    "cluster recovered Ready: not-ready NoExecute taint "
                    "removed", origin="cluster-taint")
        elif not ready and not has:
            def add(c: Cluster) -> None:
                c.spec.taints.append(Taint(
                    key=TAINT_NOT_READY, effect=EFFECT_NO_EXECUTE,
                    time_added=self.clock(),
                ))
            self.store.mutate(Cluster.KIND, "", name, add)
            ev.emit(ev.ObjectRef(kind=Cluster.KIND, name=name),
                    ev.TYPE_WARNING, ev.REASON_TAINT_CLUSTER_SUCCEED,
                    "cluster Ready=False: not-ready NoExecute taint added",
                    origin="cluster-taint")


class NoExecuteTaintManager:
    """Evict bindings from NoExecute-tainted clusters (taint_manager.go:101),
    honoring tolerationSeconds: a tolerated taint delays the eviction until
    the toleration expires, and a taint removed before that deadline
    cancels it (the reference's needEviction/tolerationTime semantics —
    a brief flap never evicts a workload with the defaulted 300s
    not-ready toleration).

    With an eviction_queue attached, due evictions flow through the
    rate-limited queue (cluster/eviction_worker.go) instead of executing
    inline — a mass cluster failure then drains gradually."""

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 eviction_queue=None, clock=None) -> None:
        import threading

        self.store = store
        self.eviction_queue = eviction_queue
        self.clock = clock if clock is not None else time.time
        # (ns, name, cluster) -> deadline: tolerated taints awaiting expiry;
        # touched by the worker AND the periodic flush (separate threads in
        # serve mode), so every access holds the lock
        self._pending: Dict[tuple, float] = {}
        self._pending_lock = threading.Lock()
        self.worker = runtime.register(AsyncWorker("taint-manager", self._reconcile))
        runtime.register_periodic(self._flush_deadlines, name="taint-manager")
        store.bus.subscribe(self._on_event, kind=Cluster.KIND)

    def _on_event(self, event: Event) -> None:
        taints = [t for t in event.obj.spec.taints if t.effect == EFFECT_NO_EXECUTE]
        had = event.old is not None and any(
            t.effect == EFFECT_NO_EXECUTE for t in event.old.spec.taints)
        # taint cleared is as important as taint added: pending deadlines
        # for the recovered cluster must be CANCELLED, not left to burn
        # rate-limited queue tokens at their stale expiry
        if taints or had:
            self.worker.enqueue(event.obj.name)

    def _eviction_due(self, rb: ResourceBinding, taints, now: float):
        """None = never (all taints tolerated forever); otherwise the
        timestamp at which eviction is due (<= now means due immediately).
        k8s/karmada semantics: due at the MINIMUM expiry across taints,
        where an untolerated taint is due immediately and a matching
        toleration without seconds tolerates that taint forever."""
        placement = rb.spec.placement
        tolerations = placement.cluster_tolerations if placement else []
        due = None
        for taint in taints:
            matching = [t for t in tolerations if t.tolerates(taint)]
            if not matching:
                return now
            secs = [t.toleration_seconds for t in matching]
            if any(s is None for s in secs):
                continue  # tolerated forever
            start = taint.time_added if taint.time_added is not None else now
            d = start + min(secs)
            due = d if due is None else min(due, d)
        return due

    def _cancel_cluster(self, cluster_name: str) -> None:
        with self._pending_lock:
            for key in [k for k in self._pending if k[2] == cluster_name]:
                self._pending.pop(key, None)

    def _reconcile(self, cluster_name) -> None:
        cluster = self.store.try_get(Cluster.KIND, "", cluster_name)
        if cluster is None:
            self._cancel_cluster(cluster_name)
            return
        taints = [t for t in cluster.spec.taints if t.effect == EFFECT_NO_EXECUTE]
        if not taints:
            self._cancel_cluster(cluster_name)
            return
        now = self.clock()
        for rb in self.store.list(ResourceBinding.KIND):
            if not any(t.name == cluster_name for t in rb.spec.clusters):
                continue
            due = self._eviction_due(rb, taints, now)
            key = (rb.namespace, rb.name, cluster_name)
            if due is None:
                with self._pending_lock:
                    self._pending.pop(key, None)
            elif due > now:
                with self._pending_lock:
                    newly = key not in self._pending
                    self._pending[key] = due
                if newly:
                    # toleration countdown visible on the timeline: the
                    # eviction is armed but waiting out tolerationSeconds
                    # (a taint cleared before expiry cancels it)
                    ev.emit_key((rb.namespace, rb.name), ev.TYPE_WARNING,
                                ev.REASON_EVICTION_PENDING,
                                f"eviction from {cluster_name} pending "
                                "toleration expiry (NoExecute taint "
                                "tolerated for a bounded window)",
                                origin="taint-manager")
            else:
                with self._pending_lock:
                    self._pending.pop(key, None)
                if self.eviction_queue is not None:
                    self.eviction_queue.add(key)
                else:
                    self.evict_one(key)

    def _flush_deadlines(self) -> None:
        """Expired toleration deadlines become evictions; evict_one
        re-verifies, so a taint cleared in the meantime cancels cleanly."""
        now = self.clock()
        with self._pending_lock:
            due_now = [k for k, d in self._pending.items() if d <= now]
            for key in due_now:
                self._pending.pop(key, None)
        for key in due_now:
            if self.eviction_queue is not None:
                self.eviction_queue.add(key)
            else:
                self.evict_one(key)

    def evict_one(self, key) -> None:
        """One paced eviction; re-verifies the decision at processing time
        (the binding or the taints may have changed while queued)."""
        ns, name, cluster_name = key
        cluster = self.store.try_get(Cluster.KIND, "", cluster_name)
        if cluster is None:
            return
        taints = [t for t in cluster.spec.taints if t.effect == EFFECT_NO_EXECUTE]
        if not taints:
            return
        rb = self.store.try_get(ResourceBinding.KIND, ns, name)
        if rb is None or not any(t.name == cluster_name for t in rb.spec.clusters):
            return
        due = self._eviction_due(rb, taints, self.clock())
        if due is None or due > self.clock():
            return  # toleration re-verified: cancelled or not yet expired

        changed = []

        def do_evict(obj: ResourceBinding) -> None:
            changed.clear()  # mutate may retry the closure
            if evict_cluster(
                obj, cluster_name,
                reason="TaintUntolerated", producer="taint-manager",
                now=self.clock(),
            ):
                changed.append(True)

        try:
            self.store.mutate(ResourceBinding.KIND, ns, name, do_evict)
        except NotFoundError:
            return
        if changed:
            ev.emit_key((ns, name), ev.TYPE_WARNING,
                        ev.REASON_EVICT_WORKLOAD_FROM_CLUSTER,
                        f"gracefully evicted from {cluster_name}: "
                        "untolerated NoExecute taint (toleration expired)",
                        origin="taint-manager")


class GracefulEvictionController:
    """Drain eviction tasks once replacement is healthy or grace expires."""

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 grace_period_s: float = DEFAULT_GRACE_PERIOD_S,
                 clock=None) -> None:
        self.store = store
        self.clock = clock if clock is not None else time.time
        self.grace_period_s = grace_period_s
        self.worker = runtime.register(AsyncWorker("graceful-eviction", self._reconcile))
        store.bus.subscribe(self._on_event, kind=ResourceBinding.KIND)
        runtime.register_periodic(self.resync, name="graceful-eviction")

    def resync(self) -> None:
        for rb in self.store.list(ResourceBinding.KIND):
            if rb.spec.graceful_eviction_tasks:
                self.worker.enqueue((rb.namespace, rb.name))

    def _on_event(self, event: Event) -> None:
        if event.obj.spec.graceful_eviction_tasks:
            self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _replacement_ready(self, rb: ResourceBinding) -> bool:
        """assessEvictionTasks health gate: every scheduled cluster applied
        and healthy (evictiontask.go:70-96)."""
        if not rb.spec.clusters:
            return False
        by_cluster = {i.cluster_name: i for i in rb.status.aggregated_status}
        for target in rb.spec.clusters:
            item = by_cluster.get(target.name)
            if item is None or not item.applied or item.health != "Healthy":
                return False
        return True

    def _reconcile(self, key) -> None:
        ns, name = key
        rb = self.store.try_get(ResourceBinding.KIND, ns, name)
        if rb is None or not rb.spec.graceful_eviction_tasks:
            return
        now = self.clock()
        ready = self._replacement_ready(rb)
        keep = []
        for task in rb.spec.graceful_eviction_tasks:
            if task.suppress_deletion:
                keep.append(task)
                continue
            grace = (
                task.grace_period_seconds
                if task.grace_period_seconds is not None
                else self.grace_period_s
            )
            expired = now - task.creation_timestamp >= grace
            if ready or expired:
                continue  # drop the task; binding controller prunes the Work
            keep.append(task)
        if len(keep) != len(rb.spec.graceful_eviction_tasks):
            drained = {t.from_cluster for t in rb.spec.graceful_eviction_tasks} - {
                t.from_cluster for t in keep
            }

            def update(obj: ResourceBinding) -> None:
                obj.spec.graceful_eviction_tasks = [
                    t for t in obj.spec.graceful_eviction_tasks
                    if t.from_cluster not in drained
                ]
            self.store.mutate(ResourceBinding.KIND, ns, name, update)
            # replacement-health progression on the timeline: the stale
            # Work finally vacates only now — "replacement healthy" is
            # the production signal, "grace expired" the bounded escape
            why = ("replacement healthy on every scheduled cluster"
                   if ready else "grace period expired")
            for cluster in sorted(drained):
                ev.emit_key((ns, name), ev.TYPE_NORMAL,
                            ev.REASON_EVICTION_TASK_DRAINED,
                            f"eviction task for {cluster} drained ({why})",
                            origin="graceful-eviction")


class ApplicationFailoverController:
    """Unhealthy-too-long workloads get evicted and rescheduled.

    Periodic-only (the reference drives this with time-based requeues,
    rb_application_failover_controller.go:89-160); eviction additionally
    requires the cluster to have been seen unhealthy in a PREVIOUS periodic
    round, so a workload that is merely still starting up (applied but not
    yet ready) never flaps even with tolerationSeconds=0.
    """

    def __init__(self, store: ObjectStore, runtime: Runtime,
                 clock=None, recorder=None) -> None:
        self.store = store
        self.clock = clock if clock is not None else time.time
        self.recorder = recorder
        self._unhealthy_since: Dict[tuple, float] = {}
        self._round = 0
        self._seen_round: Dict[tuple, int] = {}
        self._deferral_logged: set = set()
        runtime.register_periodic(self.run_once, name="application-failover")

    def run_once(self) -> None:
        self._round += 1
        for rb in self.store.list(ResourceBinding.KIND):
            if rb.spec.failover is not None:
                self._reconcile(rb)

    def _task_state(self, rb: ResourceBinding, cluster: str):
        """StatefulFailoverInjection payload for evicting `cluster`
        (applicationfailover/common.go:139-170 buildTaskOptions): preserved
        labels extracted from the failed cluster's collected status, plus
        the pre-failover cluster set.  Returns (preserved, ok); ok=False
        means the status needed by the rules has not been collected yet —
        the eviction must wait (the reference surfaces an error and
        retries)."""
        from karmada_tpu.utils.features import GATES

        rules = getattr(rb.spec.failover, "state_preservation", None) or []
        if not rules or not GATES.enabled("StatefulFailoverInjection"):
            return {}, True
        item = next((i for i in rb.status.aggregated_status
                     if i.cluster_name == cluster), None)
        if item is None or item.status is None:
            self._defer_event(rb, cluster,
                              "application status not collected yet")
            return {}, False
        try:
            preserved = build_preserved_label_state(rules, item.status)
        except (KeyError, ValueError, IndexError) as e:
            self._defer_event(rb, cluster,
                              f"state preservation rule failed: {e}")
            return {}, False
        return preserved, True

    def _defer_event(self, rb: ResourceBinding, cluster: str,
                     why: str) -> None:
        """A deferred eviction must never be invisible: the reference
        surfaces buildTaskOptions errors on every retry (common.go:147);
        here the deferral lands in the event journal (coalesced) and on
        stderr once per (binding, cluster)."""
        msg = (f"application failover of cluster {cluster!r} deferred: "
               f"{why}")
        if self.recorder is not None:
            self.recorder.event(rb, ev.TYPE_WARNING,
                                ev.REASON_EVICTION_DEFERRED, msg,
                                origin="app-failover")
        key = (rb.namespace, rb.name, cluster)
        if key not in self._deferral_logged:
            self._deferral_logged.add(key)
            import sys

            print(f"[app-failover] {rb.namespace}/{rb.name}: {msg}",
                  file=sys.stderr, flush=True)

    def _reconcile(self, rb: ResourceBinding) -> None:
        ns, name = rb.namespace, rb.name
        toleration = getattr(rb.spec.failover, "toleration_seconds",
                             DEFAULT_TOLERATION_S)
        purge = getattr(rb.spec.failover, "purge_mode", PURGE_GRACIOUSLY)
        now = self.clock()
        to_evict = []
        unhealthy_now = set()
        for item in rb.status.aggregated_status:
            k = (ns, name, item.cluster_name)
            if item.health == "Unhealthy":
                unhealthy_now.add(item.cluster_name)
                since = self._unhealthy_since.setdefault(k, now)
                first_round = self._seen_round.setdefault(k, self._round)
                if now - since >= toleration and first_round < self._round:
                    to_evict.append(item.cluster_name)
            else:
                self._unhealthy_since.pop(k, None)
                self._seen_round.pop(k, None)
        # forget stale entries for clusters no longer targeted
        for k in list(self._unhealthy_since):
            if k[:2] == (ns, name) and k[2] not in unhealthy_now:
                self._unhealthy_since.pop(k, None)
                self._seen_round.pop(k, None)
        if not to_evict:
            return

        evicted: list = []

        def update(obj: ResourceBinding) -> None:
            changed = False
            evicted.clear()  # mutate may retry the closure
            # snapshot BEFORE any eviction mutates the list: every task of
            # this pass must record the same pre-failover cluster set, or
            # later tasks omit earlier-evicted clusters and the injection
            # guard lets preserved state land on a pre-failover cluster
            before_fo = [t.name for t in obj.spec.clusters]
            for cluster in to_evict:
                preserved, ok = self._task_state(obj, cluster)
                if not ok:
                    # state-preservation rules configured but the failed
                    # cluster's status is not collected yet: keep the
                    # workload until the payload can be built (common.go:
                    # 147-151 returns an error and retries)
                    continue
                evicted.append(cluster)
                if purge == PURGE_IMMEDIATELY:
                    if preserved:
                        # an Immediately task carries the injection payload
                        # (binding/common.go:171-207 injects ONLY from
                        # Immediately/Directly tasks); the Work itself is
                        # not kept alive for Immediately purges
                        changed = evict_cluster(
                            obj, cluster, reason="ApplicationUnhealthy",
                            producer="app-failover", now=now,
                            purge_mode=PURGE_IMMEDIATELY,
                            preserved_label_state=preserved,
                            clusters_before_failover=before_fo,
                        ) or changed
                    else:
                        before = len(obj.spec.clusters)
                        obj.spec.clusters = [
                            t for t in obj.spec.clusters if t.name != cluster
                        ]
                        changed = changed or len(obj.spec.clusters) != before
                elif purge == PURGE_NEVER:
                    changed = evict_cluster(
                        obj, cluster, reason="ApplicationUnhealthy",
                        producer="app-failover", suppress_deletion=True,
                        now=now, purge_mode=PURGE_NEVER,
                        preserved_label_state=preserved,
                        clusters_before_failover=before_fo,
                    ) or changed
                else:
                    changed = evict_cluster(
                        obj, cluster, reason="ApplicationUnhealthy",
                        producer="app-failover",
                        grace_period_seconds=getattr(
                            rb.spec.failover, "grace_period_seconds", None),
                        now=now, purge_mode=PURGE_GRACIOUSLY,
                        preserved_label_state=preserved,
                        clusters_before_failover=before_fo,
                    ) or changed
            # the spec change alone re-triggers scheduling; steady mode then
            # tops the lost replicas back up without disrupting survivors

        self.store.mutate(ResourceBinding.KIND, ns, name, update)
        for cluster in evicted:
            ev.emit_key((ns, name), ev.TYPE_WARNING,
                        ev.REASON_EVICT_WORKLOAD_FROM_CLUSTER,
                        f"application unhealthy past toleration on "
                        f"{cluster}: evicted (purge={purge})",
                        origin="app-failover")
        # deferred evictions (payload not collectable yet) keep their
        # tracking state so they fire as soon as the status arrives
        for cluster in evicted:
            self._unhealthy_since.pop((ns, name, cluster), None)
            self._seen_round.pop((ns, name, cluster), None)
            # a fresh failover episode on this cluster gets its own
            # deferral notice (and the set stays bounded)
            self._deferral_logged.discard((ns, name, cluster))
