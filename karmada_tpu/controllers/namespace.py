"""Namespace sync: auto-propagate namespaces to every member cluster.

Mirrors reference pkg/controllers/namespace/namespace_sync_controller.go:70:
each non-system Namespace template is rendered into a Work for every known
cluster (no policy needed); new clusters receive all existing namespaces.
"""

from __future__ import annotations

from karmada_tpu.controllers.binding import execution_namespace
from karmada_tpu.interpreter.interpreter import prune_for_propagation
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import Work, WorkSpec
from karmada_tpu.store.store import DELETED, Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

SKIPPED_PREFIXES = ("kube-", "karmada-")
SKIPPED = {"default", "kube-system", "kube-public"}


def should_sync(name: str) -> bool:
    return name not in SKIPPED and not any(
        name.startswith(p) for p in SKIPPED_PREFIXES
    )


class NamespaceSyncController:
    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("namespace-sync", self._reconcile))
        store.bus.subscribe(self._on_event)

    def _on_event(self, event: Event) -> None:
        if event.kind == "Namespace":
            self.worker.enqueue((event.obj.name, event.type == DELETED))
        elif event.kind == Cluster.KIND and event.type != DELETED:
            for ns in self.store.list("Namespace"):
                self.worker.enqueue((ns.name, False))

    def _reconcile(self, key) -> None:
        name, deleted = key
        if not should_sync(name):
            return
        obj = self.store.try_get("Namespace", "", name)
        work_id = f"namespace-{name}"
        if deleted or obj is None or obj.metadata.deleting:
            for c in self.store.list(Cluster.KIND):
                try:
                    self.store.delete(Work.KIND, execution_namespace(c.name), work_id)
                except NotFoundError:
                    pass
            return
        assert isinstance(obj, Unstructured)
        manifest = prune_for_propagation(obj.to_manifest())
        for c in self.store.list(Cluster.KIND):
            ns = execution_namespace(c.name)
            existing = self.store.try_get(Work.KIND, ns, work_id)
            if existing is None:
                w = Work()
                w.metadata.namespace = ns
                w.metadata.name = work_id
                w.spec = WorkSpec(workload=[manifest])
                self.store.create(w)
            else:
                def update(w: Work) -> None:
                    w.spec.workload = [manifest]
                self.store.mutate(Work.KIND, ns, work_id, update)
