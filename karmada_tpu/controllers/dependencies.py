"""Dependencies distributor: propagate what a workload needs alongside it.

Mirrors reference pkg/dependenciesdistributor/dependencies_distributor.go:
117-489: when a binding has propagateDeps=true, the interpreter's
GetDependencies lists the ConfigMaps/Secrets/PVCs/ServiceAccounts its pod
template references; each existing dependency gets an *attached*
ResourceBinding whose RequiredBy snapshot mirrors the independent binding's
schedule result (syncScheduleResultToAttachedBindings :381), so the binding
controller propagates it to the same clusters.  Attached bindings are never
scheduled themselves.
"""

from __future__ import annotations

from karmada_tpu.controllers.detector import binding_name
from karmada_tpu.ops.webster import fnv32a
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import (
    BindingSnapshot,
    ObjectReference,
    ResourceBinding,
    ResourceBindingSpec,
)
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

ATTACHED_LABEL = "resourcebinding.karmada.io/depended-by"


def attached_label_key(parent_id: str) -> str:
    """Per-parent label key, so two independent bindings sharing a dependency
    each own their marker (reference dependencies_distributor.go keys labels
    by a hash of the independent binding's id for the same reason)."""
    return f"{ATTACHED_LABEL}-{fnv32a(parent_id):08x}"


def _is_attached(rb: ResourceBinding) -> bool:
    return any(k.startswith(ATTACHED_LABEL) for k in rb.metadata.labels)


class DependenciesDistributor:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        interpreter: ResourceInterpreter | None = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter or ResourceInterpreter()
        self.worker = runtime.register(AsyncWorker("deps-distributor", self._reconcile))
        store.bus.subscribe(self._on_event, kind=ResourceBinding.KIND)

    def _on_event(self, event: Event) -> None:
        rb = event.obj
        # enqueue regardless of propagate_deps: a flip to False must GC the
        # attached bindings (the reconcile handles both directions)
        if not _is_attached(rb):
            self.worker.enqueue((rb.namespace, rb.name))

    def _reconcile(self, key) -> None:
        ns, name = key
        rb = self.store.try_get(ResourceBinding.KIND, ns, name)
        parent_id = f"{ns}.{name}"
        if rb is None or rb.metadata.deleting or not rb.spec.propagate_deps:
            self._gc(parent_id, keep=set())
            return
        resource = rb.spec.resource
        template = self.store.try_get(resource.kind, resource.namespace, resource.name)
        if template is None or not isinstance(template, Unstructured):
            return
        deps = self.interpreter.get_dependencies(template.to_manifest())
        snapshot = BindingSnapshot(
            namespace=ns, name=name, clusters=list(rb.spec.clusters)
        )
        keep = set()
        for dep in deps:
            dep_obj = self.store.try_get(dep.kind, dep.namespace, dep.name)
            if dep_obj is None:
                continue  # dependency not present in the control plane yet
            attached_name = binding_name(dep.kind, dep.name)
            keep.add(attached_name)
            existing = self.store.try_get(ResourceBinding.KIND, dep.namespace,
                                          attached_name)
            if existing is None:
                arb = ResourceBinding()
                arb.metadata.namespace = dep.namespace
                arb.metadata.name = attached_name
                arb.metadata.labels[attached_label_key(parent_id)] = parent_id
                arb.spec = ResourceBindingSpec(
                    resource=ObjectReference(
                        api_version=dep.api_version, kind=dep.kind,
                        namespace=dep.namespace, name=dep.name,
                        uid=dep_obj.metadata.uid,
                    ),
                    required_by=[snapshot],
                )
                self.store.create(arb)
            else:
                def update(obj: ResourceBinding) -> None:
                    obj.metadata.labels[attached_label_key(parent_id)] = parent_id
                    rest = [s for s in obj.spec.required_by
                            if (s.namespace, s.name) != (ns, name)]
                    obj.spec.required_by = rest + [snapshot]
                self.store.mutate(ResourceBinding.KIND, dep.namespace,
                                  attached_name, update)
        self._gc(parent_id, keep)

    def _gc(self, parent_id: str, keep) -> None:
        key = attached_label_key(parent_id)
        for rb in self.store.list(ResourceBinding.KIND):
            if rb.metadata.labels.get(key) != parent_id:
                continue
            if rb.name in keep:
                continue
            ns, name = parent_id.split(".", 1)

            def update(obj: ResourceBinding, ns=ns, name=name, key=key) -> None:
                obj.spec.required_by = [
                    s for s in obj.spec.required_by
                    if (s.namespace, s.name) != (ns, name)
                ]
                obj.metadata.labels.pop(key, None)

            try:
                self.store.mutate(ResourceBinding.KIND, rb.namespace, rb.name, update)
                cur = self.store.get(ResourceBinding.KIND, rb.namespace, rb.name)
                if not cur.spec.required_by and not cur.spec.placement:
                    self.store.delete(ResourceBinding.KIND, rb.namespace, rb.name)
            except NotFoundError:
                pass
