"""Cluster lifecycle (join/unjoin) + rate-limited eviction.

Reference: pkg/controllers/cluster/cluster_controller.go:156-381 —
  * join: finalizer on the Cluster + execution space (the karmada-es-<name>
    namespace every Work for that cluster lives in);
  * unjoin: drain the execution space (delete Works), delete the space,
    then release the finalizer so the Cluster object goes away;
and eviction_worker.go + dynamic_rate_limiter.go — taint-driven evictions
flow through a rate-limited queue (ResourceEvictionRate items/second;
rate 0 halts evictions) so a zone-wide outage drains gradually instead of
stampeding every binding through rescheduling at once.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Hashable, Optional

from karmada_tpu.controllers.binding import execution_namespace
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import Work
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

CLUSTER_FINALIZER = "karmada.io/cluster-controller"


class ClusterLifecycleController:
    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("cluster-lifecycle", self._reconcile))
        store.bus.subscribe(self._on_event, kind=Cluster.KIND)
        # finalizer-held Works drain asynchronously: the periodic resync
        # retries deleting clusters until their execution space empties
        runtime.register_periodic(self._resync_deleting, name="cluster-lifecycle")

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue(event.obj.name)

    def _resync_deleting(self) -> None:
        for c in self.store.list(Cluster.KIND):
            if c.metadata.deleting:
                self.worker.enqueue(c.metadata.name)

    def _reconcile(self, name: str) -> None:
        cluster = self.store.try_get(Cluster.KIND, "", name)
        if cluster is None:
            return
        if cluster.metadata.deleting:
            self._unjoin(cluster)
            return
        # join: finalizer + execution space (createExecutionSpace :380)
        if CLUSTER_FINALIZER not in cluster.metadata.finalizers:
            def add_fin(c: Cluster) -> None:
                if CLUSTER_FINALIZER not in c.metadata.finalizers:
                    c.metadata.finalizers.append(CLUSTER_FINALIZER)
            self.store.mutate(Cluster.KIND, "", name, add_fin)
        ns_name = execution_namespace(name)
        if self.store.try_get("Namespace", "", ns_name) is None:
            self.store.create(Unstructured.from_manifest({
                "apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": ns_name, "labels": {
                    "karmada.io/managed": "true",
                    "karmada.io/execution-space-for": name,
                }},
            }))

    def _unjoin(self, cluster: Cluster) -> None:
        """removeCluster (:220): strip the cluster from schedule results,
        drain Works, drop the space, release the finalizer — ordering
        guarantees no Work survives its cluster."""
        name = cluster.metadata.name
        ns_name = execution_namespace(name)
        # bindings still targeting the vanishing cluster must lose it NOW:
        # the spec change re-enqueues the scheduler (which tops the lost
        # replicas back up elsewhere) and stops the binding controller from
        # re-creating orphan Works in the drained space
        from karmada_tpu.models.work import ResourceBinding

        for rb in self.store.list(ResourceBinding.KIND):
            if not any(tc.name == name for tc in rb.spec.clusters):
                continue

            def strip(obj: ResourceBinding) -> None:
                obj.spec.clusters = [
                    tc for tc in obj.spec.clusters if tc.name != name
                ]
                obj.spec.graceful_eviction_tasks = [
                    t for t in obj.spec.graceful_eviction_tasks
                    if t.from_cluster != name
                ]
            try:
                self.store.mutate(ResourceBinding.KIND, rb.namespace, rb.name, strip)
            except NotFoundError:
                pass
        for w in self.store.list(Work.KIND, ns_name):
            try:
                self.store.delete(Work.KIND, ns_name, w.name)
            except NotFoundError:
                pass
        if self.store.list(Work.KIND, ns_name):
            return  # finalizer-held Works drain first; retry on their events
        try:
            self.store.delete("Namespace", "", ns_name)
        except NotFoundError:
            pass
        if CLUSTER_FINALIZER in cluster.metadata.finalizers:
            def drop_fin(c: Cluster) -> None:
                if CLUSTER_FINALIZER in c.metadata.finalizers:
                    c.metadata.finalizers.remove(CLUSTER_FINALIZER)
            try:
                self.store.mutate(Cluster.KIND, "", name, drop_fin)
            except NotFoundError:
                pass


class RateLimitedEvictionQueue:
    """Token-bucket pacing for evictions (eviction_worker.go semantics:
    one item per 1/rate seconds; rate 0 halts).  Items are dedup-ed keys;
    a periodic hook drains up to the accrued allowance each tick."""

    def __init__(
        self,
        runtime: Runtime,
        process: Callable[[Hashable], None],
        rate_per_s: float = 10.0,
        clock: Callable[[], float] = time.time,
        controller_name: Optional[str] = None,
    ) -> None:
        self.process = process
        self.rate = rate_per_s
        self.clock = clock
        self._pending: "OrderedDict[Hashable, None]" = OrderedDict()
        self._tokens = max(rate_per_s, 1.0) if rate_per_s > 0 else 0.0
        self._burst = max(rate_per_s, 1.0)
        self._last = clock()
        # the owning controller's enablement switch governs the drain; a
        # generic utility must not hard-code any controller's name
        runtime.register_periodic(self.drain, name=controller_name)

    def add(self, key: Hashable) -> None:
        self._pending.setdefault(key, None)

    def pending(self) -> int:
        return len(self._pending)

    def drain(self) -> None:
        if self.rate <= 0:
            return  # evictions halted (the reference's maxEvictionDelay path)
        now = self.clock()
        self._tokens = min(self._burst, self._tokens + (now - self._last) * self.rate)
        self._last = now
        while self._pending and self._tokens >= 1.0:
            key, _ = self._pending.popitem(last=False)
            self._tokens -= 1.0
            try:
                self.process(key)
            # vet: ignore[exception-hygiene] traceback printed, eviction requeued for a paced retry
            except Exception:  # noqa: BLE001 — an eviction must not be lost
                import traceback

                traceback.print_exc()
                # requeue at the back; the spent token still paces retries
                self._pending[key] = None
