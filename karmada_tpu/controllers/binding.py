"""ResourceBinding controller: render per-cluster Work objects.

Mirrors reference pkg/controllers/binding/binding_controller.go:71-198 +
common.go:51-151 ensureWork: merge RequiredBy snapshots into the target
list, revise replicas via the interpreter for Divided scheduling
(common.go:81-89), divide Job completions (:95-108), apply override
policies (:112), and write one Work per target cluster into the cluster's
execution namespace (karmada-es-<cluster>); stale Works for dropped
clusters are removed.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karmada_tpu import obs
from karmada_tpu.controllers.override import OverrideManager
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.models.policy import (
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
)
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import (
    ResourceBinding,
    TargetCluster,
    Work,
    WorkSpec,
    merge_target_clusters,
)
from karmada_tpu.ops.webster import dispense_by_weight, fnv32a
from karmada_tpu.store.store import Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

EXECUTION_NS_PREFIX = "karmada-es-"
WORK_BINDING_LABEL = "resourcebinding.karmada.io/key"


def execution_namespace(cluster: str) -> str:
    return EXECUTION_NS_PREFIX + cluster


def work_name(binding: ResourceBinding) -> str:
    """Collision-free Work name (names.GenerateWorkName in the reference):
    the '-'-joined readable prefix is ambiguous (ns='a-b',name='c' vs
    ns='a',name='b-c'), so a hash of the full (kind, ns, name) tuple is
    appended to disambiguate."""
    ref = binding.spec.resource
    h = fnv32a(f"{ref.kind}\x00{ref.namespace}\x00{ref.name}")
    return f"{ref.name.lower()}-{ref.kind.lower()}-{h:08x}"


class BindingController:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        interpreter: Optional[ResourceInterpreter] = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter or ResourceInterpreter()
        self.overrides = OverrideManager(store)
        self.worker = runtime.register(AsyncWorker("binding", self._reconcile))
        store.bus.subscribe(self._on_event)

    def _on_event(self, event: Event) -> None:
        if event.kind == ResourceBinding.KIND:
            self.worker.enqueue((event.obj.namespace, event.obj.name))
        elif event.kind in ("OverridePolicy", "ClusterOverridePolicy"):
            for rb in self.store.list(ResourceBinding.KIND):
                self.worker.enqueue((rb.namespace, rb.name))

    # -- helpers ------------------------------------------------------------
    def _divided(self, rb: ResourceBinding) -> bool:
        placement = rb.spec.placement
        return (
            placement is not None
            and placement.replica_scheduling is not None
            and placement.replica_scheduling.replica_scheduling_type
            == REPLICA_SCHEDULING_DIVIDED
        )

    def _target_clusters(self, rb: ResourceBinding) -> List[TargetCluster]:
        """mergeTargetClusters (common.go:56-66): RequiredBy joins targets."""
        targets = list(rb.spec.clusters)
        for snapshot in rb.spec.required_by:
            targets = merge_target_clusters(targets, snapshot.clusters)
        return targets

    def _job_completions(
        self, rb: ResourceBinding, manifest: Dict, targets: List[TargetCluster]
    ) -> Dict[str, int]:
        """divideReplicasByJobCompletions (common.go:95-108): completions
        split by the same Webster weights as the replica division."""
        from karmada_tpu.models.meta import deep_get

        completions = deep_get(manifest, "spec.completions")
        if manifest.get("kind") != "Job" or completions is None or not self._divided(rb):
            return {}
        weights = {t.name: t.replicas for t in targets}
        return dispense_by_weight(int(completions), weights, None, rb.spec.resource.uid)

    # -- reconcile ----------------------------------------------------------
    def _reconcile(self, key) -> None:
        ns, name = key
        rb = self.store.try_get(ResourceBinding.KIND, ns, name)
        if rb is None or rb.metadata.deleting:
            self._remove_works(ns, name, keep=set())
            return
        resource = rb.spec.resource
        template = self.store.try_get(resource.kind, resource.namespace, resource.name)
        if template is None or not isinstance(template, Unstructured):
            return
        from karmada_tpu.interpreter.interpreter import prune_for_propagation

        manifest = prune_for_propagation(template.to_manifest())
        targets = self._target_clusters(rb)
        completions = self._job_completions(rb, manifest, targets)

        # Immediately-purged clusters do not keep their old Work alive; the
        # task itself survives only as the injection payload carrier
        eviction = {t.from_cluster for t in rb.spec.graceful_eviction_tasks
                    if t.purge_mode != "Immediately"}
        keep = set()
        # flight recorder: per-target Work rendering (interpreter revise +
        # override apply + store write) is where a binding reconcile's time
        # goes — one span under the worker's reconcile root
        with obs.TRACER.span(obs.SPAN_BINDING_RENDER,
                             targets=len(targets)):
            for target in targets:
                # never materialize a Work for a cluster that no longer
                # exists: an unjoined cluster's execution space has been
                # drained and nothing would ever clean an orphan up
                if self._cluster(target.name) is None:
                    continue
                m = dict(manifest)
                if self._divided(rb) and rb.spec.replicas > 0:
                    m = self.interpreter.revise_replica(m, target.replicas)
                if target.name in completions:
                    m = self.interpreter.revise_job_completions(
                        m, completions[target.name])
                m = self.overrides.apply(m, self._cluster(target.name))
                m = self._inject_preserved_state(rb, target, m, len(targets))
                suspend = self._suspended(rb, target.name)
                self._ensure_work(rb, target.name, m, suspend)
                keep.add(target.name)
        # graceful eviction: keep the old Work until the task drains
        keep |= eviction
        self._remove_works(ns, name, keep)

    def _inject_preserved_state(self, rb: ResourceBinding,
                                target: TargetCluster, manifest: Dict,
                                n_targets: int) -> Dict:
        """StatefulFailoverInjection (binding/common.go:171-207
        injectReservedLabelState): merge the last eviction task's preserved
        label state into the replacement cluster's rendered workload.
        Restrictions mirror the reference: single-target bindings only,
        latest task must be an Immediately/Directly purge with a non-empty
        payload, and the move-to cluster must not be one the application
        ran on before the failover."""
        from karmada_tpu.utils.features import GATES

        if not GATES.enabled("StatefulFailoverInjection"):
            return manifest
        if n_targets > 1 or not rb.spec.graceful_eviction_tasks:
            return manifest
        task = rb.spec.graceful_eviction_tasks[-1]
        if task.purge_mode not in ("Immediately", "Directly"):
            return manifest
        if target.name in task.clusters_before_failover:
            return manifest
        if not task.preserved_label_state:
            return manifest
        m = dict(manifest)
        meta = dict(m.get("metadata") or {})
        labels = dict(meta.get("labels") or {})
        labels.update(task.preserved_label_state)
        meta["labels"] = labels
        m["metadata"] = meta
        return m

    def _suspended(self, rb: ResourceBinding, cluster: str) -> bool:
        s = rb.spec.suspension
        if s is None:
            return False
        if s.dispatching:
            return True
        return cluster in (s.dispatching_on_clusters or [])

    def _cluster(self, name: str):
        return self.store.try_get("Cluster", "", name)

    def _ensure_work(self, rb: ResourceBinding, cluster: str, manifest, suspend: bool) -> None:
        ns = execution_namespace(cluster)
        name = work_name(rb)
        label_val = f"{rb.namespace}.{rb.name}"
        existing = self.store.try_get(Work.KIND, ns, name)
        if existing is None:
            w = Work()
            w.metadata.namespace = ns
            w.metadata.name = name
            w.metadata.labels[WORK_BINDING_LABEL] = label_val
            w.spec = WorkSpec(workload=[manifest], suspend_dispatching=suspend)
            self.store.create(w)
        else:
            def update(w):
                w.metadata.labels[WORK_BINDING_LABEL] = label_val
                w.spec.workload = [manifest]
                w.spec.suspend_dispatching = suspend
            self.store.mutate(Work.KIND, ns, name, update)

    def _remove_works(self, rb_ns: str, rb_name: str, keep) -> None:
        label_val = f"{rb_ns}.{rb_name}"
        for w in self.store.list(Work.KIND):
            if w.metadata.labels.get(WORK_BINDING_LABEL) != label_val:
                continue
            cluster = w.metadata.namespace[len(EXECUTION_NS_PREFIX):]
            if cluster in keep:
                continue
            try:
                self.store.delete(Work.KIND, w.metadata.namespace, w.name)
            except NotFoundError:
                pass
