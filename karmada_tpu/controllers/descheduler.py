"""Descheduler: move replicas stuck unschedulable in their member cluster.

Mirrors reference pkg/descheduler/descheduler.go:80-330: every descheduling
interval, for Divided+Dynamic bindings, query per-cluster unschedulable
replicas (the estimator's GetUnschedulableReplicas; here the member
simulator's admission plan), subtract them from the binding's target
(core/helper.go SchedulingResultHelper.TargetToUnschedulableReplicas), and
let the scheduler top the lost replicas back up elsewhere (steady mode).
"""

from __future__ import annotations

from typing import Dict

from karmada_tpu.members.member import FakeMemberCluster
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_SCHEDULING_DIVIDED,
)
from karmada_tpu.models.work import ResourceBinding, TargetCluster
from karmada_tpu.store.store import ObjectStore
from karmada_tpu.store.worker import Runtime


class Descheduler:
    """Shares the scheduler's estimator tier: unschedulable counts come from
    the per-member estimator servers over the wire protocol
    (descheduler.go:141 -> GetUnschedulableReplicas gRPC), exactly the path
    the reference runs.  `members` remains only as a health gate and as a
    fallback when no estimator client is wired (unit-test harnesses)."""

    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        members: Dict[str, FakeMemberCluster],
        estimator=None,  # AccurateEstimatorClient (wire path) or None
        # shared eviction-pacing ledger (rebalance/pacing.EvictionBudget):
        # the stuck-replica mover and the rebalance plane's drains draw
        # from the SAME per-cluster budget, so the two evictors cannot
        # stampede one cluster in the same interval.  None = unpaced
        # (the pre-budget behavior; unit-test harnesses).
        budget=None,
    ) -> None:
        self.store = store
        self.members = members
        self.estimator = estimator
        self.budget = budget
        runtime.register_periodic(self.run_once, name="descheduler")

    def _stuck_replicas(self, cluster: str, resource) -> int:
        if self.estimator is not None:
            n = self.estimator.unschedulable_replicas(
                cluster, resource.kind, resource.namespace, resource.name
            )
            return max(n, 0)  # UNAUTHENTIC_REPLICA (-1) == unknown: skip
        member = self.members.get(cluster)
        if member is None:
            return 0
        return member.unschedulable_replicas(
            resource.kind, resource.namespace, resource.name
        )

    def _eligible(self, rb: ResourceBinding) -> bool:
        """descheduler.go:197-214: Divided + dynamic-weight or aggregated."""
        placement = rb.spec.placement
        if placement is None or placement.replica_scheduling is None:
            return False
        s = placement.replica_scheduling
        if s.replica_scheduling_type != REPLICA_SCHEDULING_DIVIDED:
            return False
        if s.replica_division_preference == REPLICA_DIVISION_AGGREGATED:
            return True
        return (
            s.weight_preference is not None
            and s.weight_preference.dynamic_weight == DYNAMIC_WEIGHT_AVAILABLE_REPLICAS
        )

    def run_once(self) -> None:
        for rb in self.store.list(ResourceBinding.KIND):
            if not self._eligible(rb) or not rb.spec.clusters:
                continue
            resource = rb.spec.resource
            shrink: Dict[str, int] = {}
            for target in rb.spec.clusters:
                member = self.members.get(target.name)
                if member is None or not member.healthy:
                    continue
                stuck = self._stuck_replicas(target.name, resource)
                if stuck <= 0:
                    continue
                # shared pacing: one token per (binding, cluster) shrink,
                # drawn from the same per-cluster ledger the rebalance
                # plane drains against — a cluster that already absorbed
                # its interval's evictions is skipped until the window
                # rolls (the skipped shrink re-detects next round)
                if (self.budget is not None
                        and not self.budget.try_acquire(
                            target.name, consumer="descheduler")):
                    continue
                shrink[target.name] = min(stuck, target.replicas)
            if not shrink:
                continue

            def update(obj: ResourceBinding) -> None:
                new = []
                for t in obj.spec.clusters:
                    n = t.replicas - shrink.get(t.name, 0)
                    if n > 0:
                        new.append(TargetCluster(name=t.name, replicas=n))
                obj.spec.clusters = new

            self.store.mutate(ResourceBinding.KIND, rb.namespace, rb.name, update)
