"""Multi-cluster service discovery (MCS).

Reference controllers:
  * MCSController (pkg/controllers/multiclusterservice/mcs_controller.go:71)
    — propagates the referenced Service to provider + consumer clusters via
    Works when a MultiClusterService exists.
  * EndpointSliceCollectController (endpointslice_collect_controller.go:87)
    — watches provider members' EndpointSlices for exported services and
    reports them UP into the control plane (cluster-tagged).
  * EndpointsliceDispatchController (endpointslice_dispatch_controller.go:68)
    — dispatches the collected provider slices DOWN to consumer clusters
    via Works, renamed per origin cluster so consumers resolve endpoints.
  * ServiceExportController (pkg/controllers/mcs/service_export_controller.go:103)
    — the mcs.k8s.io flavor: a propagated ServiceExport marks a service for
    collection the same way.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

from karmada_tpu.controllers.binding import execution_namespace
from karmada_tpu.models.meta import deep_get
from karmada_tpu.models.networking import (
    EXPOSURE_CROSS_CLUSTER,
    MultiClusterService,
    ServiceExport,
)
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import Work, WorkSpec
from karmada_tpu.store.store import DELETED, Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

# annotations/labels on collected + dispatched slices (reference constants)
SERVICE_NAME_LABEL = "kubernetes.io/service-name"
ORIGIN_CLUSTER_ANNOTATION = "endpointslice.karmada.io/origin-cluster"
MANAGED_BY_ANNOTATION = "endpointslice.karmada.io/managed-by"
WORK_PREFIX = "mcs"


def _collected_name(cluster: str, ns: str, name: str) -> str:
    """Cluster-qualified upward name.  A short hash disambiguates the
    '-'-joined parts (cluster 'a' + slice 'b-c' vs cluster 'a-b' + slice
    'c' would otherwise collide and silently drop one provider's
    endpoints)."""
    from karmada_tpu.ops.webster import fnv32a

    h = fnv32a(f"{cluster}/{ns}/{name}") & 0xFFFF
    return f"imported-{cluster}-{name}-{h:04x}"


class MultiClusterServiceController:
    """MCS object -> Service Works on provider + consumer clusters."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(AsyncWorker("mcs", self._reconcile))
        store.bus.subscribe(self._on_event, kind=MultiClusterService.KIND)
        store.bus.subscribe(self._on_service_event, kind="Service")

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _on_service_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _work_name(self, ns: str, name: str) -> str:
        return f"{WORK_PREFIX}-service-{ns}-{name}"

    def _target_clusters(self, mcs: MultiClusterService) -> List[str]:
        from karmada_tpu.models.cluster import Cluster

        all_clusters = [c.name for c in self.store.list(Cluster.KIND)]
        providers = mcs.provider_names() or all_clusters
        consumers = mcs.consumer_names() or all_clusters
        # preserve order, dedupe
        out: List[str] = []
        for n in providers + consumers:
            if n in all_clusters and n not in out:
                out.append(n)
        return out

    def _reconcile(self, key) -> None:
        ns, name = key
        mcs = self.store.try_get(MultiClusterService.KIND, ns, name)
        service = self.store.try_get("Service", ns, name)
        work_name = self._work_name(ns, name)
        from karmada_tpu.models.cluster import Cluster

        if (
            mcs is None or mcs.metadata.deleting
            or EXPOSURE_CROSS_CLUSTER not in mcs.spec.types
            or service is None
        ):
            for c in self.store.list(Cluster.KIND):
                try:
                    self.store.delete(Work.KIND, execution_namespace(c.name), work_name)
                except NotFoundError:
                    pass
            return
        assert isinstance(service, Unstructured)
        manifest = copy.deepcopy(service.to_manifest())
        targets = set(self._target_clusters(mcs))
        for c in self.store.list(Cluster.KIND):
            wns = execution_namespace(c.name)
            if c.name not in targets:
                try:
                    self.store.delete(Work.KIND, wns, work_name)
                except NotFoundError:
                    pass
                continue
            existing = self.store.try_get(Work.KIND, wns, work_name)
            if existing is None:
                w = Work()
                w.metadata.namespace = wns
                w.metadata.name = work_name
                w.spec = WorkSpec(workload=[manifest])
                self.store.create(w)
            else:
                def update(w: Work) -> None:
                    w.spec.workload = [manifest]
                self.store.mutate(Work.KIND, wns, work_name, update)


class MultiClusterIngressController:
    """MultiClusterIngress -> per-cluster Ingress Works
    (pkg/controllers/multiclusteringress): the derived Ingress lands on the
    clusters serving its backend services — the consumer clusters of each
    backend's MultiClusterService, or every cluster when no MCS scopes it."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        from karmada_tpu.models.networking import MultiClusterIngress

        self.store = store
        self.worker = runtime.register(AsyncWorker("mci", self._reconcile))
        store.bus.subscribe(self._on_event, kind=MultiClusterIngress.KIND)
        store.bus.subscribe(self._on_mcs, kind=MultiClusterService.KIND)
        store.bus.subscribe(self._on_cluster, kind="Cluster")

    def _on_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _on_mcs(self, event: Event) -> None:
        from karmada_tpu.models.networking import MultiClusterIngress

        for mci in self.store.list(MultiClusterIngress.KIND, event.obj.namespace):
            self.worker.enqueue((mci.namespace, mci.name))

    def _on_cluster(self, event: Event) -> None:
        # membership changes must refresh the "everywhere" fallback scope
        from karmada_tpu.models.networking import MultiClusterIngress

        for mci in self.store.list(MultiClusterIngress.KIND):
            self.worker.enqueue((mci.namespace, mci.name))

    def _work_name(self, ns: str, name: str) -> str:
        from karmada_tpu.ops.webster import fnv32a

        h = fnv32a(f"{ns}/{name}") & 0xFFFF
        return f"{WORK_PREFIX}-ingress-{ns}-{name}-{h:04x}"

    def _backend_services(self, mci) -> List[str]:
        names: List[str] = []
        svc = deep_get(mci.spec.default_backend, "service.name")
        if svc:
            names.append(svc)
        for rule in mci.spec.rules:
            for path in deep_get(rule, "http.paths", []) or []:
                svc = deep_get(path, "backend.service.name")
                if svc and svc not in names:
                    names.append(svc)
        return names

    def _target_clusters(self, mci) -> List[str]:
        from karmada_tpu.models.cluster import Cluster

        all_clusters = [c.name for c in self.store.list(Cluster.KIND)]
        scoped: List[str] = []
        any_mcs = False
        for svc in self._backend_services(mci):
            mcs = self.store.try_get(MultiClusterService.KIND, mci.namespace, svc)
            if mcs is None or mcs.metadata.deleting:
                continue
            any_mcs = True
            for n in mcs.consumer_names() or all_clusters:
                if n not in scoped:
                    scoped.append(n)
        return scoped if any_mcs else all_clusters

    def _reconcile(self, key) -> None:
        from karmada_tpu.models.cluster import Cluster
        from karmada_tpu.models.networking import MultiClusterIngress

        ns, name = key
        mci = self.store.try_get(MultiClusterIngress.KIND, ns, name)
        work_name = self._work_name(ns, name)
        targets = set()
        if mci is not None and not mci.metadata.deleting:
            targets = set(self._target_clusters(mci))
            manifest = {
                "apiVersion": "networking.k8s.io/v1",
                "kind": "Ingress",
                "metadata": {"name": name, "namespace": ns,
                             "labels": {"multiclusteringress.karmada.io/name": name}},
                "spec": {
                    "rules": copy.deepcopy(mci.spec.rules),
                    **(
                        {"defaultBackend": copy.deepcopy(mci.spec.default_backend)}
                        if mci.spec.default_backend else {}
                    ),
                },
            }
        for c in self.store.list(Cluster.KIND):
            wns = execution_namespace(c.name)
            if c.name not in targets:
                try:
                    self.store.delete(Work.KIND, wns, work_name)
                except NotFoundError:
                    pass
                continue
            existing = self.store.try_get(Work.KIND, wns, work_name)
            if existing is None:
                w = Work()
                w.metadata.namespace = wns
                w.metadata.name = work_name
                w.spec = WorkSpec(workload=[manifest])
                self.store.create(w)
            else:
                def update(w: Work) -> None:
                    w.spec.workload = [manifest]
                self.store.mutate(Work.KIND, wns, work_name, update)


class EndpointSliceCollectController:
    """Provider members' EndpointSlices -> control-plane (cluster-tagged).

    Subscribes to each member's store (the per-cluster informer); slices
    labeled kubernetes.io/service-name for a service exported by an MCS
    (with that member as provider) or by a ServiceExport are reported up.
    """

    def __init__(self, store: ObjectStore, runtime: Runtime, members: Dict) -> None:
        self.store = store
        self.members = members
        self.worker = runtime.register(
            AsyncWorker("endpointslice-collect", self._reconcile)
        )
        self._subscribed: set = set()
        # cluster -> (member, bus handler): teardown needs the exact refs
        self._member_handlers: Dict[str, tuple] = {}
        for name in list(members):
            self.watch_member(name)
        # resync when exports change
        store.bus.subscribe(self._on_export_event, kind=MultiClusterService.KIND)
        store.bus.subscribe(self._on_export_event, kind=ServiceExport.KIND)

    def watch_member(self, cluster: str) -> None:
        if cluster in self._subscribed:
            return
        self._subscribed.add(cluster)
        member = self.members[cluster]
        handler = self._member_event(cluster)
        self._member_handlers[cluster] = (member, handler)
        member.store.bus.subscribe(handler)
        for obj in member.store.list("EndpointSlice"):
            self.worker.enqueue((cluster, obj.namespace, obj.name, False))

    def unwatch_member(self, cluster: str) -> None:
        """Unjoin teardown for one member: bus handler off, refs dropped."""
        self._subscribed.discard(cluster)
        entry = self._member_handlers.pop(cluster, None)
        if entry is not None:
            member, handler = entry
            member.store.bus.unsubscribe(handler)

    def detach(self, runtime: Runtime) -> None:
        """Full teardown (agent-scoped instances unwind on unregister)."""
        runtime.unregister(self.worker)
        self.store.bus.unsubscribe(self._on_export_event)
        for cluster in list(self._member_handlers):
            self.unwatch_member(cluster)

    def _member_event(self, cluster: str):
        def handler(event: Event) -> None:
            if event.obj.KIND != "EndpointSlice":
                return
            self.worker.enqueue(
                (cluster, event.obj.namespace, event.obj.name,
                 event.type == DELETED)
            )
        return handler

    def _on_export_event(self, event: Event) -> None:
        for cluster, member in self.members.items():
            for obj in member.store.list("EndpointSlice"):
                self.worker.enqueue((cluster, obj.namespace, obj.name, False))

    def _exported(self, cluster: str, ns: str, service: str) -> bool:
        mcs = self.store.try_get(MultiClusterService.KIND, ns, service)
        if mcs is not None and not mcs.metadata.deleting:
            providers = mcs.provider_names()
            if not providers or cluster in providers:
                return True
        exp = self.store.try_get(ServiceExport.KIND, ns, service)
        return exp is not None and not exp.metadata.deleting

    def _reconcile(self, key) -> None:
        cluster, ns, name, deleted = key
        collected = _collected_name(cluster, ns, name)
        member = self.members.get(cluster)
        obj = None if (deleted or member is None) else member.get("EndpointSlice", ns, name)
        service = ""
        if obj is not None:
            # never re-collect a slice this framework dispatched INTO the
            # member: that would bounce slices between collect and dispatch
            # forever (each round minting a new imported-... name)
            annotations = deep_get(obj.manifest, "metadata.annotations", {}) or {}
            if MANAGED_BY_ANNOTATION in annotations:
                return
            service = deep_get(obj.manifest, "metadata.labels", {}).get(
                SERVICE_NAME_LABEL, "")
        if obj is None or not service or not self._exported(cluster, ns, service):
            try:
                self.store.delete("EndpointSlice", ns, collected)
            except NotFoundError:
                pass
            return
        manifest = copy.deepcopy(obj.to_manifest())
        manifest.setdefault("metadata", {})["name"] = collected
        md = manifest["metadata"]
        md.setdefault("labels", {})[SERVICE_NAME_LABEL] = service
        md.setdefault("annotations", {})[ORIGIN_CLUSTER_ANNOTATION] = cluster
        md["annotations"][MANAGED_BY_ANNOTATION] = "karmada-tpu"
        reported = Unstructured.from_manifest(manifest)
        existing = self.store.try_get("EndpointSlice", ns, collected)
        if existing is None:
            self.store.create(reported)
        else:
            def update(o) -> None:
                o.manifest = copy.deepcopy(manifest)
                o.metadata.labels = dict(md.get("labels", {}))
                o.metadata.annotations = dict(md.get("annotations", {}))
            self.store.mutate("EndpointSlice", ns, collected, update)


class EndpointSliceDispatchController:
    """Collected provider slices -> Works on consumer clusters (excluding
    the origin cluster), so a consumer's resolver sees remote endpoints."""

    def __init__(self, store: ObjectStore, runtime: Runtime) -> None:
        self.store = store
        self.worker = runtime.register(
            AsyncWorker("endpointslice-dispatch", self._reconcile)
        )
        store.bus.subscribe(self._on_slice_event, kind="EndpointSlice")
        store.bus.subscribe(self._on_mcs_event, kind=MultiClusterService.KIND)

    def _on_slice_event(self, event: Event) -> None:
        self.worker.enqueue((event.obj.namespace, event.obj.name))

    def _on_mcs_event(self, event: Event) -> None:
        ns = event.obj.namespace
        for obj in self.store.list("EndpointSlice", ns):
            self.worker.enqueue((ns, obj.name))

    def _work_name(self, ns: str, slice_name: str) -> str:
        return f"{WORK_PREFIX}-eps-{ns}-{slice_name}"

    def _reconcile(self, key) -> None:
        ns, name = key
        from karmada_tpu.models.cluster import Cluster

        obj = self.store.try_get("EndpointSlice", ns, name)
        work_name = self._work_name(ns, name)
        origin = ""
        service = ""
        consumers: List[str] = []
        if obj is not None and not obj.metadata.deleting:
            origin = obj.metadata.annotations.get(ORIGIN_CLUSTER_ANNOTATION, "")
            service = obj.metadata.labels.get(SERVICE_NAME_LABEL, "")
        ok = bool(origin and service)
        if ok:
            mcs = self.store.try_get(MultiClusterService.KIND, ns, service)
            if mcs is None or mcs.metadata.deleting:
                ok = False
            else:
                all_clusters = [c.name for c in self.store.list(Cluster.KIND)]
                consumers = mcs.consumer_names() or all_clusters
        for c in self.store.list(Cluster.KIND):
            wns = execution_namespace(c.name)
            want = ok and c.name in consumers and c.name != origin
            if not want:
                try:
                    self.store.delete(Work.KIND, wns, work_name)
                except NotFoundError:
                    pass
                continue
            manifest = copy.deepcopy(obj.to_manifest())
            existing = self.store.try_get(Work.KIND, wns, work_name)
            if existing is None:
                w = Work()
                w.metadata.namespace = wns
                w.metadata.name = work_name
                w.spec = WorkSpec(workload=[manifest])
                self.store.create(w)
            else:
                def update(w: Work) -> None:
                    w.spec.workload = [manifest]
                self.store.mutate(Work.KIND, wns, work_name, update)
