"""ResourceDetector: match templates to policies, emit ResourceBindings.

Mirrors reference pkg/detector/detector.go: watches every template kind
(dynamic informers, :183), matches template<->policy (LookForMatchedPolicy
:382 -- namespaced PropagationPolicy beats ClusterPropagationPolicy;
explicit priority, then name-selector specificity, then alphabetical),
claims the object, and builds the ResourceBinding (BuildResourceBinding
:793) with replicas/requirements from the resource interpreter
(applyReplicaInterpretation :1455).  Policy create/update fans out to all
matching templates (:991); policy delete releases claims and GCs bindings.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from karmada_tpu import obs
from karmada_tpu.controllers.override import selector_matches
from karmada_tpu.interpreter import ResourceInterpreter
from karmada_tpu.models.meta import OwnerReference
from karmada_tpu.models.policy import (
    LAZY_ACTIVATION,
    ClusterPropagationPolicy,
    PropagationPolicy,
    ResourceSelector,
)
from karmada_tpu.models.unstructured import Unstructured
from karmada_tpu.models.work import (
    BindingSuspension,
    ObjectReference,
    ResourceBinding,
    ResourceBindingSpec,
)
from karmada_tpu.store.store import DELETED, Event, NotFoundError, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime

# claim labels (reference pkg/util/constants: PropagationPolicy labels)
POLICY_LABEL = "propagationpolicy.karmada.io/permanent-id"
CLUSTER_POLICY_LABEL = "clusterpropagationpolicy.karmada.io/permanent-id"
BINDING_POLICY_LABEL = POLICY_LABEL

# kinds owned by the framework itself -- never treated as templates
FRAMEWORK_KINDS = {
    "Cluster", "PropagationPolicy", "ClusterPropagationPolicy",
    "OverridePolicy", "ClusterOverridePolicy", "ResourceBinding",
    "ClusterResourceBinding", "Work", "FederatedResourceQuota",
    "WorkloadRebalancer", "FederatedHPA", "CronFederatedHPA", "Remedy",
    "ClusterTaintPolicy", "MultiClusterService", "ResourceRegistry",
    "ResourceInterpreterCustomization",
}


def binding_name(kind: str, name: str) -> str:
    """names.GenerateBindingName: lowercase kind suffix."""
    return f"{name}-{kind.lower()}"


def _selector_specificity(sel: ResourceSelector) -> int:
    """name match > label-selector match > kind-wide (detector/policy.go)."""
    if sel.name:
        return 2
    if sel.label_selector is not None:
        return 1
    return 0


class ResourceDetector:
    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        interpreter: Optional[ResourceInterpreter] = None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter or ResourceInterpreter()
        self.worker = runtime.register(AsyncWorker("detector", self._reconcile))
        self.policy_worker = runtime.register(
            AsyncWorker("detector-policy", self._reconcile_policy)
        )
        store.bus.subscribe(self._on_event)

    # -- event wiring -------------------------------------------------------
    def _on_event(self, event: Event) -> None:
        kind = event.kind
        if kind in (PropagationPolicy.KIND, ClusterPropagationPolicy.KIND):
            self.policy_worker.enqueue((kind, event.obj.namespace, event.obj.name,
                                        event.type == DELETED))
            return
        if kind in FRAMEWORK_KINDS or not isinstance(event.obj, Unstructured):
            return
        self.worker.enqueue((kind, event.obj.namespace, event.obj.name, False))

    # -- policy fan-out -----------------------------------------------------
    def _reconcile_policy(self, key) -> None:
        kind, namespace, name, deleted = key
        if deleted:
            label = POLICY_LABEL if kind == PropagationPolicy.KIND else CLUSTER_POLICY_LABEL
            uid = f"{namespace}/{name}" if namespace else name
            for rb in self.store.list(ResourceBinding.KIND):
                if rb.metadata.labels.get(label) == uid:
                    try:
                        self.store.delete(ResourceBinding.KIND, rb.namespace, rb.name)
                    except NotFoundError:
                        pass
        # re-evaluate every template (policy preemption/claim updates);
        # from_policy=True so Lazy activation can defer (detector.go:1485)
        for obj in self.store.items():
            if isinstance(obj, Unstructured) and obj.KIND not in FRAMEWORK_KINDS:
                self.worker.enqueue((obj.KIND, obj.namespace, obj.name, True))

    # -- template reconcile -------------------------------------------------
    def _matched_policies(
        self, obj: Unstructured, manifest: dict
    ) -> Tuple[Optional[PropagationPolicy], Optional[ClusterPropagationPolicy]]:
        def best(policies):
            matched = []
            for p in policies:
                for sel in p.spec.resource_selectors:
                    if selector_matches(sel, manifest):
                        matched.append((p.spec.priority, _selector_specificity(sel), p))
                        break
            if not matched:
                return None
            # highest priority, then most specific selector, then name asc
            matched.sort(key=lambda t: (-t[0], -t[1], t[2].name))
            return matched[0][2]

        pps = [
            p for p in self.store.list(PropagationPolicy.KIND)
            if p.metadata.namespace == obj.namespace
        ]
        cpps = self.store.list(ClusterPropagationPolicy.KIND)
        return best(pps), best(cpps)

    def _current_claim(self, obj: Unstructured):
        """The policy currently claiming `obj` via claim labels (or None)."""
        pid = obj.metadata.labels.get(POLICY_LABEL)
        if pid is not None:
            ns, _, nm = pid.partition("/")
            return self.store.try_get(PropagationPolicy.KIND, ns, nm)
        pid = obj.metadata.labels.get(CLUSTER_POLICY_LABEL)
        if pid is not None:
            return self.store.try_get(ClusterPropagationPolicy.KIND, "", pid)
        return None

    @staticmethod
    def _still_matches(policy, manifest) -> bool:
        return any(
            selector_matches(sel, manifest) for sel in policy.spec.resource_selectors
        )

    def _effective_policy(self, obj: Unstructured, manifest: dict, pp, cpp):
        """Claim stickiness + preemption (preemption.go:50-107).

        An object claimed by a still-matching policy STAYS claimed; a
        different policy takes over only with `preemption: Always` and the
        reference's priority rule (high-priority PP > low-priority PP >
        CPP; CPP preempts CPP by priority only).
        """
        challenger = pp if pp is not None else cpp
        cur = self._current_claim(obj)
        if cur is None or not self._still_matches(cur, manifest):
            return challenger
        if challenger is None or challenger is cur:
            return cur
        cur_is_cpp = isinstance(cur, ClusterPropagationPolicy)
        ch_is_cpp = isinstance(challenger, ClusterPropagationPolicy)
        always = challenger.spec.preemption == "Always"
        if not always:
            return cur
        if cur_is_cpp and not ch_is_cpp:
            return challenger  # PP > CPP (preemptClusterPropagationPolicyDirectly)
        if cur_is_cpp == ch_is_cpp and challenger.spec.priority > cur.spec.priority:
            return challenger
        return cur

    def _reconcile(self, key) -> None:
        kind, namespace, name, from_policy = key
        obj = self.store.try_get(kind, namespace, name)
        rb_name = binding_name(kind, name)
        if obj is None or obj.metadata.deleting:
            try:
                self.store.delete(ResourceBinding.KIND, namespace, rb_name)
            except NotFoundError:
                pass
            return
        assert isinstance(obj, Unstructured)
        manifest = obj.to_manifest()
        # flight recorder: policy matching is the detector's hot phase (it
        # scans every policy's selector list per template event), so it
        # gets its own span under the worker's reconcile root
        with obs.TRACER.span(obs.SPAN_DETECTOR_MATCH, kind=kind,
                             template=name) as sp:
            pp, cpp = self._matched_policies(obj, manifest)
            policy = self._effective_policy(obj, manifest, pp, cpp)
            if sp:
                sp.set_attr(matched=policy.name if policy else None)
        # Lazy activation (detector.go:1485-1497): a policy-driven change
        # does not touch templates whose effective policy is Lazy -- the new
        # policy content applies only when the resource itself next changes
        if (
            from_policy
            and policy is not None
            and policy.spec.activation_preference == LAZY_ACTIVATION
        ):
            return
        if policy is None:
            # no policy claims it; drop a stale binding if we created one
            try:
                self.store.delete(ResourceBinding.KIND, namespace, rb_name)
            except NotFoundError:
                pass
            return
        label = POLICY_LABEL if isinstance(policy, PropagationPolicy) and not isinstance(
            policy, ClusterPropagationPolicy) else CLUSTER_POLICY_LABEL
        policy_id = (
            f"{policy.metadata.namespace}/{policy.name}"
            if policy.metadata.namespace
            else policy.name
        )

        other_label = (
            CLUSTER_POLICY_LABEL if label == POLICY_LABEL else POLICY_LABEL
        )
        # claim the template (ClaimPolicyForObject, detector/claim.go);
        # preemption drops the losing policy's claim so its deletion can no
        # longer GC this object's binding
        if (
            obj.metadata.labels.get(label) != policy_id
            or other_label in obj.metadata.labels
        ):
            def claim(o):
                o.metadata.labels[label] = policy_id
                o.metadata.labels.pop(other_label, None)
            self.store.mutate(kind, namespace, name, claim)

        # applyReplicaInterpretation (detector.go:1454-1482): components win
        # over plain replicas when an InterpretComponent customization exists
        components = self.interpreter.get_components(manifest)
        if components is not None:
            replicas, requirements = 0, None
        else:
            components = []
            replicas, requirements = self.interpreter.get_replicas(manifest)
        spec = policy.spec
        suspension = None
        if spec.suspension is not None:
            suspension = BindingSuspension(
                scheduling=spec.suspension.scheduling,
                dispatching=spec.suspension.dispatching,
            )

        existing = self.store.try_get(ResourceBinding.KIND, namespace, rb_name)
        if existing is None:
            rb = ResourceBinding()
            rb.metadata.name = rb_name
            rb.metadata.namespace = namespace
            rb.metadata.labels[label] = policy_id
            rb.metadata.labels.pop(other_label, None)
            rb.metadata.owner_references = [OwnerReference(
                api_version=obj.API_VERSION, kind=kind, name=name,
                uid=obj.metadata.uid,
            )]
            rb.spec = ResourceBindingSpec(
                resource=ObjectReference(
                    api_version=obj.API_VERSION, kind=kind, namespace=namespace,
                    name=name, uid=obj.metadata.uid,
                    resource_version=obj.metadata.resource_version,
                ),
                replicas=replicas,
                replica_requirements=requirements,
                components=list(components),
                placement=spec.placement,
                propagate_deps=spec.propagate_deps,
                conflict_resolution=spec.conflict_resolution,
                schedule_priority=spec.schedule_priority,
                suspension=suspension,
                failover=spec.failover,
            )
            self.store.create(rb)
        else:
            def update(rb):
                rb.metadata.labels[label] = policy_id
                rb.metadata.labels.pop(other_label, None)
                # preserve the schedule result + eviction state; refresh the rest
                rb.spec.resource.resource_version = obj.metadata.resource_version
                rb.spec.resource.uid = obj.metadata.uid
                rb.spec.replicas = replicas
                rb.spec.replica_requirements = requirements
                rb.spec.components = list(components)
                rb.spec.placement = spec.placement
                rb.spec.propagate_deps = spec.propagate_deps
                rb.spec.conflict_resolution = spec.conflict_resolution
                rb.spec.schedule_priority = spec.schedule_priority
                rb.spec.suspension = suspension
                rb.spec.failover = spec.failover
            self.store.mutate(ResourceBinding.KIND, namespace, rb_name, update)
