"""Override manager: per-cluster manifest mutation before Work rendering.

Mirrors reference pkg/util/overridemanager/overridemanager.go:95
ApplyOverridePolicies: ClusterOverridePolicies apply first, then namespaced
OverridePolicies (both name-ordered), each rule gated on the target-cluster
affinity; overriders are image / command / args / labels / annotations /
plaintext in that order (overridemanager.go applyJSONPatchs order).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import deep_get, deep_set
from karmada_tpu.models.policy import (
    ClusterOverridePolicy,
    CommandArgsOverrider,
    ImageOverrider,
    LabelAnnotationOverrider,
    OverridePolicy,
    Overriders,
    PlaintextOverrider,
    ResourceSelector,
)
from karmada_tpu.store.store import ObjectStore


def selector_matches(sel: ResourceSelector, manifest: Dict[str, Any]) -> bool:
    if sel.api_version and sel.api_version != manifest.get("apiVersion"):
        return False
    if sel.kind and sel.kind != manifest.get("kind"):
        return False
    md = manifest.get("metadata", {})
    if sel.namespace and sel.namespace != md.get("namespace", ""):
        return False
    if sel.name and sel.name != md.get("name", ""):
        return False
    if sel.label_selector is not None and not sel.label_selector.matches(
        md.get("labels", {}) or {}
    ):
        return False
    return True


def _split_image(image: str):
    """registry/repository:tag -> (registry, repository, tag)."""
    registry, rest = "", image
    if "/" in image:
        head, tail = image.split("/", 1)
        if "." in head or ":" in head or head == "localhost":
            registry, rest = head, tail
    tag = ""
    if ":" in rest:
        rest, tag = rest.rsplit(":", 1)
    return registry, rest, tag


def _join_image(registry: str, repository: str, tag: str) -> str:
    out = f"{registry}/{repository}" if registry else repository
    if tag:
        out = f"{out}:{tag}"
    return out


def _apply_image(ov: ImageOverrider, manifest: Dict[str, Any]) -> None:
    containers = deep_get(manifest, "spec.template.spec.containers") or deep_get(
        manifest, "spec.containers"
    ) or []
    for c in containers:
        image = c.get("image", "")
        if not image:
            continue
        registry, repo, tag = _split_image(image)
        part = {"Registry": registry, "Repository": repo, "Tag": tag}[ov.component]
        if ov.operator == "remove":
            part = ""
        elif ov.operator in ("add", "replace"):
            part = (part + ov.value) if ov.operator == "add" else ov.value
        if ov.component == "Registry":
            registry = part
        elif ov.component == "Repository":
            repo = part
        else:
            tag = part
        c["image"] = _join_image(registry, repo, tag)


def _apply_cmdargs(ov: CommandArgsOverrider, manifest: Dict[str, Any], fld: str) -> None:
    containers = deep_get(manifest, "spec.template.spec.containers") or deep_get(
        manifest, "spec.containers"
    ) or []
    for c in containers:
        if c.get("name") != ov.container_name:
            continue
        cur = list(c.get(fld, []) or [])
        if ov.operator == "add":
            cur.extend(ov.value)
        elif ov.operator == "remove":
            cur = [v for v in cur if v not in set(ov.value)]
        c[fld] = cur


def _apply_map(ov: LabelAnnotationOverrider, manifest: Dict[str, Any], fld: str) -> None:
    md = manifest.setdefault("metadata", {})
    cur = dict(md.get(fld, {}) or {})
    if ov.operator in ("add", "replace"):
        cur.update(ov.value)
    elif ov.operator == "remove":
        for k in ov.value:
            cur.pop(k, None)
    md[fld] = cur


def _apply_plaintext(ov: PlaintextOverrider, manifest: Dict[str, Any]) -> None:
    if ov.operator in ("add", "replace"):
        deep_set(manifest, ov.path, copy.deepcopy(ov.value))
    elif ov.operator == "remove":
        parts = ov.path.split(".")
        cur: Any = manifest
        for p in parts[:-1]:
            if not isinstance(cur, dict) or p not in cur:
                return
            cur = cur[p]
        if isinstance(cur, dict):
            cur.pop(parts[-1], None)


def apply_overriders(overriders: Overriders, manifest: Dict[str, Any]) -> None:
    for ov in overriders.image_overrider:
        _apply_image(ov, manifest)
    for ov in overriders.command_overrider:
        _apply_cmdargs(ov, manifest, "command")
    for ov in overriders.args_overrider:
        _apply_cmdargs(ov, manifest, "args")
    for ov in overriders.labels_overrider:
        _apply_map(ov, manifest, "labels")
    for ov in overriders.annotations_overrider:
        _apply_map(ov, manifest, "annotations")
    for ov in overriders.plaintext:
        _apply_plaintext(ov, manifest)


class OverrideManager:
    """Applies matching override policies to a manifest for one cluster."""

    def __init__(self, store: ObjectStore) -> None:
        self.store = store

    def apply(
        self, manifest: Dict[str, Any], cluster: Optional[Cluster]
    ) -> Dict[str, Any]:
        out = copy.deepcopy(manifest)
        namespace = deep_get(manifest, "metadata.namespace", "")
        cops: List[ClusterOverridePolicy] = sorted(
            self.store.list(ClusterOverridePolicy.KIND), key=lambda p: p.name
        )
        ops: List[OverridePolicy] = sorted(
            (p for p in self.store.list(OverridePolicy.KIND)
             if p.metadata.namespace == namespace),
            key=lambda p: p.name,
        )
        for policy in list(cops) + list(ops):
            if not any(selector_matches(s, out) for s in policy.spec.resource_selectors):
                continue
            for rule in policy.spec.override_rules:
                if rule.target_cluster is not None and (
                    cluster is None or not rule.target_cluster.matches(cluster)
                ):
                    # no Cluster object (deleted / not yet registered) means
                    # the affinity cannot match -- reference only applies a
                    # rule when the target affinity affirmatively matches
                    continue
                apply_overriders(rule.overriders, out)
        return out
