"""Scheduler metrics (pkg/scheduler/metrics/metrics.go:60-142 equivalents).

Same metric names and label shapes as the reference so dashboards/alerts
port over; per-step latency covers the batched pipeline's real stages
(Encode / Solve / Decode on the device path, Serial on the host path).
"""

from __future__ import annotations

from karmada_tpu.utils.metrics import REGISTRY, exponential_buckets

RESULT_SCHEDULED = "scheduled"
RESULT_ERROR = "error"
RESULT_UNSCHEDULABLE = "unschedulable"
SCHEDULE_TYPE_RECONCILE = "reconcile"

STEP_ENCODE = "Encode"
STEP_H2D = "H2D"      # host->device transfer + async launch (dispatch)
STEP_SOLVE = "Solve"  # device execution wait
STEP_D2H = "D2H"      # device->host result copy (+ rare nnz escalation)
STEP_DECODE = "Decode"
STEP_SERIAL = "Serial"

SCHEDULE_ATTEMPTS = REGISTRY.counter(
    "karmada_scheduler_schedule_attempts_total",
    "Number of attempts to schedule a ResourceBinding",
    ("result", "schedule_type"),
)

E2E_LATENCY = REGISTRY.histogram(
    "karmada_scheduler_e2e_scheduling_duration_seconds",
    "E2e scheduling latency in seconds",
    ("result", "schedule_type"),
    buckets=exponential_buckets(0.001, 2, 15),
)

STEP_LATENCY = REGISTRY.histogram(
    "karmada_scheduler_scheduling_algorithm_duration_seconds",
    "Scheduling algorithm latency in seconds by pipeline step",
    ("schedule_step",),
    buckets=exponential_buckets(0.001, 2, 15),
)

BACKEND_DEGRADED = REGISTRY.counter(
    "karmada_scheduler_backend_degraded_total",
    "Times the device backend was abandoned mid-serve (hung cycle) and "
    "the scheduler degraded to a host backend",
    ("to",),
)

BACKEND_REARMED = REGISTRY.counter(
    "karmada_scheduler_backend_rearmed_total",
    "Times a degraded scheduler re-armed the device backend after its "
    "cooldown re-probe (device_recover_cycles) — degrade is no longer "
    "one-way for transient faults",
    ("backend",),
)

# cycle fault containment: a schedule_batch that RAISES must not lose its
# popped bindings — they route to the backoff queue and the fault is
# counted here by exception class (chaos device faults land here too)
CYCLE_FAULTS = REGISTRY.counter(
    "karmada_scheduler_cycle_faults_total",
    "Scheduling cycles whose batch solve raised; the popped bindings "
    "were re-queued to backoff instead of being lost, by exception class",
    ("kind",),
)

QUEUE_INCOMING = REGISTRY.counter(
    "karmada_scheduler_queue_incoming_bindings_total",
    "Bindings added to scheduling queues by event type",
    ("event",),
)

QUEUE_DEPTH = REGISTRY.gauge(
    "karmada_scheduler_queue_depth",
    "Current scheduling queue depths",
    ("queue",),
)

# queue dwell (sustained-traffic serve harness): how long a binding waited
# before pop_ready drained it, bucketed by the queue it came from —
# "active" is a fresh external push, "backoff"/"unschedulable" entries
# include their parked wait.  The loadgen soak report derives its dwell
# quantiles from the same clock (scheduler/queue.py pop_ready).
QUEUE_DWELL = REGISTRY.histogram(
    "karmada_scheduler_queue_dwell_seconds",
    "Seconds a binding waited in the scheduling queue before being "
    "drained into a cycle, by queue of origin",
    ("queue",),
    buckets=exponential_buckets(0.001, 2, 18),
)

QUEUE_OLDEST_AGE = REGISTRY.gauge(
    "karmada_scheduler_queue_oldest_age_seconds",
    "Age of the oldest resident entry per scheduling queue (starvation "
    "early warning; refreshed each cycle and periodic flush)",
    ("queue",),
)

# bounded-queue admission gate (scheduler/queue.py push): every Push is
# exactly one of admitted/shed, so admitted + shed == total pushes;
# displaced counts residents evicted to make room for a higher-priority
# newcomer (each displacement also admits that newcomer)
ADMISSION = REGISTRY.counter(
    "karmada_scheduler_admission_total",
    "Scheduling-queue admission decisions under the bounded-resident "
    "gate, by decision (admitted/shed/displaced)",
    ("decision",),
)

# priority pushes (Scheduler.promote): the rebalance plane re-placing a
# drained binding and the FederatedHPA fast path pushing a refreshed
# binding straight into the queue, bypassing no gate but jumping the
# detector round-trip — autoscale/rebalance -> re-place is one cycle
PRIORITY_PUSHES = REGISTRY.counter(
    "karmada_scheduler_priority_pushes_total",
    "Bindings pushed straight into the active queue by a control-loop "
    "fast path, by origin (rebalance / hpa)",
    ("origin",),
)

OVERLOAD_MODE = REGISTRY.gauge(
    "karmada_scheduler_overload_mode",
    "1 while the scheduler is in overload degradation (measured queue "
    "dwell exceeded the batch deadline): explain sampling suppressed, "
    "batch-formation deadline widened",
)

# unschedulable-reason accounting (explain plane, obs/decisions taxonomy):
# every binding routed to the unschedulable queue counts under its
# dominant rejection reason — kube-scheduler's "0/5 clusters available"
# breakdown as a time series
UNSCHEDULABLE = REGISTRY.counter(
    "karmada_schedule_unschedulable_total",
    "Bindings routed to the unschedulable queue, by dominant reason",
    ("reason",),
)

# pipelined chunk executor spans (scheduler/pipeline.py): "own" is the
# chunk's own work (encode span + finalize/decode span), "wall" its
# submit-to-result time — under pipelining wall also contains the
# interleaved work of neighboring chunks, so own ~= wall means the
# pipeline degenerated to serial while wall >> own means deep overlap
PIPELINE_CHUNK_SPAN = "own"
PIPELINE_CHUNK_WALL = "wall"

PIPELINE_CHUNK_LATENCY = REGISTRY.histogram(
    "karmada_scheduler_pipeline_chunk_duration_seconds",
    "Per-chunk latency of the pipelined executor by span kind",
    ("span",),
    buckets=exponential_buckets(0.001, 2, 15),
)

PIPELINE_CHUNKS = REGISTRY.counter(
    "karmada_scheduler_pipeline_chunks_total",
    "Chunks finalized by the pipelined executor",
    ("carry",),
)

BATCH_SIZE = REGISTRY.histogram(
    "karmada_scheduler_batch_size",
    "Bindings drained into one batched solver cycle",
    (),
    buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192],
)
