"""Dirty-set incremental solving: the watch-driven steady state.

The reference control plane never rescans the world — its reconcile
loop touches exactly what the watch stream dirtied.  The batched
solver's full-cycle equivalent (re-encode + re-solve every binding,
every cycle) is what makes a million-binding steady state expensive:
at 0.1% churn, 99.9% of that work reproduces last cycle's answer
bit-for-bit.  This module is the solver-side reconcile loop:

  1. ``ops/dirty.dirty_codes`` classifies every slot-store row
     clean/dirty in one jitted pass (rv churn from the coalesced watch
     deltas + our own write-backs, feasibility-flip lanes from the
     resident plane, capacity-sensitive rows, non-device routes).
  2. Dirty rows gather from the resident slot store into compact
     sub-batches — grouped by their ORIGINAL chunk so each group is one
     single-chunk ``run_pipeline`` call, chained through a carried
     consumed-capacity ledger.
  3. Everything else keeps last cycle's placement untouched.

Sequential equivalence (the bit-exact contract, waves=1 only)
-------------------------------------------------------------
The control is ``run_pipeline(all items, chunk=K, waves=1, carry=True,
carry_state=ledger)``: a row in chunk c prices against the ledger plus
the consumption of chunks < c, and never sees same-chunk rows.  The
incremental cycle reproduces that visibility exactly:

* CLEAN rows reproduce their previous placement and consume zero —
  the solver's stickiness contract (steady rows take rep = prev and
  charge nothing; a clean Static/Duplicated row's eligible set did not
  change, so its re-solve would be its prev).  Skipping them removes
  no consumption any dirty row would have seen.  This leans on the
  WRITE-BACK PROTOCOL: ``write_back()`` must run between cycles so a
  row's stored prev advances to its last result (the write bumps the
  rv, the row re-solves once, reproduces, and goes quiet).  A caller
  that solves without writing back leaves moved rows re-charging their
  prev-delta in every dense control pass while the incremental leg
  skips them — the audit catches exactly this drift and recovers.
* Dirty rows grouped by original chunk (pos // chunk) solve as ONE
  chunk each, seeded with ledger + consumption of earlier groups —
  exactly the chunks-before-this-one environment of the control.
* Consecutive chunk-groups COALESCE into one dispatch only when
  provably order-free: the incoming group's capacity-SENSITIVE rows'
  placement masks must be disjoint from the union of the already-
  grouped CONSUMER rows' masks (ops/dirty grades both bits).  Rows
  whose result cannot observe the skipped consumption are safe to
  solve a chunk early.

The carried ledger
------------------
``tensors.CarryState`` keyed by resource name / scoreclass key in the
full cluster vocabulary.  Invariants:

* ledger_0 = empty; every cycle's rows (control and incremental alike)
  price against the PRE-cycle ledger.
* ledger_{t+1} = ledger_t, retired on the cycle's capacity-updated
  lanes (``state.last_cap_lanes`` — a cluster status write means the
  reported availability now embeds previously-charged consumption),
  plus this cycle's own consumption (the final group's carry-out).
* A structural plane rebuild resets the ledger (the lane/resource
  vocabulary it indexes is gone) and forces a full solve.

Audit cadence
-------------
Every ``audit_every``-th cycle (knob; 0 disables) the full dense solve
runs as a bit-exact control against the SAME pre-cycle ledger and the
merged incremental results are compared row-by-row (and the ledgers
store-by-store).  A mismatch is loud: metric, lifecycle-ledger event,
and the full solve's results + ledger are adopted wholesale — the
incremental plane recovers by construction, never schedules from a
diverged state twice.

Driven single-threaded from one scheduler/bench cycle loop, like the
ResidentState it wraps.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from karmada_tpu.obs import events as ev
from karmada_tpu.obs import incidents as obs_incidents
from karmada_tpu.ops import dirty as dirty_mod
from karmada_tpu.ops import tensors as T
from karmada_tpu.scheduler import pipeline
from karmada_tpu.utils.locks import OwnerThread
from karmada_tpu.utils.metrics import REGISTRY

INC_CYCLES = REGISTRY.counter(
    "karmada_incremental_cycles_total",
    "Incremental-plane scheduling cycles by mode (incremental: dirty "
    "sub-batches only; full: dense solve forced by adopt/rebuild/"
    "roster-change/audit-mismatch)",
    ("mode",),
)
INC_AUDITS = REGISTRY.counter(
    "karmada_incremental_audits_total",
    "Bit-exact parity audits of the incremental solve against the full "
    "dense control (outcome=ok|mismatch; mismatch also forces adoption "
    "of the control's results and ledger)",
    ("outcome",),
)

#: conservative dirty grade for rows with no slot-store row yet
#: (appended bindings, affinity-failover rows that bypass the cache)
_ALL_BITS = dirty_mod.DIRTY | dirty_mod.SENSITIVE | dirty_mod.CONSUMER

#: slot-store fields the dirty kernel gathers row-wise — the device
#: mirrors are used only when they cover all of these
_KERNEL_ROW_FIELDS = ("placement_id", "replicas", "fresh", "non_workload",
                      "route", "prev_idx", "prev_val", "evict_idx")


def _norm(res) -> tuple:
    """Order-free comparable form of one scheduling outcome."""
    if isinstance(res, Exception):
        return ("exc", type(res).__name__)
    return tuple(sorted((t.name, int(t.replicas)) for t in res))


def _ledger_equal(a: T.CarryState, b: T.CarryState) -> bool:
    """Store equality treating missing keys as zeros (a group's sub-
    vocabulary may simply never have priced a resource)."""
    def eq(da, db):
        for k in set(da) | set(db):
            x, y = da.get(k), db.get(k)
            if x is None:
                x = np.zeros_like(y)
            if y is None:
                y = np.zeros_like(x)
            if x.shape != y.shape or not np.array_equal(x, y):
                return False
        return True

    pa = a.pods if a.pods is not None else None
    pb = b.pods if b.pods is not None else None
    if (pa is None) != (pb is None):
        pa = np.zeros(0, np.int64) if pa is None else pa
        pb = np.zeros(pa.shape, np.int64) if pb is None else pb
    return (eq(a.milli, b.milli) and eq(a.sets, b.sets)
            and (pa is None or np.array_equal(pa, pb)))


@dataclass
class CycleReport:
    """One incremental cycle's outcome (the bench payload's raw rows)."""

    mode: str = "incremental"        # or "full"
    reason: str = ""                 # full-solve trigger ("" incremental)
    total: int = 0                   # roster size
    dirty: int = 0                   # rows re-solved this cycle
    chunk_groups: int = 0            # original-chunk groups before coalesce
    groups: List[int] = field(default_factory=list)  # dispatch sizes
    host_rows: int = 0               # rows the device tiers stopped owning
    audited: bool = False
    audit_outcome: Optional[str] = None   # "ok" | "mismatch"
    seconds: float = 0.0


class IncrementalSolver:
    """Steady-state scheduling driver over a ResidentState plane.

    ``adopt()`` once (full solve, roster + ledger established), then
    ``cycle()`` per scheduling round with the window's coalesced
    deltas; ``write_back()`` patches changed placements into the
    binding objects (rv bump ⇒ next cycle re-solves exactly those rows
    once more, reproduces them, and goes quiet — self-churn
    terminates).

    The roster is append-only between full solves: the bindings
    sequence must keep its order, with new bindings appended (they are
    force-dirtied).  Any shrink/reorder falls back to a full solve —
    loud, never wrong.
    """

    def __init__(self, state, estimator, *, chunk: int = 4096,
                 waves: int = 1, audit_every: int = 16,
                 shortlist=None, diagnose: bool = False) -> None:
        assert waves == 1, \
            "incremental solving is bit-exact only at waves=1 (a chunk's " \
            "rows must never see same-chunk consumption)"
        self.state = state
        self.estimator = estimator
        self.chunk = int(chunk)
        self.audit_every = max(0, int(audit_every))
        self.shortlist = shortlist
        self.diagnose = bool(diagnose)
        # lane budget for taint-coalescing when the shortlist is armed:
        # merging chunk-groups from disjoint placement scopes is order-
        # free, but an unbounded merge unions their candidate lanes —
        # random churn over a region-sharded fleet would coalesce into
        # one near-dense-width dispatch (union_wide fallback + a dense
        # solve, the exact work this plane exists to avoid).  Bounding
        # the merged groups' mask-union keeps every dispatch inside the
        # shortlist's narrow sub-vocabulary; more (sequential) groups
        # never break exactness, they only add barriers.
        self._lane_budget = (8 * shortlist.k) if shortlist else None

        # the whole carried-ledger/roster/audit block below is
        # single-threaded BY CONTRACT: one scheduler/bench cycle loop
        # drives adopt()/cycle()/write_back() in sequence — there is no
        # lock, the armed runtime detector enforces the contract instead
        # (utils/locks.OwnerThread: first caller owns the plane, any
        # other thread raises InvariantViolation).
        self._owner = OwnerThread("scheduler.incremental")
        self.ledger: T.CarryState = T.CarryState()  # owner-thread: _owner
        self.keys: List[str] = []  # owner-thread: _owner
        self.key_pos: Dict[str, int] = {}  # owner-thread: _owner
        self.bindings: List = []  # owner-thread: _owner
        self.results: Dict[int, object] = {}  # owner-thread: _owner
        # pos -> slot-store slot (-1: no cached row); refreshed for rows
        # that re-encode, so the next dirty pass reads live slots
        self._slots: np.ndarray = np.zeros(0, np.int64)
        # keys our own write_back() touched since the last cycle — the
        # watch stream the bench/tests drive may not carry them
        self._pending: Set[str] = set()  # owner-thread: _owner
        # pos -> last normalized outcome write_back applied (changed-only
        # patching; repeated identical results never bump an rv)
        self._applied: Dict[int, tuple] = {}  # owner-thread: _owner
        # positions whose result changed since the last write_back — at a
        # million-row roster write_back must not re-normalize the whole
        # results map to find the ~0.1% that moved
        self._since_wb: Set[int] = set()  # owner-thread: _owner
        # the caller's roster object, for the identity fast path in
        # cycle(): same list + same length skips the O(n) key rebuild.
        # Assumes the roster is append-only (replacing an element in
        # place must come as a new list — a store snapshot does).
        self._roster_src: Optional[object] = None
        self.cycles = 0
        self._plm_cache: Optional[Tuple[int, np.ndarray]] = None
        self._pid_cache: Optional[Tuple[int, np.ndarray]] = None

    # -- roster ---------------------------------------------------------------
    def _token(self, rb, key: str):
        from karmada_tpu.resident import RowToken

        terms = (rb.spec.placement.cluster_affinities
                 if rb.spec.placement else [])
        # affinity-failover rows encode against synthesized status and
        # bypass the row cache (see scheduler/service) — no stable token
        return None if terms else RowToken(key, rb.metadata.resource_version)

    def _set_roster(self, bindings: Sequence, keys: List[str]) -> List[int]:
        """Adopt the cycle's roster; returns appended positions (the
        caller has already verified prefix stability)."""
        n0 = len(self.keys)
        appended = list(range(n0, len(keys)))
        for i in appended:
            self.key_pos[keys[i]] = i
        if appended:
            self._slots = np.concatenate(
                [self._slots, np.full(len(appended), -1, np.int64)])
        self.keys = keys
        self.bindings = list(bindings)
        self._roster_src = bindings
        return appended

    def _rebuild_roster(self, bindings: Sequence, keys: List[str]) -> None:
        self.keys = keys
        self.key_pos = {k: i for i, k in enumerate(keys)}
        self.bindings = list(bindings)
        self._slots = np.full(len(keys), -1, np.int64)
        self.results = {}
        self._applied = {}
        self._since_wb = set()
        self._roster_src = bindings

    def _refresh_slots(self, positions) -> None:
        rows = self.state.rows
        sl = self._slots
        keys = self.keys
        for p in positions:
            row = rows.get(keys[p])
            sl[p] = row.slot if row is not None else -1

    # -- plane views (cached on the frozen masters' identity) -----------------
    def _plm(self) -> np.ndarray:
        m = self.state.plane.pl_mask
        if self._plm_cache is None or self._plm_cache[0] != id(m):
            self._plm_cache = (id(m), np.asarray(m).astype(bool))
        return self._plm_cache[1]

    def _pid(self) -> np.ndarray:
        a = self.state.plane.placement_id
        if self._pid_cache is None or self._pid_cache[0] != id(a):
            self._pid_cache = (id(a), np.asarray(a))
        return self._pid_cache[1]

    # -- the two solve legs ---------------------------------------------------
    def _run_all(self, seed: T.CarryState) -> "pipeline.PipelineResult":
        """Full dense control: every roster row, seeded from `seed`."""
        state = self.state
        toks = [self._token(rb, k) for rb, k in zip(self.bindings, self.keys)]
        items = [(rb.spec, rb.status) for rb in self.bindings]

        def encode(part, offset, armed):
            return state.encode_cycle(
                part, toks[offset:offset + len(part)], explain=armed)

        res = pipeline.run_pipeline(
            items, state.cindex, self.estimator,
            chunk=self.chunk, waves=1, cache=state.enc_cache,
            carry=True, collect=True, diagnose=self.diagnose,
            encode=encode, keys=self.keys, shortlist=self.shortlist,
            carry_state=seed, collect_carry=True)
        if res.cancelled or res.carry is None:
            raise RuntimeError("incremental full solve did not complete")
        return res

    def _full(self, reason: str, rep: CycleReport) -> CycleReport:
        res = self._run_all(self.ledger)
        self.results = dict(res.results)
        self._since_wb = set(self.results)
        self.ledger = res.carry
        self._refresh_slots(range(len(self.keys)))
        INC_CYCLES.inc(mode="full")
        rep.mode = "full"
        rep.reason = reason
        rep.dirty = len(self.keys)
        rep.host_rows = len(self.keys) - len(self.results)
        return rep

    def _solve_group(self, grp: List[int],
                     seed: T.CarryState) -> "pipeline.PipelineResult":
        state = self.state
        g_bind = [self.bindings[p] for p in grp]
        g_keys = [self.keys[p] for p in grp]
        g_items = [(b.spec, b.status) for b in g_bind]
        g_toks = [self._token(b, k) for b, k in zip(g_bind, g_keys)]

        def encode(part, offset, armed, _t=g_toks):
            return state.encode_cycle(
                part, _t[offset:offset + len(part)], explain=armed)

        res = pipeline.run_pipeline(
            g_items, state.cindex, self.estimator,
            chunk=self.chunk, waves=1, cache=state.enc_cache,
            carry=True, collect=True, diagnose=self.diagnose,
            encode=encode, keys=g_keys, shortlist=self.shortlist,
            carry_state=seed, collect_carry=True)
        if res.cancelled or res.carry is None:
            raise RuntimeError("incremental group solve did not complete")
        return res

    # -- lifecycle ------------------------------------------------------------
    def adopt(self, clusters: Sequence, bindings: Sequence) -> CycleReport:
        """First cycle: full solve, roster + ledger + slot store built."""
        self._owner.check("adopt()")
        t0 = time.perf_counter()
        self.cycles += 1
        self._rebuild_roster(
            bindings, [f"{rb.namespace}/{rb.name}" for rb in bindings])
        self.state.begin_cycle(clusters, None)
        self.ledger = T.CarryState()
        rep = self._full("adopt", CycleReport(total=len(self.keys)))
        rep.seconds = time.perf_counter() - t0
        return rep

    def cycle(self, clusters: Sequence, bindings: Sequence,
              deltas=None, force_audit: Optional[bool] = None) -> CycleReport:
        """One watch-driven cycle: apply `deltas` to the plane, re-solve
        the dirty set, audit on cadence.  `bindings` is the full roster
        (append-only vs the previous cycle, or a full solve triggers)."""
        self._owner.check("cycle()")
        t0 = time.perf_counter()
        self.cycles += 1
        state = self.state
        gen0 = state.generation
        state.begin_cycle(clusters, deltas)
        rep = CycleReport(total=len(bindings))

        n0 = len(self.keys)
        if bindings is self._roster_src and len(bindings) == n0:
            keys = self.keys  # identity fast path: no O(n) key rebuild
        else:
            keys = [f"{rb.namespace}/{rb.name}" for rb in bindings]
        full_reason = None
        if state.generation != gen0 or state.plane is None:
            # structural rebuild: the lane/resource vocabulary the ledger
            # indexes is gone — reset it, price from reported capacity
            full_reason = "plane-rebuild"
            self.ledger = T.CarryState()
        elif len(keys) < n0 or keys[:n0] != self.keys:
            full_reason = "roster-change"
        if full_reason:
            self._rebuild_roster(bindings, keys)
            self.ledger.retire_lanes(state.last_cap_lanes)
            ev.emit(ev.SCHEDULER_REF, ev.TYPE_NORMAL,
                    ev.REASON_INCREMENTAL_FULL_SOLVE,
                    f"incremental plane forced a full dense solve: "
                    f"{full_reason}", origin="incremental")
            rep = self._full(full_reason, rep)
            self._pending.clear()
            rep.seconds = time.perf_counter() - t0
            self._flight(rep)
            return rep

        appended = self._set_roster(bindings, keys)
        # capacity catch-up: status writes mean the snapshot's reported
        # availability now embeds previously-charged consumption
        self.ledger.retire_lanes(state.last_cap_lanes)

        # rv churn: the coalesced watch window + our own write-backs
        touched = set(self._pending)
        self._pending.clear()
        if deltas is not None:
            touched.update(f"{ns}/{nm}"
                           for ns, nm in deltas.bindings_touched)
        rv_slots: List[int] = []
        forced_pos: List[int] = list(appended)
        for key in touched:
            p = self.key_pos.get(key)
            if p is None:
                continue
            s = int(self._slots[p])
            if s >= 0:
                rv_slots.append(s)
            else:
                forced_pos.append(p)

        mirrors = None
        dr = getattr(state, "device_rows", None)
        if (dr is not None and not dr.broken
                and all(f in dr.mirrors for f in _KERNEL_ROW_FIELDS)):
            mirrors = dr.mirrors
        codes = dirty_mod.dirty_codes(
            state, np.asarray(rv_slots, np.int64), mirrors=mirrors)

        n = len(keys)
        pos_codes = np.zeros(n, np.uint8)
        has_slot = self._slots >= 0
        pos_codes[has_slot] = codes[self._slots[has_slot]]
        # no cached row = no slot to read: conservatively dirty
        pos_codes[~has_slot] = _ALL_BITS
        if forced_pos:
            pos_codes[forced_pos] = _ALL_BITS
        dirty_pos = np.flatnonzero(pos_codes & dirty_mod.DIRTY)
        rep.dirty = int(dirty_pos.size)
        dirty_mod.DIRTY_ROWS.inc(rep.dirty)
        dirty_mod.DIRTY_FRACTION.set(rep.dirty / max(n, 1))
        INC_CYCLES.inc(mode="incremental")

        groups = self._group(dirty_pos, pos_codes)
        rep.chunk_groups = len(np.unique(dirty_pos // self.chunk))
        rep.groups = [len(g) for g in groups]

        pre = self.ledger.copy()  # the audit's seed: PRE-cycle ledger
        seed = self.ledger
        new_results: Dict[int, object] = {}
        for grp in groups:
            res = self._solve_group(grp, seed)
            seed = res.carry
            for j, r in res.results.items():
                new_results[grp[j]] = r
        self.ledger = seed
        for p in dirty_pos.tolist():
            if p not in new_results:
                # the row left the device tiers (route change): the
                # caller's serial fallback owns it now
                if self.results.pop(p, None) is not None:
                    rep.host_rows += 1
        self.results.update(new_results)
        self._since_wb.update(new_results)
        self._refresh_slots(dirty_pos.tolist())

        rep.audited = (force_audit if force_audit is not None
                       else (self.audit_every > 0
                             and self.cycles % self.audit_every == 0))
        if rep.audited:
            rep.audit_outcome = self._audit(pre)
        rep.seconds = time.perf_counter() - t0
        self._flight(rep)
        return rep

    def _flight(self, rep: CycleReport) -> None:
        """One kind="incremental" flight record per cycle: the dirty-set
        and taint-group stats plus the audit verdict digest the incident
        bundles snapshot.  Disarmed cost is one list read."""
        if not obs_incidents.flight_armed():
            return
        obs_incidents.record(
            "incremental", t=round(time.time(), 6), cycle=self.cycles,
            mode=rep.mode, reason=rep.reason, total=rep.total,
            dirty=rep.dirty, chunk_groups=rep.chunk_groups,
            groups=list(rep.groups), host_rows=rep.host_rows,
            audited=rep.audited, audit_outcome=rep.audit_outcome,
            seconds=round(rep.seconds, 6))

    # -- grouping -------------------------------------------------------------
    def _group(self, dirty_pos: np.ndarray,
               pos_codes: np.ndarray) -> List[List[int]]:
        """Original-chunk groups with the taint-coalescing rule (see
        module docstring): merge chunk-group B into the running dispatch
        only when B's sensitive rows' placement masks are disjoint from
        the consumer-mask union accumulated so far (and the merged size
        stays within one chunk).  With the shortlist armed a third gate
        applies: the merged dispatch's candidate-lane union must stay
        within ``_lane_budget`` — splitting into more sequential groups
        is always exact (pieces stay chunk-atomic; extra ordering only
        affects rows that share lanes, and those never merged anyway),
        while over-merging disjoint regions widens the sub-vocabulary
        until the shortlist falls back to a dense solve."""
        if dirty_pos.size == 0:
            return []
        plm = self._plm()
        pid = self._pid()
        C = plm.shape[1]
        # measured on the 1M x 10k megafleet: tier-2 sub-solve cost grows
        # superlinearly with the dispatch shape ([512, 2048] costs ~4x a
        # [128, 512] solve), so many narrow shape-stable dispatches beat
        # few wide ones — 8*k keeps each group at one pow2 width
        budget = self._lane_budget if self._lane_budget else C

        def mask_union(rows: np.ndarray, bit: int) -> np.ndarray:
            sel = rows[(pos_codes[rows] & bit) != 0]
            if sel.size == 0:
                return np.zeros(C, bool)
            slots = self._slots[sel]
            if np.any(slots < 0):
                return np.ones(C, bool)  # unknown row: taints everything
            return plm[pid[slots]].any(axis=0)

        chunk_ids = dirty_pos // self.chunk
        bounds = np.flatnonzero(np.diff(chunk_ids)) + 1
        pieces = np.split(dirty_pos, bounds)

        groups: List[List[int]] = []
        cur: List[int] = []
        cur_cons = np.zeros(C, bool)
        cur_all = np.zeros(C, bool)
        for g in pieces:
            inc_sens = mask_union(g, dirty_mod.SENSITIVE)
            g_all = mask_union(g, dirty_mod.DIRTY)  # every row is DIRTY
            if (cur and len(cur) + len(g) <= self.chunk
                    and not np.any(cur_cons & inc_sens)
                    and int(np.count_nonzero(cur_all | g_all)) <= budget):
                cur.extend(g.tolist())
            else:
                if cur:
                    groups.append(cur)
                cur = g.tolist()
                cur_cons = np.zeros(C, bool)
                cur_all = np.zeros(C, bool)
            cur_cons |= mask_union(g, dirty_mod.CONSUMER)
            cur_all |= g_all
        if cur:
            groups.append(cur)
        return groups

    # -- audit ----------------------------------------------------------------
    def _audit(self, pre: T.CarryState) -> str:
        """Full dense control against the same pre-cycle ledger; adopt
        its results + ledger on any divergence."""
        res = self._run_all(pre)
        bad: List[int] = []
        for p in set(res.results) | set(self.results):
            a = self.results.get(p)
            b = res.results.get(p)
            if (a is None) != (b is None) or \
                    (a is not None and _norm(a) != _norm(b)):
                bad.append(p)
        ledger_ok = _ledger_equal(self.ledger, res.carry)
        if not bad and ledger_ok:
            INC_AUDITS.inc(outcome="ok")
            return "ok"
        INC_AUDITS.inc(outcome="mismatch")
        what = (f"{len(bad)} row(s) diverged"
                + ("" if ledger_ok else " and the capacity ledger drifted"))
        names = ", ".join(self.keys[p] for p in sorted(bad)[:5])
        ev.emit(ev.SCHEDULER_REF, ev.TYPE_WARNING,
                ev.REASON_INCREMENTAL_AUDIT_MISMATCH,
                f"incremental solve diverged from the dense control: {what}"
                + (f" ({names})" if names else "")
                + "; adopting the control's results and ledger",
                origin="incremental")
        # incident bundle with the divergence diff (built BEFORE the
        # adoption below rewrites self.results): row-level incremental vs
        # control answers, bounded
        diff = [{"key": self.keys[p],
                 "incremental": (None if self.results.get(p) is None
                                 else _norm(self.results[p])),
                 "control": (None if res.results.get(p) is None
                             else _norm(res.results[p]))}
                for p in sorted(bad)[:10]]
        obs_incidents.trigger(
            obs_incidents.TRIGGER_AUDIT_DIVERGENCE,
            f"incremental audit divergence adopted: {what}",
            refs=[self.keys[p] for p in sorted(bad)[:16]],
            detail={"rows": diff, "n_bad": len(bad),
                    "ledger_ok": ledger_ok, "cycle": self.cycles})
        self.results = dict(res.results)
        self._since_wb = set(self.results)
        self.ledger = res.carry
        self._refresh_slots(range(len(self.keys)))
        return "mismatch"

    # -- write-back -----------------------------------------------------------
    def write_back(self) -> int:
        """Patch changed placements into the roster's binding objects
        (spec.clusters + rv bump), changed-only: a result identical to
        the last applied one writes nothing, so re-solve -> identical
        answer -> no rv bump terminates the self-churn loop.  Returns
        the number of bindings written.  Visits only positions whose
        result changed since the last write_back (``_since_wb``) — the
        steady-state contract is O(dirty) here too, not O(roster)."""
        self._owner.check("write_back()")
        changed = 0
        for pos in self._since_wb:
            res = self.results.get(pos)
            if res is None:
                continue  # row left the device tiers since
            norm = _norm(res)
            if self._applied.get(pos) == norm:
                continue
            self._applied[pos] = norm
            if isinstance(res, Exception):
                continue  # no placement to record; outcome tracked only
            rb = self.bindings[pos]
            rb.spec.clusters = list(res)
            rb.metadata.resource_version += 1
            self._pending.add(self.keys[pos])
            changed += 1
        self._since_wb.clear()
        return changed
