"""Scheduler service: batching window over the TPU solver.

The reference scheduler pops ONE binding at a time (pkg/scheduler/
scheduler.go:335-340 worker/scheduleNext) and runs the generic pipeline per
binding.  This service keeps the same *decision* semantics
(doScheduleBinding :376 -- schedule when the spec generation moved, a
reschedule was triggered, or the binding is unscheduled; honor scheduling
suspension) but drains every pending binding per cycle into the pipelined
chunk executor (scheduler/pipeline.py over ops/solver.schedule_compact —
chunked async dispatch with encode/finalize overlap and chunk-to-chunk
consumed-capacity carry), falling back to the serial pipeline for bindings
the dense encoding routes to host (ops/tensors.route).

The ClusterAffinities failover loop (scheduleResourceBinding :599-662)
iterates ordered affinity terms; each round re-batches the still-failing
bindings under their next term, and the observed term is recorded in
status.schedulerObservedAffinityName exactly like the reference.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu import chaos as chaos_mod
from karmada_tpu.utils.locks import VetLock
from karmada_tpu import obs
from karmada_tpu.obs import decisions as obs_decisions
from karmada_tpu.obs import incidents as obs_incidents
from karmada_tpu.obs import timeseries as obs_timeseries
from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import Cluster
from karmada_tpu.models.meta import Condition, set_condition
from karmada_tpu.models.work import (
    COND_SCHEDULED,
    ResourceBinding,
    ResourceBindingSpec,
    ResourceBindingStatus,
    TargetCluster,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.webhook.admission import AdmissionDenied
from karmada_tpu.scheduler import metrics as sched_metrics
from karmada_tpu.scheduler.queue import QueuedBindingInfo, SchedulingQueue
from karmada_tpu.store.store import Event, ObjectStore
from karmada_tpu.store.worker import AsyncWorker, Runtime
from karmada_tpu.utils import events as ev

REASON_SUCCESS = "BindingScheduled"
REASON_NO_FIT = "NoClusterFit"
REASON_UNSCHEDULABLE = "Unschedulable"

_CYCLE = "__cycle__"

# priority of control-loop fast-path pushes (FederatedHPA scale events):
# high enough to jump the steady backlog so autoscale -> re-place is one
# cycle — the fast path exists to skip the detector round-trip, not the
# admission gate (promote() still runs the gate)
FAST_PATH_PRIORITY = 10

# cap on the per-binding samples a cycle span carries (loadgen SLO
# reporting): a 4096-binding cycle records every ~8th value instead of
# an unbounded list; the stride rides along so aggregators can weight
_SPAN_SAMPLE_CAP = 512


def _span_samples(values: List[float]) -> Tuple[List[float], int]:
    """Deterministic stride subsample of per-binding measurements for a
    cycle span record (bounded, reproducible — no RNG on the hot path)."""
    stride = max(1, -(-len(values) // _SPAN_SAMPLE_CAP))
    return [round(v, 6) for v in values[::stride]], stride


class Scheduler:
    """Watches bindings + clusters; schedules in batched cycles.

    Pending bindings wait in a three-queue SchedulingQueue (active/backoff/
    unschedulable, scheduler/queue.py); each cycle drains a priority-ordered
    batch from the active queue into one solver call, then routes failures
    back per scheduler.go:829-841 handleErr semantics."""

    def __init__(
        self,
        store: ObjectStore,
        runtime: Runtime,
        estimators: Optional[Sequence] = None,
        backend: str = "device",  # device | native | serial
        enable_empty_workload_propagation: bool = False,
        batch_window: int = 4096,
        queue: Optional[SchedulingQueue] = None,
        recorder: Optional[ev.EventRecorder] = None,
        waves: int = 8,
        # pipelined chunk executor (scheduler/pipeline.py): cycles larger
        # than this split into pipelined chunks with chunk-to-chunk
        # consumed-capacity carry; cycles at or under it keep the
        # single-dispatch path
        pipeline_chunk: int = 1024,
        # solver device mesh (ops/meshing): "BxC" / (B, C) shards every
        # compact dispatch over a (bindings, clusters) mesh — cluster
        # tensors model-parallel, binding rows data-parallel; "auto"
        # factors the live device count; None/"off"/1x1 (or a single
        # device) keeps the exact single-device dispatch.  Only consulted
        # by the device backend.
        mesh_shape=None,
        elector=None,  # utils.leaderelection.LeaderElector (None: always lead)
        # a device cycle exceeding this many seconds marks the backend dead
        # and degrades to the fastest working backend (the startup
        # probe cannot catch a tunnel that dies mid-serve, and a hung XLA
        # dispatch is uninterruptible in-process — the stuck cycle runs on
        # a discarded daemon thread).  None disables the guard (tests,
        # known-good hardware).
        device_cycle_timeout_s: Optional[float] = None,
        # recoverable degrade: after this many scheduling cycles on the
        # degraded backend the scheduler re-probes the device backend
        # (half-open: ONE cycle tries device; the guard degrades again on
        # failure, with the cooldown doubling per consecutive failed
        # re-arm so a permanently dead tunnel converges to rare probes).
        # None keeps the legacy one-way degrade.  Cycle-counted rather
        # than wall-timed so compressed-virtual-clock soaks exercise the
        # exact production path deterministically.
        device_recover_cycles: Optional[int] = None,
        # chaos plane (karmada_tpu/chaos, serve --chaos SPEC): arm the
        # process-wide fault-injection plane with this spec string at
        # construction.  None/"" leaves it disarmed (one list read per
        # seam traversal).
        chaos: Optional[str] = None,
        chaos_seed: int = 0,
        # explain plane (obs/decisions, serve --explain[=RATE]): sample
        # rate in (0, 1] of scheduling cycles that run the solver's
        # explain jit variant and record per-binding placement Decision
        # records; 0/None keeps the disarmed hot path byte-identical.
        explain: float = 0.0,
        # batch formation (sustained-traffic harness): with a deadline
        # set, a cycle is cut only when batch_window bindings are ready
        # OR the oldest ready binding has waited batch_deadline_s —
        # small trickles coalesce into fuller batches instead of paying
        # the per-cycle fixed cost per binding.  None (default) keeps
        # the legacy cut-immediately behavior.
        batch_deadline_s: Optional[float] = None,
        # bounded-resident admission gate (scheduler/queue.py): total
        # tracked bindings never exceed this; overflow sheds by priority
        # with karmada_scheduler_admission_total accounting.  Only
        # consulted when `queue` is not supplied.  None = unbounded.
        admission_limit: Optional[int] = None,
        # overload degradation: when the drained batch's p95 dwell
        # exceeds batch_deadline_s * overload_enter_factor the scheduler
        # enters overload mode (explain sampling suppressed, effective
        # deadline widened by overload_deadline_factor so cycles fill
        # toward batch_window); it exits when p95 dwell drops back under
        # the deadline.  Inert unless batch_deadline_s is set.
        overload_enter_factor: float = 2.0,
        overload_deadline_factor: float = 4.0,
        # resident-state plane (karmada_tpu/resident, serve --resident):
        # keep the cluster-side solver tensors (and their device mirrors)
        # resident BETWEEN cycles, advanced by coalesced watch-event
        # deltas, and gather cached per-binding encoded rows so a
        # steady-state cycle re-encodes only churned bindings.  Device
        # backend only — the native/serial backends never build
        # SolverBatches.  resident_audit_interval: every Nth cycle
        # re-encodes from scratch and compares bit-exact (mismatch =>
        # metric + forced rebuild); 0 disables the cadence.
        resident: bool = False,
        resident_audit_interval: int = 64,
        # fused whole-cycle-on-device steady state (ops/resident_gather,
        # serve --resident --resident-fused): the binding-axis slot
        # store mirrors on device and each chunk's rows GATHER there —
        # scatter watch deltas in, gather the pending batch, solve with
        # operands already placed, d2h only the compact COO.  Host
        # re-encode stays the behavior-defining parity control (explain
        # chunks, rebuild cycles, and mirror-sync failures fall back).
        # Requires resident=True; disarmed by default.
        resident_fused: bool = False,
        # hierarchical two-tier solve (ops/shortlist, serve --shortlist):
        # chunks whose dense B*C cell count reaches shortlist_min_cells
        # run the tier-1 candidate kernel and solve over the candidate-
        # union sub-vocabulary (B*k cells instead of B*C) — bit-exact
        # when every binding's eligible set fits k, loud dense fallback
        # otherwise.  None/0 keeps every chunk dense (the default);
        # shortlist_min_cells <= 0 arms every chunk (tests, megafleet).
        # Device backend only; the fused resident-gather path keeps the
        # dense dispatch (the slot store owns its binding rows).
        shortlist_k: Optional[int] = None,
        shortlist_min_cells: int = 1 << 21,
        # rebalance plane (karmada_tpu/rebalance, serve --rebalance):
        # interval in seconds of the periodic drain-and-re-place cycle on
        # the scheduler queue's clock — detect overcommit/spread
        # divergence, gracefully evict victims under the shared pacing
        # budget, and re-enter them through the queue with a
        # `rebalance` origin.  None/0 leaves the plane disarmed.
        rebalance: Optional[float] = None,
        rebalance_cfg=None,            # rebalance.RebalanceConfig override
        rebalance_budget=None,         # shared pacing.EvictionBudget
        # clock the rebalance plane paces on; None uses the scheduling
        # queue's clock (wall time in production serve).  ControlPlane
        # passes its injected clock so deterministic harnesses drive the
        # interval gate like every other controller's
        rebalance_clock=None,
    ) -> None:
        self.elector = elector
        if elector is not None:
            # takeover must rebuild the queue from the STORE: a standby that
            # joined late never saw the backlog's events
            prev_cb = elector.on_started_leading

            def rebuild() -> None:
                if prev_cb is not None:
                    prev_cb()
                # same discipline as the Cluster-event resync: resident keys
                # keep their queue/backoff state (a leadership flap must not
                # grant failing bindings an extra immediate attempt), and
                # already-converged bindings stay out
                with self._queue_lock:
                    for rb in self.store.list(ResourceBinding.KIND):
                        key = (rb.namespace, rb.name)
                        if self.queue.has(key):
                            continue
                        if not rb.spec.clusters or self._needs_schedule(rb):
                            self.queue.push(key, _priority_of(rb))
                self.worker.enqueue(_CYCLE)
            elector.on_started_leading = rebuild
        self.recorder = recorder if recorder is not None else ev.EventRecorder()
        self.store = store
        self.backend = backend
        self.device_cycle_timeout_s = device_cycle_timeout_s
        self.device_recover_cycles = device_recover_cycles
        if chaos:
            chaos_mod.configure(chaos, seed=chaos_seed)
        # recoverable-degrade state (owned by the one cycle worker): the
        # backend we degraded FROM (None = never degraded), cycles run
        # since the degrade, and consecutive failed re-arms (cooldown
        # escalation)
        self._degraded_from: Optional[str] = None
        self._cycles_since_degrade = 0
        self._degrade_streak = 0
        # incident-plane flight breadcrumbs (cycle-worker owned): the
        # last device pipeline's dispatch/d2h accounting and the
        # shortlist counter base the per-cycle deltas difference against
        self._last_pipeline: Optional[dict] = None
        self._flight_shortlist_base: Optional[dict] = None
        # capacity-contention waves per solver chunk (ops/solver.py): the
        # chunk is priced in `waves` sequential waves, each seeing the
        # snapshot minus what earlier waves consumed; waves == batch size
        # is exactly the reference's one-binding-at-a-time semantics
        self.waves = max(1, waves)
        self.pipeline_chunk = max(1, pipeline_chunk)
        self.mesh_shape = mesh_shape
        self.explain = min(float(explain or 0.0), 1.0)
        self._decisions = (obs_decisions.configure()
                          if self.explain > 0 else None)
        # deterministic per-scheduler sampling stream (tests, replayable)
        self._explain_rng = random.Random(0x5EED)
        # the recorder of the CURRENT cycle's explain sample (None on
        # unsampled cycles) — the decision<->event cross-link must only
        # bind outcomes to decisions THIS cycle produced, never to a
        # stale verdict from an earlier sampled cycle (owned by the one
        # cycle worker via schedule_batch)
        self._cycle_explain = None
        self.mesh_plan = None
        self._mesh_tried = False
        self.estimators = list(estimators) if estimators else [GeneralEstimator()]
        self._general = next(
            (e for e in self.estimators if isinstance(e, GeneralEstimator)),
            GeneralEstimator(),
        )
        self.enable_empty_workload_propagation = enable_empty_workload_propagation
        self.batch_window = batch_window
        self.batch_deadline_s = batch_deadline_s
        self.admission_limit = admission_limit
        self.overload_enter_factor = overload_enter_factor
        self.overload_deadline_factor = overload_deadline_factor
        # overload degradation state: flipped only by _cycle (the one
        # worker) from measured dwell; readers (explain sampling,
        # /debug/load) take the instantaneous value
        self._overload = False
        # cycles where batch formation said "cut" but the pop came back
        # empty — must stay 0 (the never-cut-an-empty-cycle invariant);
        # counted here because an empty cut leaves no span to count
        self._empty_cuts = 0
        # monotone id of the scheduling cycle in flight, stamped onto
        # every lifecycle-ledger event the cycle's outcomes emit so a
        # timeline entry names the exact batch that produced it (owned
        # by the one cycle worker; readers take the instantaneous value)
        self._cycle_id = 0
        # guarded-by: _queue_lock — keys of the batch the CURRENT cycle
        # is scheduling: their result-patch events re-push through
        # _on_event, and those echoes are gate-exempt (the slot they
        # reclaim is the one their own pop just freed; without the
        # exemption each scheduled batch would displace or starve
        # genuinely-waiting arrivals under an armed admission gate)
        self._inflight_keys: set = set()
        # the queue is touched from publisher threads (_on_event) and the
        # worker (_cycle); one lock guards every queue operation
        self._queue_lock = VetLock("scheduler.queue")
        # guarded-by: _queue_lock — the single pending deferred-cut wakeup
        # (threading.Timer): when batch formation defers an immature
        # trickle and no new push arrives, this re-drives the worker when
        # the oldest entry's dwell reaches the deadline, so the cut lands
        # on the deadline's schedule instead of the (possibly much
        # coarser) periodic tick's
        self._cut_timer: Optional[threading.Timer] = None
        # guarded-by: _queue_lock; mutators: push,pop_ready,flush_backoff,flush_unschedulable_leftover,move_all_to_active_or_backoff,push_unschedulable_if_not_present,push_backoff_if_not_present
        self.queue = (queue if queue is not None
                      else SchedulingQueue(max_resident=admission_limit))
        self._native_snap = None  # (clusters list, NativeSnapshot)
        self._resident = None
        self._delta_tracker = None
        # remembered so a recovered backend re-arms the SAME resident
        # configuration the operator chose (the degrade path detaches it)
        self._resident_cfg = (bool(resident and backend == "device"),
                              resident_audit_interval,
                              bool(resident_fused))
        self.resident_fused = bool(resident_fused and resident
                                   and backend == "device")
        # shortlist tier selection: built lazily (ops/shortlist imports
        # jax) the first device cycle that can use it.  Composes with the
        # fused resident path: shrink logic reads the host slot-store
        # masters through the batch's fused_src handle and the sub-batch
        # gathers straight into the union vocabulary on device
        # (ops/resident_gather.dispatch_sub_gather), so binding rows
        # still never re-upload.
        self.shortlist_k = (int(shortlist_k) if shortlist_k
                            and backend == "device" else None)
        self.shortlist_min_cells = int(shortlist_min_cells)
        self._shortlist_cfg = None
        if resident and backend == "device":
            self._arm_resident()
        if backend == "native":
            # warm the g++ build at startup so the first scheduling cycle
            # never blocks on a synchronous compile
            from karmada_tpu import native as native_mod

            native_mod.load()
        self.worker = runtime.register(AsyncWorker("scheduler", self._cycle))
        runtime.register_periodic(self._periodic_flush, name="scheduler")
        # rebalance plane (karmada_tpu/rebalance): a periodic hook on the
        # queue's clock, like the flushes — NOT subject to --controllers
        # (the plane belongs to the scheduler binary, not the controller
        # manager; the reference descheduler is its own deployment)
        self.rebalance_plane = None
        if rebalance:
            from karmada_tpu import rebalance as rebalance_mod
            from karmada_tpu.rebalance import RebalanceConfig, RebalancePlane

            cfg = (rebalance_cfg if rebalance_cfg is not None
                   else RebalanceConfig(interval_s=float(rebalance)))
            self.rebalance_plane = RebalancePlane(
                store, self, cfg=cfg, budget=rebalance_budget,
                clock=(rebalance_clock if rebalance_clock is not None
                       else self.queue.now))
            runtime.register_periodic(self.rebalance_plane.maybe_run,
                                      name="scheduler-rebalance")
            rebalance_mod.set_active(self.rebalance_plane)
        store.bus.subscribe(self._on_event)

    def _arm_resident(self) -> None:
        """Build + attach the resident-state plane (init and the
        recovered-backend re-arm both land here)."""
        from karmada_tpu import resident as resident_mod
        from karmada_tpu.resident import DeltaTracker, ResidentState

        self._resident = ResidentState(
            estimator=self._general,
            audit_interval=self._resident_cfg[1],
            fused=self._resident_cfg[2])
        self._delta_tracker = DeltaTracker()
        # the tracker taps the same watch bus the scheduler does; its
        # coalesced window drains at each device cycle's begin_cycle
        self.store.bus.subscribe(self._delta_tracker.on_event)
        resident_mod.set_active(self._resident)

    def _detach_resident(self) -> None:
        """Tear the resident plane down (backend degrade: the host
        backends never build SolverBatches, and the abandoned zombie may
        still be mid-encode inside the plane)."""
        from karmada_tpu import resident as resident_mod

        if self._delta_tracker is not None:
            self.store.bus.unsubscribe(self._delta_tracker.on_event)
        self._resident = None
        self._delta_tracker = None
        resident_mod.set_active(None)

    # -- event wiring -------------------------------------------------------
    def _on_event(self, event: Event) -> None:
        kind = event.kind
        if kind == ResourceBinding.KIND:
            rb = event.obj
            # only spec changes (generation moved) or creations enqueue; the
            # scheduler's own status writes must not supersede the failure
            # queues or reset the attempt counter (a status-only event would
            # otherwise hot-loop a failing binding with no backoff)
            if event.old is not None and (
                rb.metadata.generation == event.old.metadata.generation
            ):
                return
            with self._queue_lock:
                key = (rb.namespace, rb.name)
                self.queue.push(key, _priority_of(rb),
                                gate_exempt=key in self._inflight_keys)
            sched_metrics.QUEUE_INCOMING.inc(event="BindingUpdate")
            self.worker.enqueue(_CYCLE)
        elif kind == Cluster.KIND:
            # capacity/feasibility changed: unschedulable entries become
            # schedulable again (still-backing-off ones keep their timer);
            # bindings not resident in any queue get another look
            enqueued = False
            with self._queue_lock:
                self.queue.move_all_to_active_or_backoff()
                for rb in self.store.list(ResourceBinding.KIND):
                    key = (rb.namespace, rb.name)
                    if self.queue.has(key):
                        continue  # resident: respect its queue/backoff state
                    if not rb.spec.clusters or self._needs_schedule(rb):
                        self.queue.push(key, _priority_of(rb))
                        sched_metrics.QUEUE_INCOMING.inc(event="ClusterEvent")
                enqueued = self.queue.depths()["active"] > 0
            if enqueued:
                self.worker.enqueue(_CYCLE)

    def _periodic_flush(self) -> None:
        """Per-tick stand-in for the reference's 1s/30s flush goroutines.
        Doubles as the leader-election heartbeat: a follower renews its
        candidacy but never drains the queue (standby scheduler replicas,
        SURVEY §5 leader election)."""
        if self.elector is not None and not self.elector.tick():
            return
        with self._queue_lock:
            moved = self.queue.flush_backoff()
            moved += self.queue.flush_unschedulable_leftover()
            ready = self.queue.depths()["active"]
            oldest = self.queue.oldest_ages()
        # starvation early warning: refresh the oldest-resident gauges on
        # every tick, not only when a cycle runs — a wedged queue must be
        # visible precisely when cycles stop happening
        for qname, age in oldest.items():
            sched_metrics.QUEUE_OLDEST_AGE.set(age, queue=qname)
        # idle planes keep producing series too (rate-limited by the
        # ring's min_interval on the same queue clock): a wedged queue's
        # depth trajectory must be in the ring precisely when cycles
        # stop happening
        obs_timeseries.maybe_sample(self.queue.now())
        if moved or ready:
            self.worker.enqueue(_CYCLE)

    # -- scheduling decision (doScheduleBinding scheduler.go:376) -----------
    def _needs_schedule(self, rb: ResourceBinding) -> bool:
        if rb.metadata.deleting:
            return False
        if rb.spec.placement is None and rb.spec.required_by:
            return False  # attached binding: follows its parents' schedule
        if rb.spec.suspension is not None and rb.spec.suspension.scheduling:
            return False
        if rb.metadata.generation != rb.status.scheduler_observed_generation:
            return True
        if serial.reschedule_required(rb.spec, rb.status):
            return True
        return not rb.spec.clusters and not _is_scheduled_empty(rb)

    # -- batch formation ----------------------------------------------------
    def _batch_ready_locked(self) -> bool:
        """Deadline-vs-size batch formation (call under _queue_lock): cut a
        cycle when batch_window bindings are ready OR the oldest ready
        binding has waited out the deadline; never cut an empty cycle.
        Without a deadline (the default) any non-empty activeQ cuts —
        the legacy immediate-drain behavior.  In overload mode the
        effective deadline widens so trickle cuts stop and cycles fill
        toward batch_window (amortizing the per-cycle fixed cost)."""
        depth = self.queue.depths()["active"]
        if depth == 0:
            return False
        if self.batch_deadline_s is None or depth >= self.batch_window:
            return True
        deadline = self.batch_deadline_s * (
            self.overload_deadline_factor if self._overload else 1.0)
        return self.queue.oldest_active_age() >= deadline

    def _arm_cut_timer_locked(self, oldest_age: float) -> None:
        """Schedule the deferred-cut wakeup (call under _queue_lock): fire
        when the oldest active entry's remaining time to the (possibly
        overload-widened) deadline elapses.  At most one timer is pending;
        firing is deferral-safe — the woken cycle re-runs
        _batch_ready_locked and simply re-arms if still immature (e.g. an
        injected test clock where wall time and queue time diverge), so a
        spurious wakeup costs one no-op cycle, never an empty cut."""
        if self._cut_timer is not None:
            return
        deadline = self.batch_deadline_s * (
            self.overload_deadline_factor if self._overload else 1.0)
        delay = max(deadline - oldest_age, 0.0) + 1e-3

        def fire() -> None:
            with self._queue_lock:
                self._cut_timer = None
            self.worker.enqueue(_CYCLE)

        t = threading.Timer(delay, fire)
        t.daemon = True
        self._cut_timer = t
        t.start()

    def _update_overload(self, dwells_sorted: List[float],
                         popped: int, active_after: int) -> None:
        """Overload degradation driven by MEASURED dwell of the drained
        batch: enter when p95 dwell exceeds deadline * enter_factor (the
        queue is aging faster than cycles retire it).  While in
        overload: explain sampling is suppressed and the batch-formation
        deadline widens.

        Exit fires only on a cycle that actually drained something
        (`popped > 0` — a deferred no-cut invocation is exactly the
        widened deadline doing its coalescing job, not a drain signal)
        and then on ANY of: a sub-window cut (`popped < batch_window`),
        the activeQ empty after the pop (the final full-window cut of a
        backlog must not latch the mode), or p95 dwell back under the
        deadline.  Dwell alone cannot be the only exit: while
        overloaded, deadline-triggered cuts happen at the WIDENED
        deadline, so their p95 could never satisfy the unwidened
        threshold and the mode would stick forever after the storm
        subsides."""
        if self.batch_deadline_s is None:
            return
        p95 = (dwells_sorted[int(0.95 * (len(dwells_sorted) - 1))]
               if dwells_sorted else 0.0)
        if not self._overload:
            if dwells_sorted and \
                    p95 > self.batch_deadline_s * self.overload_enter_factor:
                self._overload = True
                ev.emit(ev.SCHEDULER_REF, ev.TYPE_WARNING,
                        ev.REASON_OVERLOAD_ENTERED,
                        "overload mode entered: p95 batch dwell exceeded "
                        f"{self.overload_enter_factor:g}x the batch "
                        "deadline (explain sampling suppressed, deadline "
                        "widened)", origin="scheduler",
                        cycle_id=self._cycle_id)
        elif popped > 0 and (popped < self.batch_window or active_after == 0
                             or p95 <= self.batch_deadline_s):
            self._overload = False
            ev.emit(ev.SCHEDULER_REF, ev.TYPE_NORMAL,
                    ev.REASON_OVERLOAD_EXITED,
                    "overload mode exited: batch dwell back under the "
                    "deadline", origin="scheduler", cycle_id=self._cycle_id)
        sched_metrics.OVERLOAD_MODE.set(1.0 if self._overload else 0.0)

    # -- the batched cycle --------------------------------------------------
    def _cycle(self, _key) -> None:
        if self.elector is not None and not self.elector.is_leader():
            return  # standby: bindings stay queued; flush re-drives on takeover
        cycle_start = time.perf_counter()
        with self._queue_lock:
            self.queue.flush_backoff()
            # unified flush cadence: parked bindings honor
            # max_in_unschedulable_s on the per-cycle path too, not only
            # the slow periodic flush — otherwise a binding could outlive
            # its unschedulable budget by a full flush interval on a busy
            # plane whose cycles preempt the periodic tick
            self.queue.flush_unschedulable_leftover()
            cut = self._batch_ready_locked()
            infos = self.queue.pop_ready(self.batch_window) if cut else []
            if cut and not infos:
                self._empty_cuts += 1  # invariant breach; surfaced in state
            active_after_pop = self.queue.depths()["active"]
        pop_now = self.queue.now()
        todo: List[Tuple[QueuedBindingInfo, ResourceBinding]] = []
        for info in infos:
            ns, name = info.key
            rb = self.store.try_get(ResourceBinding.KIND, ns, name)
            if rb is None or not self._needs_schedule(rb):
                # pop already removed the entry; an entry concurrently pushed
                # for the same key is a REAL new event and must survive
                continue
            info.attempts += 1
            todo.append((info, rb))
        # queue dwell of the bindings this cycle actually schedules (same
        # clock the queue stamps): the overload detector input and the
        # cycle span's dwell samples.  Pops dropped by _needs_schedule
        # (e.g. the scheduler's own result-patch re-push) are excluded —
        # they are queue bookkeeping, not user-visible latency; the
        # per-origin dwell HISTOGRAM in pop_ready still counts them.
        # Skipped entirely when both consumers are disarmed (no batch
        # deadline, tracing off) — the default serve path must not pay
        # an O(n log n) sort per cycle for a discarded list.
        dwells = (sorted(max(0.0, pop_now - info.timestamp)
                         for info, _ in todo)
                  if self.batch_deadline_s is not None or obs.TRACER.enabled
                  else [])
        self._update_overload(dwells, popped=len(infos),
                              active_after=active_after_pop)
        fr: Optional[dict] = None  # this cycle's flight record, if armed
        if todo:
            sched_metrics.BATCH_SIZE.observe(len(todo))
            self._cycle_id += 1
            batch_n = len(todo)
            fault_kind: Optional[str] = None
            cut_reason = ("window" if len(infos) >= self.batch_window else
                          "deadline" if self.batch_deadline_s is not None
                          else "drain")
            # batch-formation lifecycle event on the scheduler's own
            # timeline: the THREE stable cut shapes (window-full,
            # deadline-hit, immediate drain) coalesce, so a steady plane
            # keeps one bumping entry while mode flips stay visible
            ev.emit(ev.SCHEDULER_REF, ev.TYPE_NORMAL, ev.REASON_BATCH_FORMED,
                    {"window": "batch cut at the batch window",
                     "deadline": "batch cut at the formation deadline",
                     "drain": "batch drained immediately"}[cut_reason],
                    origin="scheduler", cycle_id=self._cycle_id)
            # recoverable degrade: the cooldown ticks once per REAL
            # scheduling cycle here — not per _solve call, which the
            # affinity-failover loop invokes once per round and would
            # expire the cooldown early on multi-term bindings
            self._maybe_rearm_device()
            clusters = list(self.store.list(Cluster.KIND))
            # the batch's result-patch re-push echoes are gate-exempt for
            # the duration of this cycle (see _inflight_keys)
            with self._queue_lock:
                self._inflight_keys = {info.key for info, _ in todo}
            # flight recorder: one scheduler.cycle span per batched cycle
            # (child of the worker's reconcile span); the pipeline executor,
            # serial fallback, and estimator RPCs all nest under it
            with obs.TRACER.span(obs.SPAN_CYCLE, bindings=len(todo),
                                 backend=self.backend) as cspan:
                outcomes = None
                try:
                    outcomes = self.schedule_batch(
                        [rb for _, rb in todo], clusters)
                except Exception as e:  # noqa: BLE001 — cycle fault
                    # containment: a raising batch solve (device fault,
                    # poisoned d2h, injected chaos) must not LOSE its
                    # popped bindings — pop_ready already removed them, so
                    # without this they would vanish until a cluster event
                    # rescans the store.  Route every one to backoff and
                    # count the fault; the worker keeps running.
                    sched_metrics.CYCLE_FAULTS.inc(kind=type(e).__name__)
                    ev.emit(ev.SCHEDULER_REF, ev.TYPE_WARNING,
                            ev.REASON_CYCLE_FAULT,
                            f"cycle fault contained ({type(e).__name__}); "
                            "popped bindings routed to backoff",
                            origin="scheduler", cycle_id=self._cycle_id)
                    import traceback

                    traceback.print_exc()
                    if cspan:
                        cspan.set_attr(cycle_fault=type(e).__name__)
                    with self._queue_lock:
                        for info, _ in todo:
                            self.queue.push_backoff_if_not_present(info)
                    fault_kind = type(e).__name__
                    # incident trigger AFTER the queue lock releases: the
                    # capture reads locks.state_payload() and must not
                    # nest under any plane lock
                    obs_incidents.trigger(
                        obs_incidents.TRIGGER_CYCLE_FAULT,
                        f"cycle fault contained ({fault_kind}); popped "
                        "bindings routed to backoff",
                        refs=[info.key for info, _ in todo[:16]],
                        detail={"kind": fault_kind,
                                "cycle_id": self._cycle_id,
                                "batch": batch_n})
                    # the routing/metrics tail below runs over the empty
                    # batch: nothing scheduled, nothing double-routed
                    todo, outcomes = [], []
                finally:
                    # the echoes fire inside schedule_batch (_apply_result
                    # patches); clear even on a raise, or the keys would
                    # stay gate-exempt across the worker's retry
                    with self._queue_lock:
                        self._inflight_keys = set()
                # handleErr routing (scheduler.go:829-841): UnschedulableError
                # waits for a cluster event; other failures back off and retry.
                # Success needs no forget: pop_ready removed the entry, and any
                # concurrent re-push is a fresh event for the next cycle.
                # Unschedulable routings carry their dominant reason into the
                # queue's map and karmada_schedule_unschedulable_total — the
                # explain-armed decode attaches the solver's verdict, every
                # other path classifies by the known message shapes.
                with self._queue_lock:
                    for (info, _), res in zip(todo, outcomes):
                        if isinstance(res, serial.UnschedulableError):
                            reason = obs_decisions.classify_unschedulable(res)
                            self.queue.push_unschedulable_if_not_present(
                                info, reason=reason)
                            sched_metrics.UNSCHEDULABLE.inc(reason=reason)
                        elif isinstance(res, Exception):
                            self.queue.push_backoff_if_not_present(info)
                cycle_elapsed = time.perf_counter() - cycle_start
                now = self.queue.now()
                e2es: List[float] = []
                for (info, _), res in zip(todo, outcomes):
                    if isinstance(res, serial.UnschedulableError):
                        result = sched_metrics.RESULT_UNSCHEDULABLE
                    elif isinstance(res, Exception):
                        result = sched_metrics.RESULT_ERROR
                    else:
                        result = sched_metrics.RESULT_SCHEDULED
                    sched_metrics.SCHEDULE_ATTEMPTS.inc(
                        result=result,
                        schedule_type=sched_metrics.SCHEDULE_TYPE_RECONCILE,
                    )
                    # per-binding e2e: from its first scheduling attempt
                    # (queue clock) to this outcome; floor at the cycle cost
                    # so a single-attempt binding isn't recorded as ~0
                    e2e = max(now - (info.initial_attempt_timestamp or now),
                              cycle_elapsed)
                    e2es.append(e2e)
                    sched_metrics.E2E_LATENCY.observe(
                        e2e,
                        result=result,
                        schedule_type=sched_metrics.SCHEDULE_TYPE_RECONCILE,
                    )
                if cspan:
                    # bounded per-binding samples on the cycle span: the
                    # loadgen soak report derives its p50/p95/p99 schedule
                    # latency and dwell from these (obs flight recorder),
                    # strided deterministically so a 4096-binding cycle
                    # stays a bounded record
                    ds, d_stride = _span_samples(dwells)
                    es, e_stride = _span_samples(e2es)
                    cspan.set_attr(
                        dwell_samples=ds, dwell_stride=d_stride,
                        e2e_samples=es, e2e_stride=e_stride,
                        overload=self._overload)
            # incident plane (obs/incidents): one compact flight record
            # per batched cycle — the ring incident bundles snapshot.
            # Field assembly only runs when armed (the obs_events
            # armed() hoist pattern); disarmed cost is one list read.
            if obs_incidents.flight_armed():
                n_unsched = sum(isinstance(r, serial.UnschedulableError)
                                for r in outcomes)
                n_exc = sum(isinstance(r, Exception) for r in outcomes)
                fr = {
                    "t": round(now, 6),
                    "cycle_id": self._cycle_id,
                    "trace_id": cspan.trace.trace_id if cspan else None,
                    "popped": len(infos),
                    "batch": batch_n,
                    "cut": cut_reason,
                    "backend": self.backend,
                    "degraded_from": self._degraded_from,
                    "overload": self._overload,
                    "fault": fault_kind,
                    "scheduled": len(outcomes) - n_exc,
                    "unschedulable": n_unsched,
                    "errors": n_exc - n_unsched,
                    "elapsed_s": round(cycle_elapsed, 6),
                    "dwell_max_s": (round(dwells[-1], 6)
                                    if dwells else None),
                    "pipeline": self._last_pipeline,
                    "shortlist": self._shortlist_flight_delta(),
                }
                self._last_pipeline = None  # consumed by this record
        with self._queue_lock:
            depths = self.queue.depths()
            oldest = self.queue.oldest_ages()
            # re-drive only when another cut is actually due: with a batch
            # deadline armed, an immature trickle must wait out the
            # deadline, not hot-loop the worker — the deferred-cut timer
            # (not the coarser periodic tick) owns the wakeup, so the
            # oldest entry's dwell cannot overshoot the deadline by a
            # full tick interval when no further push arrives
            more = self._batch_ready_locked()
            if (not more and self.batch_deadline_s is not None
                    and depths["active"] > 0):
                self._arm_cut_timer_locked(oldest["active"])
        for qname, depth in depths.items():
            sched_metrics.QUEUE_DEPTH.set(depth, queue=qname)
            sched_metrics.QUEUE_OLDEST_AGE.set(oldest[qname], queue=qname)
        if fr is not None:
            # complete and land the flight record with post-cycle queue
            # state; emitted before maybe_sample so a bundle captured off
            # this cycle's SLO verdict already sees its record
            fr["depths"] = dict(depths)
            fr["oldest_s"] = {k: round(v, 6) for k, v in oldest.items()}
            obs_incidents.record("cycle", **fr)
        # telemetry plane (obs/timeseries, serve --telemetry): one ring
        # sample per scheduling cycle on the QUEUE's clock — the loadgen
        # VirtualClock in compressed soaks, so synthetic hours produce
        # real series.  Disarmed cost is one module-global read.
        obs_timeseries.maybe_sample(self.queue.now())
        if more:
            self.worker.enqueue(_CYCLE)

    def resident_state(self) -> Optional[Dict[str, object]]:
        """The resident-state plane's stats snapshot, or None when the
        plane is not armed (serves /debug/state and the SOAK report)."""
        return self._resident.stats() if self._resident is not None else None

    def rebalance_state(self) -> Optional[Dict[str, object]]:
        """The rebalance plane's stats snapshot, or None when disarmed
        (serves /debug/rebalance and the soak report)."""
        return (self.rebalance_plane.stats()
                if self.rebalance_plane is not None else None)

    def promote(self, key, priority: int = 0, origin: str = "rebalance"):
        """Priority push straight into the active queue: the rebalance
        plane's re-place step and the FederatedHPA fast path both land
        here so drain/autoscale -> re-solve latency is one cycle instead
        of waiting for the next detector resolve or periodic flush.  The
        push respects the admission gate like any external event (a fast
        path must not become an admission bypass); `origin` buckets the
        entry's queue dwell."""
        with self._queue_lock:
            decision = self.queue.push(key, priority, origin=origin)
        sched_metrics.PRIORITY_PUSHES.inc(origin=origin)
        self.worker.enqueue(_CYCLE)
        return decision

    def queue_state(self) -> Dict[str, object]:
        """One consistent snapshot of the scheduling-queue state — depths,
        per-queue oldest-resident age, unschedulable reasons — plus the
        batch-formation/admission config and the overload flag.  Serves
        /debug/load and the loadgen soak report."""
        with self._queue_lock:
            depths = self.queue.depths()
            oldest = self.queue.oldest_ages()
            reasons = self.queue.unschedulable_reasons()
        return {
            "depths": depths,
            "oldest_age_s": {k: round(v, 6) for k, v in oldest.items()},
            "unschedulable_reasons": reasons,
            "overload": self._overload,
            "empty_cuts": self._empty_cuts,
            "batch_window": self.batch_window,
            "batch_deadline_s": self.batch_deadline_s,
            "admission_limit": self.queue.max_resident,
        }

    # -- core: schedule a list of bindings against a cluster snapshot ------
    def schedule_batch(
        self, bindings: List[ResourceBinding], clusters: List[Cluster]
    ) -> List[object]:
        results, affinity_name = self.solve_batch(bindings, clusters)
        outcomes: List[object] = []
        for i, rb in enumerate(bindings):
            res = results.get(i)
            # _apply_result may downgrade a success to unschedulable (e.g.
            # the quota-enforcement admission denies the patch) — the queue
            # must route on the EFFECTIVE outcome
            outcomes.append(self._apply_result(rb, res, affinity_name.get(i, "")))
        return outcomes

    def solve_batch(
        self, bindings: List[ResourceBinding], clusters: List[Cluster],
        *, detached: bool = False,
    ) -> Tuple[Dict[int, object], Dict[int, str]]:
        """The affinity-failover solve loop WITHOUT the store patch-back:
        returns ({index: List[TargetCluster] | Exception}, {index:
        affinity term name}).  The live path (schedule_batch) applies the
        results to the store; the facade/what-if plane (karmada_tpu/
        facade) consumes them directly.

        ``detached=True`` is the hypothetical-solve contract: no explain
        sampling, no resident-plane advance, no encoder-cache reuse, no
        mid-serve degradation — the solve reads the cluster snapshot it
        was handed and touches NOTHING owned by the live cycle worker, so
        it is safe to run from a facade thread concurrently with live
        cycles (detached callers serialize among themselves)."""
        # affinity failover loop: term index per binding
        term_idx: Dict[int, int] = {}
        active: List[Tuple[int, ResourceBinding]] = list(enumerate(bindings))
        results: Dict[int, object] = {}
        affinity_name: Dict[int, str] = {}
        # explain plane: one sampling decision per cycle (every affinity
        # round of a sampled cycle records, so a failover story is whole)
        explain_rec = None if detached else self._explain_sample()
        if not detached:
            self._cycle_explain = explain_rec
        keys_all = [f"{rb.namespace}/{rb.name}" for rb in bindings]
        tokens_all = None
        if self._resident is not None and not detached:
            from karmada_tpu.resident import RowToken

            tokens_all = []
            for rb, key in zip(bindings, keys_all):
                terms = (rb.spec.placement.cluster_affinities
                         if rb.spec.placement else [])
                # any write to the binding (spec or status) bumps its
                # resourceVersion, so (key, rv) is exactly the encoded
                # row's identity; affinity-failover bindings encode
                # against a per-round synthesized status (observed
                # affinity name), so their rows are not snapshot-
                # addressable and bypass the row cache
                tokens_all.append(
                    None if terms
                    else RowToken(key, rb.metadata.resource_version))

        while active:
            items: List[Tuple[ResourceBindingSpec, ResourceBindingStatus]] = []
            for i, rb in active:
                spec, status = rb.spec, rb.status
                terms = spec.placement.cluster_affinities if spec.placement else []
                if terms:
                    idx = term_idx.setdefault(i, self._initial_term(rb))
                    status = _status_with_affinity(status, terms[idx].affinity_name)
                    affinity_name[i] = terms[idx].affinity_name
                items.append((spec, status))

            outcome = self._solve(items, clusters,
                                  keys=[keys_all[i] for i, _ in active],
                                  explain=explain_rec,
                                  tokens=([tokens_all[i] for i, _ in active]
                                          if tokens_all is not None
                                          else None),
                                  detached=detached)

            next_active: List[Tuple[int, ResourceBinding]] = []
            for (i, rb), res in zip(active, outcome):
                if isinstance(res, Exception):
                    terms = rb.spec.placement.cluster_affinities if rb.spec.placement else []
                    if terms and term_idx.get(i, 0) + 1 < len(terms):
                        term_idx[i] = term_idx[i] + 1
                        next_active.append((i, rb))
                        continue
                results[i] = res
            active = next_active

        return results, affinity_name

    def _explain_sample(self) -> Optional["obs_decisions.DecisionRecorder"]:
        """The decision recorder for THIS cycle, or None: the explain
        plane samples whole scheduling cycles at `self.explain` rate.
        Overload degradation sheds the explain cost first — a plane that
        cannot keep dwell under the deadline has no budget for the
        explain jit variant's extra planes."""
        if self._decisions is None or self._overload:
            return None
        if self.explain >= 1.0 or self._explain_rng.random() < self.explain:
            return self._decisions
        return None

    def _initial_term(self, rb: ResourceBinding) -> int:
        """Resume from the observed affinity term (scheduler.go:599-616)."""
        terms = rb.spec.placement.cluster_affinities if rb.spec.placement else []
        observed = rb.status.scheduler_observed_affinity_name
        for idx, t in enumerate(terms):
            if t.affinity_name == observed:
                return idx
        return 0

    def _encoder_cache(self, clusters) -> "tensors.EncoderCache":
        """Warm the encoder across cycles with precise invalidation: the
        per-cycle status-derived rows always reset; the O(P x C) placement
        masks survive while no cluster SPEC changed (generation signature);
        the api-enablement rows survive while enablements are unchanged."""
        cache = getattr(self, "_enc_cache", None)
        # generation covers spec changes; labels live in metadata (no
        # generation bump) yet drive placement label selectors, so they
        # sign explicitly
        spec_sig = tuple(
            (c.name, c.metadata.generation, tuple(sorted(c.metadata.labels.items())))
            for c in clusters
        )
        api_sig = tuple(
            (c.name, tuple(
                (e.group_version, tuple(e.resources))
                for e in c.status.api_enablements
            ))
            for c in clusters
        )
        if cache is None or spec_sig != getattr(self, "_enc_spec_sig", None):
            cache = tensors.EncoderCache()
            self._enc_cache = cache
            self._enc_spec_sig = spec_sig
            self._enc_api_sig = api_sig
        elif api_sig != getattr(self, "_enc_api_sig", None):
            cache.gvk_rows = {}
            self._enc_api_sig = api_sig
        cache.reset_for_cycle()
        return cache

    # -- backend dispatch ---------------------------------------------------
    def _solve_native(
        self,
        items: List[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
        clusters: List[Cluster],
        out: List[object],
        detached: bool = False,
    ) -> List[int]:
        """backend="native": the compiled C++ pipeline (karmada_tpu/native)
        schedules the whole batch on host; bindings in its documented
        unsupported classes (multi-component, vanished prev clusters,
        resource modelings) fall through to the Python serial path, as does
        everything when the toolchain is absent or empty-workload
        propagation is on (a native no-op for that flag would silently drop
        zero-replica propagation).  Returns the handled indices."""
        from karmada_tpu import native as native_mod

        if self.enable_empty_workload_propagation or not native_mod.available():
            return []
        # the native pipeline hardcodes GeneralEstimator capacity math; a
        # custom estimator tier (accurate gRPC clients etc.) must win, so
        # anything beyond the plain GeneralEstimator routes to serial
        if not all(type(e) is GeneralEstimator for e in self.estimators):
            return []
        t0 = time.perf_counter()
        # one snapshot per cluster list: the affinity-failover loop re-solves
        # against the same snapshot object each round (EncoderCache analog).
        # A detached solve builds its own snapshot and leaves the cache
        # alone — it runs off the cycle worker, and clobbering the live
        # worker's cached snapshot from a facade thread would race it.
        cached = None if detached else self._native_snap
        if cached is not None and cached[0] is clusters:
            snap = cached[1]
        else:
            snap = native_mod.NativeSnapshot(
                clusters, native_mod.collect_res_names(items))
            if not detached:
                self._native_snap = (clusters, snap)
        nb = native_mod.marshal_batch(items, snap)
        t1 = time.perf_counter()
        sched_metrics.STEP_LATENCY.observe(
            t1 - t0, schedule_step=sched_metrics.STEP_ENCODE
        )
        results = native_mod.run_marshaled(nb, snap)
        sched_metrics.STEP_LATENCY.observe(
            time.perf_counter() - t1, schedule_step=sched_metrics.STEP_SOLVE
        )
        handled: List[int] = []
        for i, (st, targets) in enumerate(results):
            if st == native_mod.STATUS_OK:
                out[i] = targets
            elif st == native_mod.STATUS_FIT_ERROR:
                spec_i, status_i = items[i]
                _, diagnosis = serial.find_clusters_that_fit(
                    spec_i, status_i, clusters)
                out[i] = serial.FitError(diagnosis)
            elif st == native_mod.STATUS_UNSCHEDULABLE:
                out[i] = serial.UnschedulableError(
                    "insufficient capacity (native)")
            elif st == native_mod.STATUS_NO_CLUSTER:
                out[i] = serial.NoClusterAvailableError(
                    "no clusters available to schedule")
            else:  # STATUS_UNSUPPORTED: serial fallback owns it
                continue
            handled.append(i)
        return handled

    def _solve_device(
        self,
        items: List[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
        clusters: List[Cluster],
        cancelled: Optional[threading.Event] = None,
        keys: Optional[List[str]] = None,
        explain=None,
        tokens=None,
        detached: bool = False,
    ) -> Dict[int, object]:
        """backend="device": one batched cycle through the pipelined chunk
        executor (scheduler/pipeline.py — the same loop bench.py measures).
        The cycle's items split into pipeline_chunk-sized chunks: chunk
        k's compact solve dispatches asynchronously while the host encodes
        chunk k+1 and finalizes/decodes chunk k-1, and the consumed-
        capacity accumulators thread chunk to chunk so pricing stays
        sequential-equivalent at chunk granularity (chunk k+1 prices
        against everything chunks <= k consumed — a FINER contention
        granularity than the old monolithic batch's waves, i.e. strictly
        closer to the reference's one-binding-at-a-time semantics).  A
        cycle that fits one chunk takes the identical single-dispatch
        path as before (no carry operands, same jit signatures).

        Returns {index: result} for every binding a device tier owns —
        its OWN buffer, never a shared one, so the degradation guard can
        abandon a hung cycle without racing a zombie thread's writes.
        `cancelled` (set by the guard on abandonment) gates every stage
        boundary and every shared-state write inside the executor: an
        abandoned cycle that UNBLOCKS minutes later must not pollute the
        live latency histograms, and the encoder cache is acquired exactly
        once up front so a zombie never repopulates what the degrade path
        cleared."""
        from karmada_tpu.scheduler import pipeline

        if chaos_mod.armed():
            # chaos seam (device.cycle:hang): a stalled accelerator tunnel
            # looks exactly like this sleep — the mid-serve guard must
            # abandon the cycle and degrade through its REAL path
            f = chaos_mod.fire(chaos_mod.SITE_DEVICE_CYCLE,
                               backend=self.backend)
            if f is not None and f.mode == "hang":
                time.sleep(f.delay)
                if cancelled is not None and cancelled.is_set():
                    # already abandoned by the guard: the zombie must not
                    # go on to run a real solve the process may tear down
                    # underneath it (XLA aborts on threads killed mid-op)
                    return {}
        self._ensure_mesh()
        encode = None
        if detached:
            # detached (facade/what-if) cycle: per-call encoder state only.
            # The resident plane's begin_cycle would DRAIN the live delta
            # tracker and the shared EncoderCache belongs to the cycle
            # worker — a hypothetical solve must touch neither.  Shortlist
            # and carry still compose below: this is the same pipelined
            # executor the live path runs, minus the live-state hooks.
            cindex = tensors.ClusterIndex.build(clusters)
            cache = tensors.EncoderCache()
            cache.reset_for_cycle()
        elif self._resident is not None:
            # resident-state plane: advance the persistent tensors by this
            # window's coalesced watch deltas (or rebuild losslessly on a
            # structural change), then hand the pipeline an encoder that
            # gathers cached rows and re-encodes only the misses.  The
            # plane's own EncoderCache/ClusterIndex replace the per-cycle
            # ones — its invalidation is delta-precise where
            # _encoder_cache's is signature-coarse.
            state = self._resident
            state.begin_cycle(
                clusters, self._delta_tracker.drain()
                if self._delta_tracker is not None else None)
            cindex = state.cindex
            cache = state.enc_cache
            toks = tokens if tokens is not None else [None] * len(items)

            def encode(part, offset, armed):  # noqa: F811 — the hook
                return state.encode_cycle(
                    part, toks[offset:offset + len(part)], explain=armed)
        else:
            cindex = tensors.ClusterIndex.build(clusters)
            cache = self._encoder_cache(clusters)
        shortlist_cfg = None
        if self.shortlist_k:
            if self._shortlist_cfg is None:
                from karmada_tpu.ops.shortlist import ShortlistConfig

                self._shortlist_cfg = ShortlistConfig(
                    k=self.shortlist_k,
                    min_cells=self.shortlist_min_cells)
            shortlist_cfg = self._shortlist_cfg
        carry = len(items) > self.pipeline_chunk
        res = pipeline.run_pipeline(
            items, cindex, self._general,
            chunk=self.pipeline_chunk, waves=self.waves, cache=cache,
            # single-chunk cycles need no carry: waves already price the
            # whole cycle, and skipping it keeps the pre-pipeline jit
            # signatures (no with_used variants on small control planes)
            carry=carry,
            # spread/big sub-solves join the accounting too: each chunk's
            # sub-solves receive the carry-in and contribute their own
            # consumption back (one-chunk lag — see pipeline.py), so a
            # multi-chunk cycle cannot overcommit a cluster across its
            # chunks' spread sets the way independent raw-snapshot
            # sub-solves would
            carry_spread=carry,
            enable_empty_workload_propagation=(
                self.enable_empty_workload_propagation),
            cancelled=cancelled,
            explain=explain, keys=keys, encode=encode,
            shortlist=shortlist_cfg,
        )
        if not detached:
            # flight-record breadcrumb: the live pipeline's dispatch/d2h
            # accounting (solve_s spans sub-solves + device wait + sparse
            # D2H); detached what-if solves run off-worker and must not
            # clobber the cycle's record
            self._last_pipeline = {
                "solve_s": round(res.solve_s, 6),
                "chunks": res.chunks,
                "cancelled": res.cancelled,
                "scheduled": res.scheduled,
                "failures": res.failures,
            }
        return res.results

    def _shortlist_flight_delta(self) -> Optional[dict]:
        """Since-last-record deltas of the shortlist tier counters for
        the flight record; None until ops/shortlist is imported (the
        tiered path has never dispatched)."""
        import sys

        mod = sys.modules.get("karmada_tpu.ops.shortlist")
        if mod is None:
            return None
        cur = {
            "dispatches": mod.SHORTLIST_DISPATCHES.total(),
            "fallbacks": mod.SHORTLIST_FALLBACKS.total(),
            "widenings": mod.SHORTLIST_WIDENINGS.total(),
        }
        base = self._flight_shortlist_base
        self._flight_shortlist_base = cur
        if base is None:
            return cur
        return {k: cur[k] - base.get(k, 0) for k in cur}

    def _ensure_mesh(self) -> None:
        """One-shot solver-mesh activation (ops/meshing), performed INSIDE
        the device solve path — on the guarded daemon thread when the
        mid-serve death guard is armed — never in __init__: activation
        enumerates jax devices, i.e. the process's first in-process
        backend init, which can hang indefinitely on a dead accelerator
        tunnel.  In __init__ that hang would stop the control plane from
        ever coming up; here it is bounded by device_cycle_timeout_s and
        degrades like any other dead device cycle.  A single-device
        environment takes the silent no-op fallback; an explicit shape
        larger than the device pool warns and runs unsharded (the plane
        must come up wherever it is pointed)."""
        if self._mesh_tried or not self.mesh_shape:
            return
        self._mesh_tried = True
        from karmada_tpu.ops import meshing

        try:
            self.mesh_plan = meshing.activate(self.mesh_shape)
        except RuntimeError as e:
            import sys

            print(f"WARNING: {e}; scheduler runs single-device",
                  file=sys.stderr, flush=True)
            return
        if self.mesh_plan is not None:
            print(f"scheduler solver mesh active: "
                  f"{self.mesh_plan.shape_str} over "
                  f"{self.mesh_plan.n_devices} "
                  f"{self.mesh_plan.platform} device(s)", flush=True)

    def _solve_device_guarded(
        self,
        items: List[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
        clusters: List[Cluster],
        keys: Optional[List[str]] = None,
        explain=None,
        tokens=None,
    ) -> Dict[int, object]:
        """Run the device cycle under the mid-serve death guard: a cycle
        exceeding device_cycle_timeout_s is abandoned on its daemon thread
        and the scheduler degrades ONE-WAY to the fastest working backend
        (same policy as the startup probe, utils/deviceprobe) — the
        batched scheduler must never hang the control plane because the
        accelerator tunnel died under it."""
        if self.device_cycle_timeout_s is None:
            return self._solve_device(items, clusters, keys=keys,
                                      explain=explain, tokens=tokens)
        box: Dict[str, object] = {}
        cancelled = threading.Event()
        # thread handoff: the daemon thread adopts this (worker) thread's
        # span so the pipeline's spans parent into the cycle trace
        tracer = obs.TRACER
        trace_parent = tracer.current() if tracer.enabled else None

        def run() -> None:
            try:
                with tracer.attach(trace_parent):
                    box["res"] = self._solve_device(items, clusters,
                                                    cancelled=cancelled,
                                                    keys=keys,
                                                    explain=explain,
                                                    tokens=tokens)
            # vet: ignore[exception-hygiene] boxed and re-raised on the caller thread
            except Exception as e:  # noqa: BLE001 — re-raised on the caller
                box["err"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="scheduler-device-cycle")
        t.start()
        t.join(self.device_cycle_timeout_s)
        if t.is_alive():
            cancelled.set()  # the zombie must stop touching shared state
            if trace_parent is not None:
                # the abandoned cycle's trace is precisely the evidence the
                # guard used to discard: mark it and let the root's end
                # force-close the zombie's dangling stage spans
                trace_parent.set_attr(
                    cancelled=True, device_cycle_abandoned=True,
                    timeout_s=self.device_cycle_timeout_s)
            self._degrade_device()
            return {}
        if "err" in box:
            raise box["err"]  # type: ignore[misc]  # same surface as unguarded
        # a clean device cycle while probing closes the half-open window
        self._degrade_streak = 0
        return box.get("res", {})  # type: ignore[return-value]

    def _degrade_device(self) -> None:
        """Abandon the device backend after a hung cycle: fall to the
        fastest working host backend and detach every device-coupled
        plane (mesh, resident, encoder cache — the zombie thread may
        still touch them).  With device_recover_cycles set this is a
        COOLDOWN, not a death sentence: _maybe_rearm_device re-probes
        after the cooldown, doubling it per consecutive failure."""
        from karmada_tpu import native as native_mod

        self.backend = ("native" if native_mod.available() else "serial")
        self._degraded_from = "device"
        self._cycles_since_degrade = 0
        self._degrade_streak += 1
        # the zombie thread still holds the old encoder cache: future
        # cycles must never share it
        self._enc_cache = None
        self._enc_spec_sig = None
        if self._resident is not None:
            # the device backend is gone and the zombie may still be
            # mid-encode inside the plane: detach it (the degraded
            # backends never build SolverBatches) and stop reporting
            # a resident plane at /debug/resident
            self._detach_resident()
        if self.mesh_plan is not None:
            # the device backend is gone: stop reporting an active
            # solver mesh (/debug/state, karmada_mesh_* gauges)
            from karmada_tpu.ops import meshing

            meshing.deactivate()
            self.mesh_plan = None
        sched_metrics.BACKEND_DEGRADED.inc(to=self.backend)
        ev.emit(ev.SCHEDULER_REF, ev.TYPE_WARNING, ev.REASON_BACKEND_DEGRADED,
                f"device backend degraded to {self.backend} after a hung "
                "cycle (mid-serve death guard)", origin="scheduler",
                cycle_id=self._cycle_id)
        obs_incidents.trigger(
            obs_incidents.TRIGGER_BACKEND_DEGRADE,
            f"device backend degraded to {self.backend} after a hung cycle",
            detail={"to": self.backend,
                    "streak": self._degrade_streak,
                    "timeout_s": self.device_cycle_timeout_s,
                    "recover_cycles": self.device_recover_cycles,
                    "cycle_id": self._cycle_id})
        import sys

        recover = self.device_recover_cycles
        fate = ("permanently" if not recover else
                f"for ~{recover * (2 ** (self._degrade_streak - 1))} "
                "cycle(s) (cooldown re-probe armed)")
        print(
            f"WARNING: device solve cycle exceeded "
            f"{self.device_cycle_timeout_s:g}s (tunnel dead "
            f"mid-serve?); abandoning it and degrading the scheduler "
            f"to backend={self.backend} {fate}",
            file=sys.stderr, flush=True,
        )

    def _maybe_rearm_device(self) -> None:
        """Half-open re-probe of a degraded device backend: after the
        cooldown (device_recover_cycles scheduling cycles, doubled per
        consecutive failed re-arm) the next cycle tries the device path
        again.  A hang degrades it right back (the guard is still
        armed); a clean cycle resets the escalation streak.  Runs on the
        cycle worker only, once per non-empty cycle (_cycle)."""
        if self._degraded_from != "device" or self.backend == "device":
            return
        if not self.device_recover_cycles:
            return  # legacy one-way degrade
        self._cycles_since_degrade += 1
        need = self.device_recover_cycles * (
            2 ** max(self._degrade_streak - 1, 0))
        if self._cycles_since_degrade < need:
            return
        self.backend = "device"
        self._cycles_since_degrade = 0
        self._mesh_tried = False  # the mesh may reactivate with the device
        self._native_snap = None
        if self._resident_cfg[0] and self._resident is None:
            self._arm_resident()
        sched_metrics.BACKEND_REARMED.inc(backend="device")
        ev.emit(ev.SCHEDULER_REF, ev.TYPE_NORMAL, ev.REASON_BACKEND_REARMED,
                "device backend re-armed after its degrade cooldown "
                "(half-open re-probe)", origin="scheduler",
                cycle_id=self._cycle_id)
        import sys

        print(
            "scheduler re-arming the device backend after its degrade "
            f"cooldown ({need} cycle(s)); the mid-serve guard stays armed",
            file=sys.stderr, flush=True,
        )

    def _solve(
        self,
        items: List[Tuple[ResourceBindingSpec, ResourceBindingStatus]],
        clusters: List[Cluster],
        keys: Optional[List[str]] = None,
        explain=None,
        tokens=None,
        detached: bool = False,
    ) -> List[object]:
        """Returns per item either List[TargetCluster] or an Exception."""
        cal = serial.make_cal_available(self.estimators)
        out: List[object] = [None] * len(items)
        device_idx: List[int] = []
        if self.backend == "device" and items:
            if detached:
                # no mid-serve death guard: a detached (facade/what-if)
                # solve must never degrade the LIVE backend as a side
                # effect — its caller bounds it with transport timeouts
                solved = self._solve_device(items, clusters, keys=keys,
                                            detached=True)
            else:
                solved = self._solve_device_guarded(items, clusters,
                                                    keys=keys,
                                                    explain=explain,
                                                    tokens=tokens)
            for i, res in solved.items():
                out[i] = res
            device_idx = list(solved.keys())
        # not elif: the guard may have just degraded device -> native, and
        # the CURRENT batch deserves the fast path too
        if self.backend == "native" and items and not device_idx:
            device_idx = self._solve_native(items, clusters, out,
                                            detached=detached)
        device_set = set(device_idx)
        host_idx = [i for i in range(len(items)) if i not in device_set]
        if host_idx:
            t3 = time.perf_counter()
            with obs.TRACER.span(obs.SPAN_SERIAL, bindings=len(host_idx)):
                for i in host_idx:
                    spec, status = items[i]
                    try:
                        out[i] = serial.schedule(
                            spec, status, clusters, cal,
                            enable_empty_workload_propagation=self.enable_empty_workload_propagation,
                        )
                    # vet: ignore[exception-hygiene] failure returned as the binding's outcome object
                    except Exception as e:  # noqa: BLE001 — per-binding failure object
                        out[i] = e
                if explain is not None:
                    # the serial reference path records decisions too: a
                    # FitError's per-cluster diagnosis maps onto the same
                    # verdict bitmask (obs/decisions.bit_for_serial_reason),
                    # so serial and device decisions stay comparable
                    sp = obs.TRACER.current()
                    tid = (sp.trace.trace_id if sp is not None else None)
                    for i in host_idx:
                        key = (keys[i] if keys is not None
                               else obs_decisions.default_key(items[i][0]))
                        explain.record(obs_decisions.decision_from_result(
                            key, out[i], len(clusters), trace_id=tid,
                            backend="serial"))
            sched_metrics.STEP_LATENCY.observe(
                time.perf_counter() - t3, schedule_step=sched_metrics.STEP_SERIAL
            )
        return out

    def _link_decision(self, rb: ResourceBinding,
                       event_id: Optional[int]) -> None:
        """Cross-reference the outcome event with the explain plane's
        Decision record for the same binding: the Decision gets the
        event id, the event gets the decision id, so
        /debug/explain/{ns}/{name} and the timeline point at each
        other.  Only fires on an EXPLAIN-SAMPLED cycle (_cycle_explain):
        an unsampled cycle's outcome must never adopt a stale verdict an
        earlier sampled cycle recorded for the same binding."""
        if self._cycle_explain is None or event_id is None:
            return
        did = self._cycle_explain.link_event(f"{rb.namespace}/{rb.name}",
                                             event_id)
        if did is not None:
            self.recorder.link_decision(event_id, did)

    # -- result patch-back (patchScheduleResultForResourceBinding :664) -----
    def _apply_result(self, rb: ResourceBinding, res, affinity_name: str):
        """Patch the schedule outcome back; returns the EFFECTIVE outcome
        (admission may downgrade a success to UnschedulableError)."""
        if res is None:
            return None

        if isinstance(res, Exception):
            reason = (
                REASON_NO_FIT if isinstance(res, serial.FitError) else REASON_UNSCHEDULABLE
            )

            def mark_failed(obj: ResourceBinding) -> None:
                set_condition(obj.status.conditions, Condition(
                    type=COND_SCHEDULED, status="False", reason=reason,
                    message=str(res),
                ))
                if affinity_name:
                    obj.status.scheduler_observed_affinity_name = affinity_name

            self.store.mutate(ResourceBinding.KIND, rb.namespace, rb.name, mark_failed)
            # the timeline's unschedulable entry carries the dominant
            # reason from the explain classifier (exc.reason when an
            # explain-armed decode attached the solver's verdict, the
            # message-shape classifier otherwise)
            dom = obs_decisions.classify_unschedulable(res) \
                if isinstance(res, serial.UnschedulableError) else None
            eid = self.recorder.event(
                rb, ev.TYPE_WARNING, ev.REASON_SCHEDULE_BINDING_FAILED,
                (f"{res} (dominant reason: {dom})" if dom else str(res)),
                origin="scheduler", cycle_id=self._cycle_id)
            self._link_decision(rb, eid)
            return res

        # success: patch spec.clusters, then record the *stored* generation in
        # status — two steps exactly like the reference (scheduler.go:664
        # patches spec, then patchBindingStatus reads the patched object's
        # Generation into SchedulerObservedGeneration).  Predicting the bump
        # inside one mutation would silently break idempotence if the store's
        # no-op/equality semantics ever changed.
        targets: List[TargetCluster] = res

        def patch_spec(obj: ResourceBinding) -> None:
            obj.spec.clusters = list(targets)

        try:
            stored = self.store.mutate(
                ResourceBinding.KIND, rb.namespace, rb.name, patch_spec
            )
        except AdmissionDenied as denial:
            # the FederatedQuotaEnforcement webhook (or any admission gate)
            # rejected the schedule-result patch: treat exactly like an
            # unschedulable outcome so the binding lands in the backoff/
            # unschedulable queue instead of crash-looping the cycle
            return self._apply_result(
                rb, serial.UnschedulableError(str(denial)), affinity_name
            )

        def patch_status(obj: ResourceBinding) -> None:
            obj.status.scheduler_observed_generation = stored.metadata.generation
            if affinity_name:
                obj.status.scheduler_observed_affinity_name = affinity_name
            obj.status.last_scheduled_time = __import__("time").time()
            set_condition(obj.status.conditions, Condition(
                type=COND_SCHEDULED, status="True", reason=REASON_SUCCESS,
            ))

        self.store.mutate(ResourceBinding.KIND, rb.namespace, rb.name, patch_status)
        where = ", ".join(f"{t.name}({t.replicas})" for t in targets)
        eid = self.recorder.event(
            rb, ev.TYPE_NORMAL, ev.REASON_SCHEDULE_BINDING_SUCCEED,
            "Binding has been scheduled successfully"
            + (f" to {where}." if where else "."),
            origin="scheduler", cycle_id=self._cycle_id)
        self._link_decision(rb, eid)
        return res


def _priority_of(rb: ResourceBinding) -> int:
    return rb.spec.schedule_priority or 0


def _is_scheduled_empty(rb: ResourceBinding) -> bool:
    """A successfully scheduled binding may legitimately have no targets
    (e.g. replicas=0 workload); the Scheduled condition disambiguates."""
    for c in rb.status.conditions:
        if c.type == COND_SCHEDULED and c.status == "True":
            return True
    return False


def _status_with_affinity(
    status: ResourceBindingStatus, name: str
) -> ResourceBindingStatus:
    import copy

    out = copy.deepcopy(status)
    out.scheduler_observed_affinity_name = name
    return out
