"""Out-of-tree scheduler plugin registry.

Reference: pkg/scheduler/framework/interface.go:45-66 (FilterPlugin /
ScorePlugin) + pkg/scheduler/framework/runtime/registry.go (named factory
registry) + cmd/scheduler app options `--plugins=*,-Foo` enablement.

TPU-first contract — deliberately narrower than the reference's
`Filter(ctx, bindingSpec, bindingStatus, cluster)`:

* Plugins are **placement-scoped**: `fn(placement, cluster)`.  Their
  outputs are per-(placement, cluster) ROWS, which is what lets one
  evaluation fold into every backend — the batched encoder's `pl_mask` /
  `pl_extra_score` tensors (one row per distinct placement, amortized over
  thousands of bindings), the serial control's filter/score chain, and the
  native C++ control's marshaled placement rows.  A spec-scoped plugin
  would force O(bindings x clusters) host work per cycle and could never
  ride the device path.
* Filter plugins return `None` (cluster passes) or a reason string (the
  per-cluster diagnosis, shown in FitError exactly like in-tree filters).
* Score plugins return an int; the registry SUMS enabled plugin scores per
  (placement, cluster) and clamps the total to [0, EXTRA_SCORE_CAP].  The
  clamp lives HERE so every backend composes the identical value (the
  solver's packed sort keys budget 8 bits for the score field: in-tree
  locality contributes 0 or 100, extras at most 100 more).

All three backends consult the SAME registry evaluation, so an
out-of-tree plugin behaves bit-identically on the serial, native and
device paths (asserted by tests/test_scheduler_plugins.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

EXTRA_SCORE_CAP = 100

FilterFn = Callable[[object, object], Optional[str]]  # (placement, cluster)
ScoreFn = Callable[[object, object], int]


class PluginRegistry:
    """Named filter/score contributor registry with `*,-Name` enablement
    (the reference registry's semantics: `*` enables everything, `-Name`
    disables one, a bare `Name` force-enables it)."""

    def __init__(self) -> None:
        self._filters: Dict[str, FilterFn] = {}
        self._scores: Dict[str, ScoreFn] = {}
        self._star = True
        self._on: set = set()
        self._off: set = set()
        self._lock = threading.Lock()
        # bumped on every mutation: encoder caches key their memoized
        # placement rows on this so a plugin change invalidates them
        self.generation = 0

    # -- registration ------------------------------------------------------
    def register_filter(self, name: str, fn: FilterFn) -> None:
        with self._lock:
            self._filters[name] = fn
            self.generation += 1

    def register_score(self, name: str, fn: ScoreFn) -> None:
        with self._lock:
            self._scores[name] = fn
            self.generation += 1

    def unregister(self, name: str) -> None:
        with self._lock:
            self._filters.pop(name, None)
            self._scores.pop(name, None)
            self.generation += 1

    def set_enablement(self, spec: str) -> None:
        """Parse the `--plugins=*,-Foo,Bar` flag format."""
        star, on, off = False, set(), set()
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if part == "*":
                star = True
            elif part.startswith("-"):
                off.add(part[1:])
            else:
                on.add(part)
        with self._lock:
            self._star, self._on, self._off = star, on, off
            self.generation += 1

    def _enabled(self, name: str) -> bool:
        if name in self._off:
            return False
        return self._star or name in self._on

    # -- evaluation (shared by serial / native / device encoders) ----------
    def enabled_filters(self) -> List[Tuple[str, FilterFn]]:
        with self._lock:
            return [(n, f) for n, f in self._filters.items()
                    if self._enabled(n)]

    def enabled_scores(self) -> List[Tuple[str, ScoreFn]]:
        with self._lock:
            return [(n, f) for n, f in self._scores.items()
                    if self._enabled(n)]

    def extra_filter(self, placement, cluster) -> Optional[str]:
        """First rejection reason among enabled out-of-tree filters, in
        registration order (mirrors the in-tree chain's first-hit-wins)."""
        return eval_filters(self.enabled_filters(), placement, cluster)

    def extra_score(self, placement, cluster) -> int:
        """Sum of enabled out-of-tree scores, clamped to
        [0, EXTRA_SCORE_CAP] — the single clamp every backend shares."""
        return eval_scores(self.enabled_scores(), placement, cluster)


def eval_filters(filters, placement, cluster) -> Optional[str]:
    """First rejection among pre-fetched (name, fn) filters — encoders
    hoist `enabled_filters()` once and evaluate O(placements x clusters)
    times without re-taking the registry lock."""
    for _, fn in filters:
        reason = fn(placement, cluster)
        if reason is not None:
            return reason
    return None


def eval_scores(scores, placement, cluster) -> int:
    """Clamped sum over pre-fetched (name, fn) scorers — THE clamp every
    backend shares."""
    total = 0
    for _, fn in scores:
        total += int(fn(placement, cluster))
    return max(0, min(total, EXTRA_SCORE_CAP))


# process-wide default instance; components accept an injected one in tests
REGISTRY = PluginRegistry()
