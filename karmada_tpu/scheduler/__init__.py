from karmada_tpu.scheduler.service import Scheduler  # noqa: F401
