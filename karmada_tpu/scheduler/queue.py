"""Three-queue scheduling queue: active / backoff / unschedulable.

Mirrors the reference's priority scheduling queue
(pkg/scheduler/internal/queue/scheduling_queue.go:127-372, active_queue.go:40,
types.go Less):

  * activeQ       — priority heap (priority desc, enqueue timestamp asc) of
                    bindings ready to schedule now;
  * backoffQ      — heap ordered by backoff expiry; failed attempts wait out
                    an exponential backoff (initial 1s doubling to max 10s,
                    calculateBackoffDuration :225) before re-entering activeQ;
  * unschedulable — map of bindings whose last attempt said "no capacity /
                    nothing will change until the cluster state does"; they
                    re-enter activeQ on a cluster event
                    (move_all_to_active_or_backoff) or after the leftover
                    flush interval (flushUnschedulableBindingsLeftover :252,
                    default 5min).

Failure routing matches scheduler.go:829-841 handleErr: UnschedulableError
-> unschedulable map; any other scheduling error (including FitError) ->
backoffQ.  Success -> forget.

Differences from the reference, by design:
  * pop_ready drains a *batch* (the whole point of the TPU path is to
    schedule many bindings per cycle); order within the drain is still
    (priority desc, timestamp asc).
  * no blocking Pop — the service runs tick-driven (store/worker.Runtime);
    flush_backoff()/flush_unschedulable() are called per tick instead of by
    1s/30s goroutines.  Wall-clock is injectable for deterministic tests.
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, Hashable, List, Optional, Tuple

DEFAULT_INITIAL_BACKOFF_S = 1.0
DEFAULT_MAX_BACKOFF_S = 10.0
DEFAULT_MAX_IN_UNSCHEDULABLE_S = 300.0


@dataclass
class QueuedBindingInfo:
    """types.go QueuedBindingInfo: key + priority + queue bookkeeping."""

    key: Hashable
    priority: int = 0
    timestamp: float = 0.0  # last time added to a queue
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None

    def _active_sort_key(self, seq: int) -> Tuple:
        # Less (types.go:182): priority desc, then timestamp asc
        return (-self.priority, self.timestamp, seq)


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_s: float = DEFAULT_INITIAL_BACKOFF_S,
        max_backoff_s: float = DEFAULT_MAX_BACKOFF_S,
        max_in_unschedulable_s: float = DEFAULT_MAX_IN_UNSCHEDULABLE_S,
        now: Callable[[], float] = _time.time,
    ) -> None:
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.max_in_unschedulable_s = max_in_unschedulable_s
        self.now = now
        self._seq = itertools.count()
        # heaps hold (sort_key..., key); staleness is resolved against the
        # authoritative _where map (lazy deletion)
        self._active_heap: List[Tuple] = []
        self._backoff_heap: List[Tuple] = []
        self._info: Dict[Hashable, QueuedBindingInfo] = {}
        self._where: Dict[Hashable, str] = {}  # key -> active|backoff|unschedulable
        # the expiry of the CURRENT backoff residence; a heap entry whose
        # expiry differs is stale (the key left and re-entered backoff)
        self._backoff_expiry: Dict[Hashable, float] = {}
        # dominant unschedulable reason per resident unschedulable key
        # (explain plane / classify_unschedulable taxonomy); dropped when
        # the key leaves the unschedulable map
        self._unsched_reason: Dict[Hashable, str] = {}

    # -- internals -----------------------------------------------------------
    def _move_to_active(self, info: QueuedBindingInfo) -> None:
        """moveToActiveQ (scheduling_queue.go:330): also removes the key from
        backoff/unschedulable (lazily, via _where)."""
        self._info[info.key] = info
        self._where[info.key] = "active"
        self._backoff_expiry.pop(info.key, None)
        self._unsched_reason.pop(info.key, None)
        heapq.heappush(
            self._active_heap, info._active_sort_key(next(self._seq)) + (info.key,)
        )

    def _backoff_duration(self, info: QueuedBindingInfo) -> float:
        """calculateBackoffDuration (:225): 0 for first attempt, then initial
        doubling per prior attempt, saturating at max."""
        if info.attempts == 0:
            return 0.0
        d = self.initial_backoff_s
        for _ in range(1, info.attempts):
            if d > self.max_backoff_s - d:
                return self.max_backoff_s
            d += d
        return d

    # -- producer side -------------------------------------------------------
    def push(self, key: Hashable, priority: int = 0) -> None:
        """Push (:276): external event -> activeQ, superseding any backoff /
        unschedulable residence."""
        prev = self._info.get(key)
        info = QueuedBindingInfo(
            key=key, priority=priority, timestamp=self.now(),
            attempts=prev.attempts if prev else 0,
            initial_attempt_timestamp=(
                prev.initial_attempt_timestamp if prev else None
            ),
        )
        self._move_to_active(info)

    def push_unschedulable_if_not_present(self, info: QueuedBindingInfo,
                                          reason: str = "") -> None:
        """:288 — no-op when the key already waits in active/backoff.
        `reason` is the dominant unschedulable reason (explain-plane /
        classify_unschedulable taxonomy); the map keeps it so operators
        can see WHY each resident binding is parked."""
        if self._where.get(info.key) in ("active", "backoff"):
            return
        info.timestamp = self.now()
        self._info[info.key] = info
        self._where[info.key] = "unschedulable"
        if reason:
            self._unsched_reason[info.key] = reason

    def push_backoff_if_not_present(self, info: QueuedBindingInfo) -> None:
        """:301 — no-op when the key already waits in active/unschedulable."""
        if self._where.get(info.key) in ("active", "unschedulable"):
            return
        info.timestamp = self.now()
        self._info[info.key] = info
        self._where[info.key] = "backoff"
        expiry = info.timestamp + self._backoff_duration(info)
        self._backoff_expiry[info.key] = expiry
        heapq.heappush(self._backoff_heap, (expiry, next(self._seq), info.key))

    def forget(self, key: Hashable) -> None:
        """:322 — scheduling finished (success or permanent); drop tracking."""
        self._info.pop(key, None)
        self._where.pop(key, None)
        self._backoff_expiry.pop(key, None)
        self._unsched_reason.pop(key, None)

    # -- consumer side -------------------------------------------------------
    def pop_ready(self, max_n: Optional[int] = None) -> List[QueuedBindingInfo]:
        """Drain up to max_n activeQ entries in (priority desc, ts asc) order.

        The batched analogue of ActiveQueue.Pop; popped entries leave the
        queue entirely (the cycle calls forget / push_* per result, which is
        the Done() of this tick-driven design).
        """
        out: List[QueuedBindingInfo] = []
        while self._active_heap and (max_n is None or len(out) < max_n):
            entry = heapq.heappop(self._active_heap)
            key = entry[-1]
            if self._where.get(key) != "active":
                continue  # stale heap entry
            info = self._info.pop(key)
            self._where.pop(key, None)
            if info.initial_attempt_timestamp is None:
                info.initial_attempt_timestamp = self.now()
            out.append(info)
        return out

    # -- periodic flushes ----------------------------------------------------
    def flush_backoff(self) -> int:
        """flushBackoffQCompleted (:195): expired backoff -> activeQ."""
        moved = 0
        now = self.now()
        while self._backoff_heap and self._backoff_heap[0][0] <= now:
            expiry, _, key = heapq.heappop(self._backoff_heap)
            if self._where.get(key) != "backoff":
                continue
            if expiry != self._backoff_expiry.get(key):
                continue  # stale entry from an earlier backoff residence
            self._move_to_active(self._info[key])
            moved += 1
        return moved

    def flush_unschedulable_leftover(self) -> int:
        """flushUnschedulableBindingsLeftover (:252): entries older than
        max_in_unschedulable_s -> activeQ."""
        now = self.now()
        stale = [
            k for k, w in self._where.items()
            if w == "unschedulable"
            and now - self._info[k].timestamp > self.max_in_unschedulable_s
        ]
        for k in stale:
            self._move_to_active(self._info[k])
        return len(stale)

    def move_all_to_active_or_backoff(self) -> int:
        """MoveAllToActiveOrBackoffQueue semantics: a cluster event may make
        unschedulable bindings schedulable; still-backing-off entries wait
        out their timer, others go active."""
        moved = 0
        for k in [k for k, w in self._where.items() if w == "unschedulable"]:
            info = self._info[k]
            expiry = info.timestamp + self._backoff_duration(info)
            if self.now() < expiry:
                self._where[k] = "backoff"
                self._backoff_expiry[k] = expiry
                self._unsched_reason.pop(k, None)
                heapq.heappush(self._backoff_heap, (expiry, next(self._seq), k))
            else:
                self._move_to_active(info)
            moved += 1
        return moved

    # -- introspection -------------------------------------------------------
    def depths(self) -> Dict[str, int]:
        counts = {"active": 0, "backoff": 0, "unschedulable": 0}
        for w in self._where.values():
            counts[w] += 1
        return counts

    def has(self, key: Hashable) -> bool:
        return key in self._where

    def unschedulable_reasons(self) -> Dict[str, int]:
        """Resident unschedulable keys bucketed by dominant reason (keys
        parked before reason accounting landed count as "unknown")."""
        counts: Dict[str, int] = {}
        for k, w in self._where.items():
            if w != "unschedulable":
                continue
            r = self._unsched_reason.get(k, "unknown")
            counts[r] = counts.get(r, 0) + 1
        return counts
