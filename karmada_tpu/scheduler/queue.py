"""Three-queue scheduling queue: active / backoff / unschedulable.

Mirrors the reference's priority scheduling queue
(pkg/scheduler/internal/queue/scheduling_queue.go:127-372, active_queue.go:40,
types.go Less):

  * activeQ       — priority heap (priority desc, enqueue timestamp asc) of
                    bindings ready to schedule now;
  * backoffQ      — heap ordered by backoff expiry; failed attempts wait out
                    an exponential backoff (initial 1s doubling to max 10s,
                    calculateBackoffDuration :225) before re-entering activeQ;
  * unschedulable — map of bindings whose last attempt said "no capacity /
                    nothing will change until the cluster state does"; they
                    re-enter activeQ on a cluster event
                    (move_all_to_active_or_backoff) or after the leftover
                    flush interval (flushUnschedulableBindingsLeftover :252,
                    default 5min).

Failure routing matches scheduler.go:829-841 handleErr: UnschedulableError
-> unschedulable map; any other scheduling error (including FitError) ->
backoffQ.  Success -> forget.

Differences from the reference, by design:
  * pop_ready drains a *batch* (the whole point of the TPU path is to
    schedule many bindings per cycle); order within the drain is still
    (priority desc, timestamp asc).
  * no blocking Pop — the service runs tick-driven (store/worker.Runtime);
    flush_backoff()/flush_unschedulable() are called per tick instead of by
    1s/30s goroutines.  Wall-clock is injectable for deterministic tests.
  * an optional bounded-resident admission gate (`max_resident`): under
    sustained overload the active queue would otherwise grow without
    bound and every binding's dwell with it.  When the gate is armed, a
    Push that would exceed the bound sheds — the LOWEST-priority resident
    active entry is displaced when the newcomer outranks it, else the
    newcomer itself is shed (it stays in the store; the next cluster
    event / resync re-offers it).  Every decision is counted in
    karmada_scheduler_admission_total{decision}.
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, Hashable, List, Optional, Tuple

from karmada_tpu.obs import events as obs_events
from karmada_tpu.scheduler import metrics as sched_metrics

DEFAULT_INITIAL_BACKOFF_S = 1.0
DEFAULT_MAX_BACKOFF_S = 10.0
DEFAULT_MAX_IN_UNSCHEDULABLE_S = 300.0

# admission decisions (karmada_scheduler_admission_total{decision}):
# every Push resolves to exactly one of ADMITTED / SHED, so
# admitted + shed == total Push calls (the accounting-exactness
# invariant the soak tests assert); DISPLACED counts evicted residents
# (a separate axis: each displacement also admits the newcomer)
ADMIT_ADMITTED = "admitted"
ADMIT_SHED = "shed"
ADMIT_DISPLACED = "displaced"


@dataclass
class QueuedBindingInfo:
    """types.go QueuedBindingInfo: key + priority + queue bookkeeping."""

    key: Hashable
    priority: int = 0
    timestamp: float = 0.0  # last time added to a queue
    attempts: int = 0
    initial_attempt_timestamp: Optional[float] = None
    # which queue this entry sat in before (re-)entering activeQ — the
    # dwell histogram buckets by it ("active": fresh external push,
    # "backoff"/"unschedulable": a flush re-admitted it)
    origin: str = "active"

    def _active_sort_key(self, seq: int) -> Tuple:
        # Less (types.go:182): priority desc, then timestamp asc
        return (-self.priority, self.timestamp, seq)


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_s: float = DEFAULT_INITIAL_BACKOFF_S,
        max_backoff_s: float = DEFAULT_MAX_BACKOFF_S,
        max_in_unschedulable_s: float = DEFAULT_MAX_IN_UNSCHEDULABLE_S,
        now: Callable[[], float] = _time.time,
        # bounded-resident admission gate: Push never grows the tracked
        # population (all three queues) beyond this; None disables
        # (unbounded, the pre-admission behavior).  Internal moves
        # between queues never consume a new slot, so the bound holds
        # across flushes.  Precise guarantee: the gate bounds ADMISSION
        # only — a cycle's failure re-adds (push_backoff/unschedulable_
        # if_not_present) and its gate-exempt result-patch echo pushes
        # re-enter unconditionally (entries popped before concurrent
        # pushes refilled their slots; the reference's retry semantics),
        # so the hard ceiling is max_resident + one in-flight batch
        # (<= batch_window).
        max_resident: Optional[int] = None,
    ) -> None:
        self.initial_backoff_s = initial_backoff_s
        self.max_backoff_s = max_backoff_s
        self.max_in_unschedulable_s = max_in_unschedulable_s
        self.max_resident = max_resident
        self.now = now
        self._seq = itertools.count()
        # heaps hold (sort_key..., key); staleness is resolved against the
        # authoritative _where map (lazy deletion)
        self._active_heap: List[Tuple] = []
        self._backoff_heap: List[Tuple] = []
        self._info: Dict[Hashable, QueuedBindingInfo] = {}
        # key -> active|backoff|unschedulable; mutate ONLY through
        # _set_where so the O(1) depth counters can never drift —
        # depths() runs per cycle AND per publisher-thread admission
        # check, and an O(n) scan there would hold _queue_lock for the
        # whole resident population on the hot path
        self._where: Dict[Hashable, str] = {}
        self._depths: Dict[str, int] = {"active": 0, "backoff": 0,
                                        "unschedulable": 0}
        # per-queue (entry-timestamp, key) min-heaps backing the oldest-
        # resident lookups (lazy deletion like _backoff_heap/_prio_heap):
        # oldest_ages()/oldest_active_age() run per cycle AND per 0.5s
        # tick, and an O(n) resident scan there would hold _queue_lock
        # against every publisher push.  Timestamps are monotone, so
        # stale entries surface at the head and the every-tick peek
        # cleans them promptly; _set_where compacts as a backstop.
        self._entry_heaps: Dict[str, List[Tuple]] = {
            "active": [], "backoff": [], "unschedulable": []}
        # lowest-priority-first heap over active residents (lazy deletion,
        # same discipline as _active_heap) — the shed victim lookup must
        # not scan the whole resident map on every overloaded Push
        self._prio_heap: List[Tuple] = []
        # the expiry of the CURRENT backoff residence; a heap entry whose
        # expiry differs is stale (the key left and re-entered backoff)
        self._backoff_expiry: Dict[Hashable, float] = {}
        # dominant unschedulable reason per resident unschedulable key
        # (explain plane / classify_unschedulable taxonomy); dropped when
        # the key leaves the unschedulable map
        self._unsched_reason: Dict[Hashable, str] = {}

    # -- internals -----------------------------------------------------------
    def _set_where(self, key: Hashable, state: Optional[str]) -> None:
        """The single _where mutation point, keeping the depth counters
        exact and the oldest-entry heaps fed (state None removes the
        key; callers store the entry's _info BEFORE transitioning so
        the heap records the current residence timestamp)."""
        old = self._where.get(key)
        if old is not None:
            self._depths[old] -= 1
        if state is None:
            self._where.pop(key, None)
        else:
            self._where[key] = state
            self._depths[state] += 1
            heap = self._entry_heaps[state]
            if len(heap) > 4 * max(len(self._where), 64):
                heap = [(self._info[k].timestamp, k)
                        for k, w in self._where.items() if w == state]
                heapq.heapify(heap)
                self._entry_heaps[state] = heap
            heapq.heappush(heap, (self._info[key].timestamp, key))

    def _oldest_entry_age(self, qname: str, now: float) -> float:
        """Age of `qname`'s oldest resident via its lazy entry heap —
        stale heads (key left the queue or re-entered with a newer
        timestamp) are popped on the way."""
        heap = self._entry_heaps[qname]
        while heap:
            ts, key = heap[0]
            info = self._info.get(key)
            if (self._where.get(key) != qname or info is None
                    or info.timestamp != ts):
                heapq.heappop(heap)  # stale entry
                continue
            return max(0.0, now - ts)
        return 0.0

    def _move_to_active(self, info: QueuedBindingInfo,
                        origin: str = "active") -> None:
        """moveToActiveQ (scheduling_queue.go:330): also removes the key from
        backoff/unschedulable (lazily, via _where).  `origin` names the
        queue the entry came from — pop_ready buckets its dwell by it."""
        info.origin = origin
        self._info[info.key] = info
        self._set_where(info.key, "active")
        self._backoff_expiry.pop(info.key, None)
        self._unsched_reason.pop(info.key, None)
        heapq.heappush(
            self._active_heap, info._active_sort_key(next(self._seq)) + (info.key,)
        )
        if self.max_resident is not None:
            # victim-lookup heap only exists while the gate is armed (an
            # unbounded queue never displaces); compaction below bounds
            # the stale entries lazy deletion leaves behind
            if len(self._prio_heap) > 4 * max(len(self._where), 64):
                self._prio_heap = [
                    (self._info[k].priority, i, k)
                    for i, (k, w) in enumerate(self._where.items())
                    if w == "active"
                ]
                heapq.heapify(self._prio_heap)
            heapq.heappush(self._prio_heap,
                           (info.priority, next(self._seq), info.key))

    def _lowest_priority_active(self) -> Optional[Hashable]:
        """The active resident with the lowest priority (oldest wins ties),
        via the lazy prio heap — the candidate a higher-priority arrival
        may displace under the admission gate."""
        while self._prio_heap:
            prio, _, key = self._prio_heap[0]
            info = self._info.get(key)
            if (self._where.get(key) != "active" or info is None
                    or info.priority != prio):
                heapq.heappop(self._prio_heap)  # stale entry
                continue
            return key
        return None

    def _backoff_duration(self, info: QueuedBindingInfo) -> float:
        """calculateBackoffDuration (:225): 0 for first attempt, then initial
        doubling per prior attempt, saturating at max."""
        if info.attempts == 0:
            return 0.0
        d = self.initial_backoff_s
        for _ in range(1, info.attempts):
            if d > self.max_backoff_s - d:
                return self.max_backoff_s
            d += d
        return d

    # -- producer side -------------------------------------------------------
    def push(self, key: Hashable, priority: int = 0,
             gate_exempt: bool = False, origin: str = "active") -> str:
        """Push (:276): external event -> activeQ, superseding any backoff /
        unschedulable residence.  Returns the admission decision:
        ADMIT_ADMITTED or ADMIT_SHED (the gate refused a NEW key; resident
        keys always re-admit — they already hold a slot).  A successful
        displacement admits the newcomer after forgetting the lowest-
        priority active resident (counted separately as ADMIT_DISPLACED).

        `gate_exempt` bypasses the admission check for a key whose slot
        was freed moments ago by its own pop in the CURRENT scheduling
        cycle (the scheduler's result-patch events re-push every
        scheduled binding): that bookkeeping echo must neither consume a
        fresh slot nor displace a genuinely-waiting resident.

        `origin` names the plane that produced this push ("active" for a
        plain external event; "rebalance"/"hpa" for the rebalance plane's
        drains and the FederatedHPA fast path) — pop_ready buckets the
        entry's queue dwell by it, so re-place latency is attributable."""
        prev = self._info.get(key)
        if (not gate_exempt
                and self.max_resident is not None and key not in self._where
                and len(self._where) >= self.max_resident):
            victim = self._lowest_priority_active()
            if victim is None or self._info[victim].priority >= priority:
                # per-priority shedding: a newcomer that does not outrank
                # the weakest resident is the one shed (equal priority
                # keeps the resident — no displacement thrash)
                sched_metrics.ADMISSION.inc(decision=ADMIT_SHED)
                obs_events.emit_key(
                    key, obs_events.TYPE_WARNING,
                    obs_events.REASON_BINDING_SHED,
                    f"admission gate full ({self.max_resident} resident): "
                    "shed without a queue slot", origin=origin)
                return ADMIT_SHED
            self.forget(victim)
            sched_metrics.ADMISSION.inc(decision=ADMIT_DISPLACED)
            obs_events.emit_key(
                victim, obs_events.TYPE_WARNING,
                obs_events.REASON_BINDING_DISPLACED,
                "displaced from the admission gate by a higher-priority "
                "arrival", origin=origin)
        info = QueuedBindingInfo(
            key=key, priority=priority, timestamp=self.now(),
            attempts=prev.attempts if prev else 0,
            initial_attempt_timestamp=(
                prev.initial_attempt_timestamp if prev else None
            ),
        )
        self._move_to_active(info, origin=origin)
        sched_metrics.ADMISSION.inc(decision=ADMIT_ADMITTED)
        if not gate_exempt:
            # the lifecycle ledger's admission record: every EXTERNAL
            # push lands one (coalescing) timeline entry — the
            # scheduler's own result-patch echoes are bookkeeping, not
            # lifecycle, and stay silent
            obs_events.emit_key(
                key, obs_events.TYPE_NORMAL,
                obs_events.REASON_BINDING_ENQUEUED,
                f"enqueued to the active queue (origin={origin})",
                origin=origin)
        return ADMIT_ADMITTED

    def push_unschedulable_if_not_present(self, info: QueuedBindingInfo,
                                          reason: str = "") -> None:
        """:288 — no-op when the key already waits in active/backoff.
        `reason` is the dominant unschedulable reason (explain-plane /
        classify_unschedulable taxonomy); the map keeps it so operators
        can see WHY each resident binding is parked."""
        if self._where.get(info.key) in ("active", "backoff"):
            return
        info.timestamp = self.now()
        self._info[info.key] = info
        self._set_where(info.key, "unschedulable")
        if reason:
            self._unsched_reason[info.key] = reason

    def push_backoff_if_not_present(self, info: QueuedBindingInfo) -> None:
        """:301 — no-op when the key already waits in active/unschedulable."""
        if self._where.get(info.key) in ("active", "unschedulable"):
            return
        info.timestamp = self.now()
        self._info[info.key] = info
        self._set_where(info.key, "backoff")
        expiry = info.timestamp + self._backoff_duration(info)
        self._backoff_expiry[info.key] = expiry
        heapq.heappush(self._backoff_heap, (expiry, next(self._seq), info.key))

    def forget(self, key: Hashable) -> None:
        """:322 — scheduling finished (success or permanent); drop tracking."""
        self._info.pop(key, None)
        self._set_where(key, None)
        self._backoff_expiry.pop(key, None)
        self._unsched_reason.pop(key, None)

    # -- consumer side -------------------------------------------------------
    def pop_ready(self, max_n: Optional[int] = None) -> List[QueuedBindingInfo]:
        """Drain up to max_n activeQ entries in (priority desc, ts asc) order.

        The batched analogue of ActiveQueue.Pop; popped entries leave the
        queue entirely (the cycle calls forget / push_* per result, which is
        the Done() of this tick-driven design).
        """
        out: List[QueuedBindingInfo] = []
        now = self.now()
        while self._active_heap and (max_n is None or len(out) < max_n):
            entry = heapq.heappop(self._active_heap)
            key = entry[-1]
            if self._where.get(key) != "active":
                continue  # stale heap entry
            info = self._info.pop(key)
            self._set_where(key, None)
            if info.initial_attempt_timestamp is None:
                info.initial_attempt_timestamp = now
            # queue dwell: time since this entry entered its CURRENT
            # residence (timestamp is stamped on every queue entry),
            # bucketed by the queue it came from — backoff/unschedulable
            # dwell includes the parked wait, exactly what starvation
            # analysis needs
            sched_metrics.QUEUE_DWELL.observe(
                max(0.0, now - info.timestamp), queue=info.origin)
            out.append(info)
        return out

    # -- periodic flushes ----------------------------------------------------
    def flush_backoff(self) -> int:
        """flushBackoffQCompleted (:195): expired backoff -> activeQ."""
        moved = 0
        now = self.now()
        while self._backoff_heap and self._backoff_heap[0][0] <= now:
            expiry, _, key = heapq.heappop(self._backoff_heap)
            if self._where.get(key) != "backoff":
                continue
            if expiry != self._backoff_expiry.get(key):
                continue  # stale entry from an earlier backoff residence
            self._move_to_active(self._info[key], origin="backoff")
            moved += 1
        return moved

    def flush_unschedulable_leftover(self) -> int:
        """flushUnschedulableBindingsLeftover (:252): entries older than
        max_in_unschedulable_s -> activeQ."""
        now = self.now()
        stale = [
            k for k, w in self._where.items()
            if w == "unschedulable"
            and now - self._info[k].timestamp > self.max_in_unschedulable_s
        ]
        for k in stale:
            self._move_to_active(self._info[k], origin="unschedulable")
        return len(stale)

    def move_all_to_active_or_backoff(self) -> int:
        """MoveAllToActiveOrBackoffQueue semantics: a cluster event may make
        unschedulable bindings schedulable; still-backing-off entries wait
        out their timer, others go active."""
        moved = 0
        for k in [k for k, w in self._where.items() if w == "unschedulable"]:
            info = self._info[k]
            expiry = info.timestamp + self._backoff_duration(info)
            if self.now() < expiry:
                self._set_where(k, "backoff")
                self._backoff_expiry[k] = expiry
                self._unsched_reason.pop(k, None)
                heapq.heappush(self._backoff_heap, (expiry, next(self._seq), k))
            else:
                self._move_to_active(info, origin="unschedulable")
            moved += 1
        return moved

    # -- introspection -------------------------------------------------------
    def depths(self) -> Dict[str, int]:
        """O(1): the incrementally-maintained per-queue counters (every
        _where transition goes through _set_where)."""
        return dict(self._depths)

    def oldest_active_age(self) -> float:
        """Age (seconds on the injected clock) of the oldest activeQ
        resident — the batch-formation deadline input: the cycle cuts when
        this exceeds the deadline even if the batch is not yet full.
        O(log n) amortized via the lazy entry heap, never a resident
        scan (this runs under _queue_lock on the cycle hot path)."""
        return self._oldest_entry_age("active", self.now())

    def oldest_ages(self) -> Dict[str, float]:
        """Per-queue oldest-resident age — exported as the
        karmada_scheduler_queue_oldest_age_seconds gauges so starvation is
        visible on a dashboard before any soak report runs.  Same lazy-
        heap cost profile as oldest_active_age."""
        now = self.now()
        return {q: self._oldest_entry_age(q, now)
                for q in ("active", "backoff", "unschedulable")}

    def has(self, key: Hashable) -> bool:
        return key in self._where

    def unschedulable_reasons(self) -> Dict[str, int]:
        """Resident unschedulable keys bucketed by dominant reason (keys
        parked before reason accounting landed count as "unknown")."""
        counts: Dict[str, int] = {}
        for k, w in self._where.items():
            if w != "unschedulable":
                continue
            r = self._unsched_reason.get(k, "unknown")
            counts[r] = counts.get(r, 0) + 1
        return counts
