"""Pipelined chunk executor: the ONE scheduling hot loop.

bench.py demonstrated the winning dispatch shape — split a cycle into
fixed-size chunks, dispatch chunk k's compact device solve asynchronously
(ops/solver.dispatch_compact), and overlap the host encode of chunk k+1
plus the finalize/decode of chunk k-1 with the device execution of chunk
k — but the production scheduler (scheduler/service._solve_device) still
encoded whole cycles into one monolithic batch and blocked on a single
dispatch.  This module extracts that loop into the shared subsystem both
drive: the benchmarked path IS the production path.

Stages per chunk (classic 3-deep software pipeline):

  Encode   (host)    items[lo:hi] -> SolverBatch via the cycle-shared
                     EncoderCache (tensors.encode_batch)
  Dispatch (async)   dispatch_compact enqueues the fused device solve and
                     returns immediately
  Finalize (host)    spread/big sub-solves, device wait, sparse D2H,
                     decode_compact -> per-binding results

While the device crunches chunk k the host finalizes chunk k-1 and
encodes chunk k+1 — host work (encode, DFS, COO decode) hides behind
device work instead of strictly alternating with it.

Carry threading (`carry=True`): the consumed-capacity accumulators
(solve_compact's with_used/used0) chain chunk-to-chunk so pricing stays
sequential-equivalent at chunk granularity — the main solve of chunk k+1
prices against the snapshot minus everything chunks <= k consumed.  The
chain is DEVICE-SIDE: chunk k+1's used0 operands are chunk k's live
used-out arrays (solver.dispatched_used), so threading costs no host
synchronization while consecutive chunks share an encoding vocabulary;
a vocabulary change remaps on device when lossless (old resource/class
keys all present in the new vocabulary) and otherwise closes the chain
segment through a host-side name-keyed CarryState (tensors.CarryState),
so consumption of a resource absent from an intermediate chunk's
vocabulary still reaches a later chunk that prices it.  With
`carry_spread=False` the spread/big sub-solves price against the raw
snapshot exactly like the pre-pipeline scheduler; `carry_spread=True`
(what both the scheduler's multi-chunk cycles and the bench's --carry
mode use) additionally hands each chunk's carry-in to the spread and
big-tier assignment kernels and folds those bindings' own consumption
back into the chain at the next dispatch boundary — as lazy device-side
adds when the pending contributions fit the next chunk's vocabulary, so
the pipeline stays overlapped; the documented divergence from fully
sequential accounting is a one-chunk lag (the sub-solve consumption of
chunk k is only known at its finalize, after chunk k+1 dispatched).

Cancellation: `cancelled` (the mid-serve degradation guard's event) gates
every stage boundary and every shared-state write — metrics observations
and the on_chunk callback are suppressed, in-flight work is abandoned,
and the partial result is returned for the caller to discard.  An
abandoned cycle that unblocks minutes later must not pollute the live
histograms (scheduler/service._solve_device_guarded).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from karmada_tpu import chaos as chaos_mod
from karmada_tpu import obs
from karmada_tpu.obs import decisions as obs_decisions
from karmada_tpu.ops import tensors
from karmada_tpu.scheduler import metrics as sm

#: routes whose results the device path owns; everything else falls back
#: to the serial host pipeline exactly as before
DEVICE_ROUTES = (
    tensors.ROUTE_DEVICE,
    tensors.ROUTE_DEVICE_SPREAD,
    tensors.ROUTE_DEVICE_SPREAD_BIG,
    tensors.ROUTE_DEVICE_BIG,
)


@dataclass
class ChunkStats:
    """Per-chunk measurement handed to on_chunk after its finalize."""

    index: int          # chunk ordinal within the cycle
    offset: int         # first item's index
    n: int              # bindings in the chunk
    n_ok: int           # device-owned rows scheduled successfully
    failures: Dict[str, int]  # device-owned failures by exception class
    encode_s: float
    solve_s: float      # sub-solves + device wait + sparse D2H
    decode_s: float
    own_s: float        # the chunk's OWN work: encode span + finalize span
    wall_s: float       # submit-to-result (contains pipeline overlap)


@dataclass
class PipelineResult:
    """Aggregate outcome of one run_pipeline call."""

    results: Dict[int, object] = field(default_factory=dict)  # global index
    scheduled: int = 0
    failures: Dict[str, int] = field(default_factory=dict)
    chunk_own: List[float] = field(default_factory=list)
    chunk_wall: List[float] = field(default_factory=list)
    solve_s: float = 0.0
    chunks: int = 0          # finalized (skipped chunks excluded)
    cancelled: bool = False  # the guard fired mid-cycle; results are partial
    # the run's cumulative consumed-capacity store (collect_carry=True,
    # carry on, not cancelled): seed carry_state + every chunk's own
    # consumption, keyed by resource name / class key in the FULL
    # cluster vocabulary — the incremental plane's ledger transport
    carry: Optional["tensors.CarryState"] = None


class _CarryChain:
    """Chunk-to-chunk consumed-capacity threading.

    Invariant: the latest dispatched handle's used-out equals the
    cumulative consumption of every chunk dispatched so far, rendered in
    the open segment's vocabulary, PLUS the segment base (everything
    absorbed before the segment opened).  `total` holds closed segments
    keyed by resource name / class key; `extras` holds spread
    contributions pending fold (carry_spread mode)."""

    def __init__(self) -> None:
        self.total = tensors.CarryState()
        self.extras = tensors.CarryState()
        # open segment: [sig, batch, base_np(tuple), handle|None]
        self._seg: Optional[list] = None

    @staticmethod
    def _sig(batch) -> tuple:
        # sub_sig joins the signature: two shortlisted sub-vocabulary
        # batches (ops/shortlist) can share every shape while holding
        # DIFFERENT cluster lane sets — chaining their live device
        # accumulators would misalign lanes silently
        return (batch.C, tuple(batch.res_names), tuple(batch.class_keys),
                batch.est_override.shape[0], batch.avail_milli.shape[1],
                getattr(batch, "sub_sig", None))

    @staticmethod
    def _subset(from_batch, to_batch) -> bool:
        """True when a device-side remap from_batch -> to_batch is
        lossless: every accumulator key of the source vocabulary exists
        in the target's (nothing to drop).  Sub-vocabulary batches only
        remap on-device within ONE lane set; crossing lane sets goes
        through the keyed store (CarryState renders across the remap)."""
        return (from_batch.C == to_batch.C
                and (getattr(from_batch, "sub_sig", None)
                     == getattr(to_batch, "sub_sig", None))
                and set(from_batch.res_names) <= set(to_batch.res_names)
                and set(from_batch.class_keys) <= set(to_batch.class_keys))

    def _extras_fit(self, batch) -> bool:
        """True when the pending extras render losslessly into batch's
        vocabulary (they can ride the device chain instead of forcing a
        segment close)."""
        return (set(self.extras.milli) <= set(batch.res_names)
                and set(self.extras.sets) <= set(batch.class_keys))

    @staticmethod
    def _device_remap(used, from_batch, to_batch):
        """Re-key live device accumulators into to_batch's vocabulary
        without materializing them (lazy jnp gathers — the chain stays
        async).  Caller guarantees _subset(from_batch, to_batch)."""
        import jax.numpy as jnp

        um, up, us = used
        r_src = {n: i for i, n in enumerate(from_batch.res_names)}
        R2 = to_batch.avail_milli.shape[1]
        idx_r = np.zeros(R2, np.int64)
        ok_r = np.zeros(R2, bool)
        for r2, name in enumerate(to_batch.res_names):
            src = r_src.get(name)
            if src is not None:
                idx_r[r2], ok_r[r2] = src, True
        um2 = jnp.where(ok_r[None, :], jnp.take(um, idx_r, axis=1), 0)
        q_src = {k: i for i, k in enumerate(from_batch.class_keys)}
        Q2 = to_batch.est_override.shape[0]
        idx_q = np.zeros(Q2, np.int64)
        ok_q = np.zeros(Q2, bool)
        for q2, key in enumerate(to_batch.class_keys):
            src = q_src.get(key)
            if src is not None:
                idx_q[q2], ok_q[q2] = src, True
        us2 = jnp.where(ok_q[:, None], jnp.take(us, idx_q, axis=0), 0)
        return um2, up, us2

    def _close(self) -> None:
        """Materialize the open segment's cumulative consumption into the
        keyed store (host sync on the segment's last dispatched solve)."""
        if self._seg is None:
            return
        _sig, batch, base, handle = self._seg
        self._seg = None
        if handle is None:
            return  # segment opened but nothing dispatched: base unchanged
        from karmada_tpu.ops.solver import dispatched_used

        used = tuple(np.asarray(u) for u in dispatched_used(handle))
        self.total.absorb(batch, used, base)

    def carry_in(self, batch):
        """The used0 operand tuple for this chunk's dispatch.  Always a
        3-tuple of arrays (zeros when nothing consumed yet) so every
        dispatch shares ONE jit signature."""
        from karmada_tpu.ops.solver import dispatched_used

        sig = self._sig(batch)
        seg = self._seg
        if seg is not None and seg[3] is not None and (
                self.extras.empty() or self._extras_fit(batch)):
            used = None
            if seg[0] == sig:
                # fast path: chain the live device arrays, no host sync
                used = dispatched_used(seg[3])
            elif self._subset(seg[1], batch):
                # lossless vocabulary growth: re-key on device (async),
                # re-base the segment in the new vocabulary
                used = self._device_remap(
                    dispatched_used(seg[3]), seg[1], batch)
                base = tensors.remap_used(seg[2], seg[1], batch)
                self._seg = [sig, batch, base, None]
            if used is not None:
                if not self.extras.empty():
                    # pending sub-solve contributions ride the chain from
                    # here: lazy device adds, no host sync; they reach the
                    # keyed store at segment close via (used_out - base)
                    extra = self.extras.used0_for(batch)
                    used = tuple(u + e for u, e in zip(used, extra))
                    self.extras = tensors.CarryState()
                return used
        # slow path (genuinely lossy vocabulary shrink): segment close
        # (host sync) + keyed re-render; pending contributions retire
        # into the cumulative store here
        self._close()
        if not self.extras.empty():
            self.total.merge(self.extras)
            self.extras = tensors.CarryState()
        base = self.total.used0_for(batch)
        self._seg = [sig, batch, base, None]
        return base

    def dispatched(self, batch, handle) -> None:
        """Advance the open segment to this chunk's handle."""
        if self._seg is None or self._seg[0] != self._sig(batch):
            # carry_in opened/rebased the segment for this batch already;
            # reaching here means it was never called (programming error)
            raise AssertionError("dispatched() without a carry_in() segment")
        self._seg[3] = handle

    def snapshot(self) -> "tensors.CarryState":
        """The cumulative consumption of every chunk dispatched so far, as
        a fresh keyed store in the FULL vocabulary — WITHOUT closing the
        open segment (the chain keeps pipelining).  Forces a host sync on
        the segment's last dispatched solve; callers use it sparingly
        (the shortlist truncation residual, the collect_carry epilogue)."""
        out = self.total.copy()
        if not self.extras.empty():
            out.merge(self.extras)
        if self._seg is not None and self._seg[3] is not None:
            from karmada_tpu.ops.solver import dispatched_used

            _sig, batch, base, handle = self._seg
            used = tuple(np.asarray(u) for u in dispatched_used(handle))
            out.absorb(batch, used, base)
        return out


def _record_decisions(recorder, batch, part, offset, keys, out_local,
                      expl_planes, sp_expl, cyc, live: bool) -> None:
    """Turn one finalized chunk's explain planes into Decision records.

    Main-path rows (ROUTE_DEVICE) decode from the dense planes, spread
    rows from their callback slices, and everything else the device owns
    (big tier, group-DFS failures) gets an outcome-level decision from
    its result object.  Dominant unschedulable reasons are attached to
    the result exceptions either way (`exc.reason` — the queue's
    unschedulable map and karmada_schedule_unschedulable_total read it),
    but nothing is RECORDED for a cancelled cycle."""
    names = batch.cluster_index.names
    nc = batch.n_clusters
    tid = (cyc.trace.trace_id
           if cyc is not None and getattr(cyc, "trace", None) is not None
           else None)

    def key_of(i: int) -> str:
        if keys is not None:
            return keys[offset + i]
        return obs_decisions.default_key(part[i][0])

    def attach_reason(res, outcome_code) -> None:
        _st, dom = obs_decisions.split_outcome(int(outcome_code))
        if dom is not None and isinstance(res, Exception):
            res.reason = dom

    covered = set()
    if expl_planes is not None:
        verdict, score, avail, outcome = expl_planes
        for i in range(len(part)):
            if batch.route[i] != tensors.ROUTE_DEVICE:
                continue
            res_i = out_local.get(i)
            attach_reason(res_i, outcome[i])
            covered.add(i)
            if not live:
                continue
            pid = int(batch.placement_id[i])
            recorder.record(obs_decisions.decision_from_planes(
                key_of(i), names, verdict[i, :nc], score[i, :nc],
                avail[i, :nc], int(outcome[i]), res_i, trace_id=tid,
                backend="device",
                static_w_row=batch.pl_static_w[pid, :nc],
                plugin_row=batch.pl_extra_score[pid, :nc]))
    for b, (vrow, srow, arow, oc) in sp_expl.items():
        res_b = out_local.get(b)
        attach_reason(res_b, oc)
        covered.add(b)
        if not live:
            continue
        pid = int(batch.placement_id[b])
        recorder.record(obs_decisions.decision_from_planes(
            key_of(b), names, vrow, srow, arow, oc, res_b, trace_id=tid,
            backend="device-spread",
            static_w_row=batch.pl_static_w[pid, :nc],
            plugin_row=batch.pl_extra_score[pid, :nc]))
    if not live:
        return
    for i, r in out_local.items():
        if i not in covered:
            # big lane tier / group-DFS failures: outcome-level record
            recorder.record(obs_decisions.decision_from_result(
                key_of(i), r, nc, trace_id=tid, backend="device-big"))


def _chaos_d2h(batch, idx, val, status, chunk_index: int) -> None:
    """The device.d2h chaos seam, applied to the finalized COO planes.
    `raise` fails the chunk outright; `poison` corrupts a COPY of the
    index plane and runs it through the d2h invariant guard
    (analysis/guards.check_d2h) — proving a poisoned result surfaces as
    a loud InvariantViolation, never as a silently wrong placement."""
    f = chaos_mod.fire(chaos_mod.SITE_DEVICE_D2H, chunk=chunk_index)
    if f is None:
        return
    if f.mode == "poison":
        poisoned = np.array(idx)
        if poisoned.size:
            from karmada_tpu.analysis import guards

            dense_nnz = int(batch.B) * int(batch.C)
            poisoned.flat[0] = dense_nnz + 7  # out of [-1, dense_nnz)
            guards.check_d2h(poisoned, np.asarray(val),
                             np.asarray(status), dense_nnz,
                             where="chaos-d2h")
            # check_d2h MUST have raised; reaching here means the guard
            # stopped guarding — fail the chunk loudly either way
    raise chaos_mod.ChaosFault(chaos_mod.SITE_DEVICE_D2H, f.mode)


@dataclass
class _InFlight:
    """A dispatched, not-yet-finalized chunk."""

    index: int
    offset: int
    part: Sequence
    batch: object
    handle: Optional[tuple]  # None: the chunk had no compact-solve rows
    used0: Optional[tuple]   # the dispatch's carry-in operands
    t_submit: float
    encode_s: float
    span: object = None      # the chunk's wall span (None: tracing off)
    # shortlist truncation residual (ops/shortlist): chunk-local row
    # indices solved per-binding at full dense width in finalize, plus
    # the full-vocabulary carry snapshot they price against (the chunk's
    # own used0 lives in the SUB vocabulary — lossy for lanes outside
    # the union, which a full-width residual row does consult)
    residual: List[int] = field(default_factory=list)
    resid_used0: object = None


def run_pipeline(
    items: Sequence[Tuple],
    cindex: "tensors.ClusterIndex",
    estimator,
    *,
    chunk: int,
    waves: int = 8,
    cache: Optional["tensors.EncoderCache"] = None,
    carry: bool = True,
    carry_spread: bool = False,
    enable_empty_workload_propagation: bool = False,
    cancelled: Optional[threading.Event] = None,
    skip: Optional[Callable[[int], bool]] = None,
    on_chunk: Optional[Callable[[ChunkStats], None]] = None,
    collect: bool = True,
    diagnose: bool = True,
    explain: Optional["obs_decisions.DecisionRecorder"] = None,
    keys: Optional[Sequence[str]] = None,
    encode: Optional[Callable[[Sequence, int, bool], object]] = None,
    shortlist=None,
    carry_state: Optional["tensors.CarryState"] = None,
    collect_carry: bool = False,
) -> PipelineResult:
    """Schedule `items` (a cycle of (spec, status) pairs) through the
    pipelined chunk executor.  Returns a PipelineResult whose `results`
    map {global item index -> List[TargetCluster] | Exception} for every
    binding a device tier owns (DEVICE_ROUTES); host-routed rows are
    absent — the caller's serial fallback owns them, exactly like the
    pre-pipeline _solve_device contract.

    chunk/waves: chunk size and capacity-contention waves per chunk.
    carry: thread the consumed-capacity accumulators chunk to chunk (see
      module docstring).  Incompatible with `skip` (a skipped chunk's
      consumption would vanish from the accounting).
    carry_spread: additionally run the bench's --carry spread accounting
      (spread sub-solves receive the chunk's carry-in and contribute
      their consumption back, one-chunk lag).
    cancelled: degradation-guard event; gates every stage boundary and
      every shared-state write.
    skip(ci): chunks to leave untouched (bench checkpoint resume) — no
      encode, no stats, no results.
    on_chunk(stats): called (live only) after each chunk's finalize.
    collect: build the global results dict (the scheduler needs it; the
      bench only aggregates counts and turns it off to keep 100k-binding
      runs out of memory).
    diagnose: rebuild full per-cluster FitError diagnosis for kernel
      FIT_ERROR rows (scheduler on; bench off — it only counts classes).
    explain: a DecisionRecorder (obs/decisions) arming the explain plane:
      chunks encode + dispatch the explain jit variant, the per-binding
      verdict/score/avail/outcome planes are decoded into Decision records
      at finalize (linked to this cycle's trace id), and unschedulable
      results get their dominant reason attached (`exc.reason`).  Main-
      path and spread-path rows carry full per-cluster verdict tables;
      big-tier rows record outcome-level decisions.  None (the default)
      leaves every jit signature and transfer byte-identical to today.
    keys: per-item binding identities ("namespace/name") for the decision
      records; derived from each spec's workload reference when omitted.
    encode: chunk encoder override `encode(part, offset, explain) ->
      SolverBatch` — the resident-state plane (karmada_tpu/resident)
      substitutes its gather-plus-miss-re-encode here; the default is a
      plain tensors.encode_batch against `cindex`/`cache`.  The returned
      batch must be semantically identical to a fresh full encode (the
      resident plane's parity audit enforces exactly that contract).
    shortlist: an ops/shortlist.ShortlistConfig arming the hierarchical
      two-tier solve — chunks at/above its cell threshold run the tier-1
      candidate kernel and dispatch the existing solver over the
      candidate-union sub-vocabulary (bit-exact when covered; loud dense
      fallback otherwise).  None (default) keeps every chunk dense.
      Rows the shortlist truncates out (eligible set beyond k_max) come
      back as per-binding dense residual solves in the chunk's finalize
      — exact at waves=1, so truncation only arms there.
    carry_state: seed the carry chain with consumption carried in from a
      PREVIOUS run (requires carry=True) — the incremental plane's
      ledger: every chunk prices against snapshot minus this seed minus
      in-run consumption.  The seed object is not mutated.
    collect_carry: return the run's cumulative consumption (seed + every
      chunk's own) as PipelineResult.carry — costs one host sync on the
      last dispatched solve at the end of the run.
    """
    from karmada_tpu.ops.solver import (
        dispatch_compact,
        finalize_compact,
        solve_big,
        solve_rows,
        wait_compact,
    )
    from karmada_tpu.ops.spread import solve_spread

    res = PipelineResult()
    n = len(items)
    if n == 0:
        return res
    assert chunk > 0, "chunk size must be positive"
    assert not (carry and skip is not None), \
        "carry threading is incompatible with chunk skipping (resume)"
    cache = cache if cache is not None else tensors.EncoderCache()
    keep_sel = enable_empty_workload_propagation
    chain = _CarryChain() if carry else None
    assert carry_state is None or chain is not None, \
        "carry_state seeding requires carry=True"
    if chain is not None and carry_state is not None:
        # merge copies every array on first insert: the caller's seed
        # object stays untouched however the chain mutates its store
        chain.total.merge(carry_state)
    carry_label = "on" if carry else "off"
    from karmada_tpu.ops import meshing

    mesh_plan = meshing.active()  # None: single-device dispatch, as before
    # flight recorder: one pipeline.cycle span (child of the ambient
    # scheduler.cycle span when the service drives us, a fresh root when
    # the bench does); traced is the ONE guard every per-chunk call site
    # checks so the disabled path allocates no spans at all
    tracer = obs.TRACER
    traced = tracer.enabled
    cyc = (tracer.start_span(obs.SPAN_PIPELINE, items=n, chunk=chunk,
                             waves=waves, carry=carry,
                             **({"mesh": mesh_plan.shape_str,
                                 "mesh_devices": mesh_plan.n_devices}
                                if mesh_plan is not None else {}))
           if traced else None)

    def live() -> bool:
        return cancelled is None or not cancelled.is_set()

    armed = explain is not None

    def finalize(entry: _InFlight) -> None:
        batch, part = entry.batch, entry.part
        ch_span = entry.span
        # spread-path explain rows land here via solve_spread's callback
        sp_expl: Dict[int, tuple] = {}

        def sp_cb(b, vrow, srow, arow, oc):
            sp_expl[b] = (vrow, srow, arow, oc)

        def stage(name):
            # stage spans parent on the chunk's wall span, NOT the ambient
            # context: chunks interleave (k+1 encodes before k finalizes),
            # so contextvar nesting would mis-parent across chunks
            return (tracer.start_span(name, parent=ch_span)
                    if ch_span is not None else None)

        t1 = time.perf_counter()
        sub: Dict[int, object] = {}
        # sub-solves FIRST: they need no main result, and for a single
        # chunk this reproduces the pre-pipeline overlap (host DFS runs
        # while the device crunches the main dispatch)
        spread_groups = tensors.spread_groups(batch, part)
        big_idx = [
            i for i in range(len(part))
            if batch.route[i] == tensors.ROUTE_DEVICE_BIG
        ]
        used0_np = None
        if (carry_spread and chain is not None and entry.used0 is not None
                and (spread_groups or big_idx)):
            # the chunk's carry-in; its producer solve finished before
            # this chunk's (data dependency), so this never stalls
            used0_np = tuple(np.asarray(u) for u in entry.used0)
        if spread_groups:
            t_sp = time.perf_counter()
            sp_span = stage(obs.SPAN_SPREAD)
            for (axis, tier), idxs in spread_groups.items():
                if used0_np is not None:
                    res_g, used_sp = solve_spread(
                        batch, part, idxs, waves=waves,
                        enable_empty_workload_propagation=keep_sel,
                        collect_used=True, used0=used0_np,
                        axis=axis, tier=tier,
                        explain=armed, explain_cb=sp_cb if armed else None,
                    )
                    if used_sp is not None:
                        chain.extras.absorb(batch, used_sp, used0_np)
                else:
                    res_g = solve_spread(
                        batch, part, idxs, waves=waves,
                        enable_empty_workload_propagation=keep_sel,
                        axis=axis, tier=tier,
                        explain=armed, explain_cb=sp_cb if armed else None,
                    )
                sub.update(res_g)
            if sp_span is not None:
                sp_span.end(groups=len(spread_groups))
            if live():
                sm.STEP_LATENCY.observe(
                    time.perf_counter() - t_sp, schedule_step=sm.STEP_SOLVE)
        if big_idx:
            t_big = time.perf_counter()
            big_span = stage(obs.SPAN_BIG)
            if used0_np is not None:
                big_res, big_used = solve_big(
                    part, big_idx, cindex, estimator, cache, waves=waves,
                    enable_empty_workload_propagation=keep_sel,
                    collect_used=True, used0=used0_np, from_batch=batch,
                )
                if big_used is not None:
                    sub_batch, used_out, used0_sub = big_used
                    chain.extras.absorb(sub_batch, used_out, used0_sub)
            else:
                big_res = solve_big(
                    part, big_idx, cindex, estimator, cache, waves=waves,
                    enable_empty_workload_propagation=keep_sel,
                )
            sub.update(big_res)
            if big_span is not None:
                big_span.end(rows=len(big_idx))
            if live():
                sm.STEP_LATENCY.observe(
                    time.perf_counter() - t_big, schedule_step=sm.STEP_SOLVE)
        if entry.residual:
            # shortlist truncation residual: the rows whose eligible set
            # outgrew k_max, solved per-binding at FULL dense width
            # against the chunk's starting consumption (exact at
            # waves=1 — within a chunk, rows never see each other).
            # Their results override the sub-solve's invalidated rows
            # via the out_local.update(sub) below.
            t_rs = time.perf_counter()
            if chain is not None and entry.resid_used0 is not None:
                r_out, r_used = solve_rows(
                    part, entry.residual, cindex, estimator, cache,
                    route=tensors.ROUTE_DEVICE, waves=waves,
                    enable_empty_workload_propagation=keep_sel,
                    collect_used=True, used0=entry.resid_used0,
                )
                if r_used is not None:
                    r_batch, r_used_out, r_used0 = r_used
                    chain.extras.absorb(r_batch, r_used_out, r_used0)
            else:
                r_out = solve_rows(
                    part, entry.residual, cindex, estimator, cache,
                    route=tensors.ROUTE_DEVICE, waves=waves,
                    enable_empty_workload_propagation=keep_sel,
                )
            sub.update(r_out)
            if live():
                sm.STEP_LATENCY.observe(
                    time.perf_counter() - t_rs, schedule_step=sm.STEP_SOLVE)
        decode_s = 0.0
        out_local: Dict[int, object] = {}
        expl_planes = None
        if entry.handle is not None:
            t_w = time.perf_counter()
            w_span = stage(obs.SPAN_WAIT)
            wait_compact(entry.handle)  # device execution wait ...
            if w_span is not None:
                # under a mesh this wait covers the cross-shard collectives
                # (all-gathers/reductions over the cluster axis), not just
                # the local compute — mark it so a waterfall attributes a
                # slow wait to the right cause
                w_span.end(**({"mesh": mesh_plan.shape_str,
                               "collective_wait": True}
                              if mesh_plan is not None else {}))
            if live():
                sm.STEP_LATENCY.observe(
                    time.perf_counter() - t_w, schedule_step=sm.STEP_SOLVE)
            t_d2h = time.perf_counter()  # ... then the result copy
            d2h_span = stage(obs.SPAN_D2H)
            if d2h_span is not None:
                # attach: the solver annotates the AMBIENT span with the
                # rare nnz-escalation re-solve (ops/solver.finalize_compact)
                with tracer.attach(d2h_span):
                    fin = finalize_compact(entry.handle)
                d2h_span.end()
            else:
                fin = finalize_compact(entry.handle)
            idx, val, status = fin[0], fin[1], fin[2]
            if chaos_mod.armed():
                _chaos_d2h(batch, idx, val, status, entry.index)
            if armed:
                expl_planes = fin[-1]  # (verdict, score, avail, outcome)
            if live():
                sm.STEP_LATENCY.observe(
                    time.perf_counter() - t_d2h, schedule_step=sm.STEP_D2H)
            t_dec = time.perf_counter()
            dec_span = stage(obs.SPAN_DECODE)
            decoded = tensors.decode_compact(
                batch, idx, val, status,
                enable_empty_workload_propagation=keep_sel,
                items=part if diagnose else None,
                # explain-armed cycles: the outcome verdict plane rides
                # the decode pass, attaching dominant rejection reasons
                # to the error objects (native or Python path alike)
                outcome=expl_planes[3] if expl_planes is not None else None,
            )
            if dec_span is not None:
                dec_span.end()
            decode_s = time.perf_counter() - t_dec
            if live():
                sm.STEP_LATENCY.observe(decode_s,
                                        schedule_step=sm.STEP_DECODE)
            for i in range(len(part)):
                if batch.route[i] == tensors.ROUTE_DEVICE:
                    out_local[i] = decoded[i]
        out_local.update(sub)
        if armed:
            _record_decisions(explain, batch, part, entry.offset, keys,
                              out_local, expl_planes, sp_expl,
                              cyc, live())
        t_end = time.perf_counter()
        n_ok = 0
        chunk_failures: Dict[str, int] = {}
        for i, r in out_local.items():
            if isinstance(r, Exception):
                k = type(r).__name__
                chunk_failures[k] = chunk_failures.get(k, 0) + 1
            else:
                n_ok += 1
        stats = ChunkStats(
            index=entry.index, offset=entry.offset, n=len(part), n_ok=n_ok,
            failures=chunk_failures,
            encode_s=entry.encode_s,
            solve_s=t_end - t1 - decode_s,
            decode_s=decode_s,
            own_s=entry.encode_s + (t_end - t1),
            wall_s=t_end - entry.t_submit,
        )
        if ch_span is not None:
            # closed even for a cancelled cycle: the trace is exactly the
            # evidence the degradation guard otherwise discards
            ch_span.end(n_ok=n_ok, own_s=round(stats.own_s, 6),
                        wall_s=round(stats.wall_s, 6))
        if not live():
            return  # abandoned cycle: nothing it computed may escape
        if collect:
            for i, r in out_local.items():
                res.results[entry.offset + i] = r
        res.scheduled += n_ok
        for k, v in chunk_failures.items():
            res.failures[k] = res.failures.get(k, 0) + v
        res.chunk_own.append(stats.own_s)
        res.chunk_wall.append(stats.wall_s)
        res.solve_s += stats.solve_s
        res.chunks += 1
        sm.PIPELINE_CHUNK_LATENCY.observe(stats.own_s,
                                          span=sm.PIPELINE_CHUNK_SPAN)
        sm.PIPELINE_CHUNK_LATENCY.observe(stats.wall_s,
                                          span=sm.PIPELINE_CHUNK_WALL)
        sm.PIPELINE_CHUNKS.inc(carry=carry_label)
        if on_chunk is not None:
            on_chunk(stats)

    pending: Optional[_InFlight] = None
    try:
        for ci in range((n + chunk - 1) // chunk):
            if not live():
                break
            if skip is not None and skip(ci):
                continue
            lo = ci * chunk
            part = items[lo:lo + chunk]
            tc = time.perf_counter()
            ch_span = enc_span = None
            if traced:
                ch_span = tracer.start_span(obs.SPAN_CHUNK, parent=cyc,
                                            index=ci, offset=lo,
                                            n=len(part))
                enc_span = tracer.start_span(obs.SPAN_ENCODE, parent=ch_span)
            batch = (encode(part, lo, armed) if encode is not None
                     else tensors.encode_batch(part, cindex, estimator,
                                               cache=cache, explain=armed))
            residual: List[int] = []
            resid_used0 = None
            if shortlist is not None:
                # tier selection (ops/shortlist): dispatch the cheap
                # candidate kernel and, when the chunk is covered, swap
                # in the sub-vocabulary batch — the dispatch/decode/
                # carry machinery below runs it unchanged.  Fallbacks
                # keep the dense batch (counted + ledgered in the
                # shortlist module; bit-exactness is never traded).
                from karmada_tpu.ops import shortlist as sl_mod

                sub, sl_info = sl_mod.shrink_chunk(
                    batch, shortlist, plan=mesh_plan, part=part,
                    # the per-binding residual is exact only at waves=1
                    # (one chunk's rows never see each other there) and
                    # keep_sel needs the full selection plane
                    allow_truncate=(waves == 1 and not keep_sel))
                if ch_span is not None:
                    ch_span.set_attr(shortlist=(
                        f"union={sl_info['union']} k={sl_info['k']}"
                        if sub is not None
                        else sl_info.get("fallback", "off")))
                if sub is not None:
                    batch = sub
                    residual = sl_info.get("residual") or []
                    if residual and chain is not None:
                        # full-vocabulary carry-in for the residual rows:
                        # the chunk's own used0 lives in the union
                        # vocabulary, blind to consumption on lanes
                        # outside it.  Snapshot BEFORE this chunk's
                        # dispatch = exactly the chunks-before-this-one
                        # consumption (rare path: super-k_max rows)
                        resid_used0 = chain.snapshot()
            t1 = time.perf_counter()
            if enc_span is not None:
                enc_span.end()
            if live():
                sm.STEP_LATENCY.observe(t1 - tc,
                                        schedule_step=sm.STEP_ENCODE)
            if not live():
                break
            # without carry an all-host chunk skips the device entirely (the
            # pre-pipeline behavior); with carry every chunk dispatches so the
            # chain stays contiguous (an all-invalid batch consumes nothing).
            # The check reads `route` (host-side by contract, fused batches
            # included) — b_valid equals route == ROUTE_DEVICE on real rows
            # by construction, but on a fused resident-gather batch it is a
            # live device array and reading it here would force a sync.
            handle = used0 = None
            if chain is not None or bool(
                    np.any(np.asarray(batch.route) == tensors.ROUTE_DEVICE)):
                if chaos_mod.armed():
                    # chaos seam (device.dispatch:raise): a dispatch-time
                    # device fault fails the whole cycle; the scheduler's
                    # cycle-fault containment re-queues the batch
                    chaos_mod.raise_if(chaos_mod.SITE_DEVICE_DISPATCH,
                                       chunk=ci)
                t_h2d = time.perf_counter()
                d_span = (tracer.start_span(obs.SPAN_DISPATCH,
                                            parent=ch_span)
                          if ch_span is not None else None)
                if chain is not None:
                    used0 = chain.carry_in(batch)
                # buffer-donation policy: the carry-in may update in place
                # (ops/solver donated dispatch) unless this chunk's finalize
                # still needs to READ it on host — carry_spread hands the
                # carry-in to the spread/big sub-solves, so chunks with such
                # rows keep their used0 alive.  The solver additionally
                # refuses donation whenever the nnz-escalation re-solve is
                # not provably impossible.
                donate = (chain is not None and not residual
                          and not (carry_spread and bool(np.isin(
                              batch.route,
                              (tensors.ROUTE_DEVICE_SPREAD,
                               tensors.ROUTE_DEVICE_SPREAD_BIG,
                               tensors.ROUTE_DEVICE_BIG)).any())))
                if d_span is not None:
                    # attach: the solver annotates the ambient span with
                    # the jit compile-cache hit/miss (ops/solver)
                    with tracer.attach(d_span):
                        handle = dispatch_compact(
                            batch, waves=waves, keep_sel=keep_sel,
                            with_used=chain is not None, used0=used0,
                            donate_used0=donate, explain=armed,
                        )
                    d_span.end()
                else:
                    handle = dispatch_compact(
                        batch, waves=waves, keep_sel=keep_sel,
                        with_used=chain is not None, used0=used0,
                        donate_used0=donate, explain=armed,
                    )
                if chain is not None:
                    chain.dispatched(batch, handle)
                if live():
                    sm.STEP_LATENCY.observe(
                        time.perf_counter() - t_h2d,
                        schedule_step=sm.STEP_H2D)
            entry = _InFlight(index=ci, offset=lo, part=part, batch=batch,
                              handle=handle, used0=used0, t_submit=tc,
                              encode_s=t1 - tc, span=ch_span,
                              residual=residual, resid_used0=resid_used0)
            if pending is not None:
                finalize(pending)
            pending = entry
        if pending is not None and live():
            finalize(pending)
        if chain is not None and collect_carry and live():
            # the incremental plane's ledger hand-off: seed + every
            # chunk's own consumption, keyed in the full vocabulary
            # (one host sync on the final dispatched solve)
            res.carry = chain.snapshot()
    finally:
        res.cancelled = not live()
        if cyc is not None:
            # ending the cycle span force-closes any still-open chunk/stage
            # spans when it is the trace root (bench); nested under a
            # scheduler.cycle trace the root's end does the same — either
            # way a cancelled cycle yields a COMPLETE cancelled=true trace
            cyc.end(cancelled=res.cancelled, chunks=res.chunks,
                    scheduled=res.scheduled)
    return res
