"""Pass 7 — metric-name <-> documentation parity (docs/OBSERVABILITY.md).

Every metric the package registers (the same ``*REGISTRY`` literal-name
registrations pass 5 vets) must appear in ``docs/OBSERVABILITY.md`` —
the scrape surface's catalogue is the only place an operator can learn
what a series means, and an undocumented metric rots into cargo-cult
the moment its author forgets it.  The reverse direction is checked
too: a metric name the doc catalogues but nothing registers is stale
documentation (a rename that forgot the doc) — waivable, because the
doc legitimately references externally-produced series.

Doc-side conventions the extractor understands:

  * label braces after a complete name are stripped:
    ``karmada_foo_total{kind}`` documents ``karmada_foo_total``;
  * a brace group directly after a trailing underscore is NAME
    expansion: ``karmada_slo_{healthy,burn_rate_milli}`` documents
    ``karmada_slo_healthy`` and ``karmada_slo_burn_rate_milli``;
  * a doc line containing ``metric-docs: ok`` (e.g. inside an HTML
    comment with a reason) waives that LINE's doc-side names — the
    doc-side analogue of ``# vet: ignore[metric-docs] why`` on a
    registration site.

Both directions only run on whole-package scans (the scanned set must
include ``utils/metrics.py``, the registry home) — vetting one file
must not report the rest of the tree's doc as stale.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile
from karmada_tpu.analysis.metric_naming import _arg, _registration

DOC_RELPATH = os.path.join("docs", "OBSERVABILITY.md")

#: a metric-shaped token: karmada_ + at least one more underscore
#: segment ("karmada_tpu" alone is the package name, never a metric)
_NAME_RE = re.compile(r"karmada_[a-z0-9]+(?:_[a-z0-9]+)+")
_EXPAND_RE = re.compile(r"(karmada_[a-z0-9_]*_)\{([a-z0-9_,]+)\}([a-z0-9_]*)")
_LABEL_BRACE_RE = re.compile(r"\{[^}]*\}")
_DOC_WAIVER = "metric-docs: ok"
_NOT_METRICS = {"karmada_tpu"}


def _find_doc(files: Sequence[SourceFile]) -> Optional[str]:
    """docs/OBSERVABILITY.md, located by walking up from the scanned
    files' directories (the doc lives at the repo root, one level above
    the package)."""
    seen = set()
    for sf in files:
        d = os.path.dirname(os.path.abspath(sf.path))
        for _ in range(6):
            if d in seen:
                break
            seen.add(d)
            cand = os.path.join(d, DOC_RELPATH)
            if os.path.isfile(cand):
                return cand
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def doc_metric_names(text: str) -> Dict[str, Tuple[int, bool]]:
    """{name: (first line number, waived)} for every metric-shaped token
    in the doc, after name-expansion and label-brace stripping."""
    out: Dict[str, Tuple[int, bool]] = {}
    for lineno, line in enumerate(text.splitlines(), start=1):
        waived = _DOC_WAIVER in line
        expanded = _EXPAND_RE.sub(
            lambda m: " ".join(m.group(1) + alt + m.group(3)
                               for alt in m.group(2).split(",")),
            line)
        stripped = _LABEL_BRACE_RE.sub(" ", expanded)
        for name in _NAME_RE.findall(stripped):
            if name in _NOT_METRICS:
                continue
            prev = out.get(name)
            if prev is None:
                out[name] = (lineno, waived)
            elif waived and not prev[1]:
                out[name] = (prev[0], True)
    return out


def registered_names(
        files: Sequence[SourceFile]) -> List[Tuple[str, SourceFile, int]]:
    """(name, file, line) of every literal-name registry registration in
    the scanned set (computed names are pass 5's finding, not ours)."""
    out: List[Tuple[str, SourceFile, int]] = []
    for sf in files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _registration(node) is None:
                continue
            name_node = _arg(node, 0, "name")
            if (isinstance(name_node, ast.Constant)
                    and isinstance(name_node.value, str)):
                out.append((name_node.value, sf, node.lineno))
    return out


def run(files: Sequence[SourceFile]) -> List[Finding]:
    whole_package = any(
        sf.path.endswith(os.path.join("utils", "metrics.py"))
        for sf in files)
    if not whole_package:
        return []
    regs = registered_names(files)
    if not regs:
        return []
    doc_path = _find_doc(files)
    if doc_path is None:
        sf = regs[0][1]
        return [Finding(
            rule="metric-docs", file=sf.path, line=regs[0][2],
            message=f"{DOC_RELPATH} not found above the scanned tree — "
                    "the metric catalogue gate cannot run (metrics are "
                    "registered but nothing documents them)",
        )]
    try:
        with open(doc_path, encoding="utf-8") as f:
            doc_text = f.read()
    except OSError as e:
        sf = regs[0][1]
        return [Finding(rule="metric-docs", file=sf.path, line=regs[0][2],
                        message=f"cannot read {doc_path}: {e}")]
    doc_names = doc_metric_names(doc_text)
    findings: List[Finding] = []
    seen_code = set()
    for name, sf, line in regs:
        seen_code.add(name)
        if name not in doc_names:
            findings.append(Finding(
                rule="metric-docs", file=sf.path, line=line,
                message=f"metric `{name}` is registered but not "
                        f"catalogued in {DOC_RELPATH} — every scrape "
                        "series needs its operator-facing row",
            ))
    for name, (lineno, waived) in sorted(doc_names.items()):
        if name in seen_code or waived:
            continue
        findings.append(Finding(
            rule="metric-docs", file=doc_path, line=lineno,
            message=f"{DOC_RELPATH} catalogues `{name}` but nothing "
                    "registers it — stale documentation (rename the doc "
                    f"row, or waive the line with `{_DOC_WAIVER} <why>`)",
        ))
    return findings
