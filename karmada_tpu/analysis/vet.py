"""`karmadactl vet` — run the static passes and assemble the report.

JSON shape (stable; bench/watch tooling ingests it):

    {
      "version": 1,
      "clean": bool,
      "files": <scanned file count>,
      "findings": [{"rule", "file", "line", "message"}, ...],
      "waivers":  [{"rule", "file", "line", "justification"}, ...],
      "counts": {"findings": N, "waivers": M,
                 "by_rule": {"<rule>": {"findings": n, "waivers": m}}}
    }

Exit policy (cmd_vet in cli.py): non-zero iff findings is non-empty;
waivers never fail the run but are always enumerated.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karmada_tpu.analysis import (
    dtype_contract,
    event_reasons,
    exception_hygiene,
    lock_discipline,
    lock_order,
    metric_docs,
    metric_naming,
    spec_coverage,
    trace_safety,
)
from karmada_tpu.analysis.core import (
    RULES,
    Finding,
    SourceFile,
    Waiver,
    apply_waivers,
    collect_files,
)

#: pass name -> (runner, rules it can emit)
PASSES = {
    "trace-safety": (trace_safety.run,
                     ("trace-branch", "trace-host-sync", "trace-weak-int")),
    "dtype-contract": (dtype_contract.run, ("dtype-contract",)),
    "spec-coverage": (spec_coverage.run, ("spec-coverage",)),
    "lock-discipline": (lock_discipline.run, ("guarded-by",)),
    "lock-order": (lock_order.run, ("lock-order", "lock-blocking-call")),
    "metric-naming": (metric_naming.run, ("metric-naming",)),
    "metric-docs": (metric_docs.run, ("metric-docs",)),
    "event-reasons": (event_reasons.run, ("event-reasons",)),
    "exception-hygiene": (exception_hygiene.run, ("exception-hygiene",)),
}


@dataclass
class VetReport:
    files: int
    findings: List[Finding] = field(default_factory=list)
    waivers: List[Waiver] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> dict:
        by_rule: Dict[str, Dict[str, int]] = {}
        for f in self.findings:
            by_rule.setdefault(f.rule, {"findings": 0, "waivers": 0})
            by_rule[f.rule]["findings"] += 1
        for w in self.waivers:
            by_rule.setdefault(w.rule, {"findings": 0, "waivers": 0})
            by_rule[w.rule]["waivers"] += 1
        return {"findings": len(self.findings), "waivers": len(self.waivers),
                "by_rule": by_rule}

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "clean": self.clean,
            "files": self.files,
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.file, f.line, f.rule))],
            "waivers": [w.to_dict() for w in sorted(
                self.waivers, key=lambda w: (w.file, w.line, w.rule))],
            "counts": self.counts(),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self) -> str:
        lines: List[str] = []
        for f in sorted(self.findings,
                        key=lambda f: (f.file, f.line, f.rule)):
            lines.append(f"{f.file}:{f.line}: [{f.rule}] {f.message}")
        c = self.counts()
        lines.append(
            f"vet: {c['findings']} finding(s), {c['waivers']} waiver(s) "
            f"across {self.files} file(s)")
        for w in sorted(self.waivers, key=lambda w: (w.file, w.line)):
            lines.append(
                f"  waived {w.file}:{w.line} [{w.rule}] — {w.justification}")
        return "\n".join(lines)


def run_vet(paths: Sequence[str],
            rules: Optional[Sequence[str]] = None) -> VetReport:
    """Run every pass over the python files under `paths`.

    `rules` (finding-rule names from core.RULES) filters which FINDINGS
    are kept — passes still all run, waivers are ALWAYS enumerated in
    full (the waiver population is an audit surface, not a per-rule
    view), and waiver-syntax problems are never hidden.

    Raises ValueError on an unknown rule or a nonexistent path: a typo'd
    path must be a usage error, never a 0-file "clean" result that lets
    the standing gate pass vacuously.
    """
    import os

    if rules is not None:
        unknown = set(rules) - set(RULES)
        if unknown:
            raise ValueError(
                f"unknown rule(s) {sorted(unknown)}; valid: {list(RULES)}")
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        raise ValueError(f"no such path(s): {missing}")
    files: List[SourceFile] = collect_files(paths)
    raw: List[Finding] = []
    for _name, (runner, _emits) in PASSES.items():
        raw.extend(runner(files))
    findings, waivers = apply_waivers(raw, files)
    if rules is not None:
        keep = set(rules) | {"waiver-syntax"}
        findings = [f for f in findings if f.rule in keep]
    return VetReport(files=len(files), findings=findings, waivers=waivers)
