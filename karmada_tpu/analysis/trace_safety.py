"""Pass 1 — trace-safety inside jit-compiled code.

Finds the module's *registered jit entrypoints* — functions wrapped by
``jax.jit`` / ``partial(jax.jit, ...)`` (decorator or assignment form) or
``jax.vmap`` — then walks the transitive closure of module-local (and
cross-module, via ``from karmada_tpu... import``) calls from those
bodies.  Everything reached is traced code, where three thing classes are
defects invisible to single-device pytest:

  * trace-branch    — Python ``if``/``while`` whose test contains a
                      jnp/lax expression: the branch runs at TRACE time on
                      a tracer (ConcretizationTypeError at best, silently
                      baked-in branch at worst).  Static/shape branches
                      (plain ints, None checks) are fine and not flagged.
  * trace-host-sync — ``.item()``, ``float(...)``/``int(...)`` over jnp
                      expressions, and ``np.asarray``/``np.array`` calls:
                      each forces a device->host transfer inside the
                      compiled region (or a trace error), serializing the
                      pipelined dispatch.
  * trace-weak-int  — ``jnp.arange/zeros/ones/full/empty`` without an
                      explicit dtype: under jax_enable_x64 these default
                      to s64/f64 and are exactly how the PR-3 mixed
                      s64/s32 stacking DUS reached the SPMD partitioner.

The walk is lexical (nested defs such as wave_step are visited as part of
their parent body); attribute calls (``meshing.wave_output_shardings``)
are trace-time host helpers and are deliberately not followed.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from karmada_tpu.analysis.core import Finding, SourceFile, dotted

# jnp constructors whose dtype defaults are the s64/f64 hazard, with the
# positional index their dtype parameter occupies
_WEAK_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "arange": 3}

_HOST_CASTS = ("float", "int", "bool", "complex")


class _Aliases:
    """Per-file import names for jax.numpy / jax.lax / numpy / jax /
    functools.partial."""

    def __init__(self, tree: ast.Module) -> None:
        self.jnp: Set[str] = set()
        self.lax: Set[str] = set()
        self.np: Set[str] = set()
        self.jax: Set[str] = set()
        self.partial: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "jax.numpy":
                        self.jnp.add(a.asname or "jax.numpy")
                    elif a.name == "jax.lax":
                        self.lax.add(a.asname or "jax.lax")
                    elif a.name == "numpy":
                        self.np.add(name)
                    elif a.name == "jax":
                        self.jax.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp.add(a.asname or "numpy")
                        elif a.name == "lax":
                            self.lax.add(a.asname or "lax")
                elif node.module == "functools":
                    for a in node.names:
                        if a.name == "partial":
                            self.partial.add(a.asname or "partial")
        self.partial.add("functools.partial")

    def is_jit(self, node: ast.AST) -> bool:
        d = dotted(node)
        return d is not None and (
            d in {f"{j}.jit" for j in self.jax} or d == "jit")

    def is_vmap(self, node: ast.AST) -> bool:
        d = dotted(node)
        return d is not None and d in {f"{j}.vmap" for j in self.jax}

    def is_partial(self, node: ast.AST) -> bool:
        d = dotted(node)
        return d is not None and d in self.partial

    def traced_array_call(self, node: ast.AST) -> bool:
        """True for a Call on a jnp/lax attribute (``jnp.sum(x)``) — the
        marker that an expression's value is traced, not static."""
        if not isinstance(node, ast.Call):
            return False
        d = dotted(node.func)
        if d is None:
            return False
        base = d.rsplit(".", 1)[0] if "." in d else None
        return base is not None and (base in self.jnp or base in self.lax)


def _wrapped_name(call: ast.Call, al: _Aliases) -> Optional[str]:
    """F for jax.jit(F) / jax.vmap(F) / partial(jax.jit, ...)(F) /
    jax.vmap(partial(F, ...)) shapes; None otherwise."""
    target: Optional[ast.AST] = None
    if al.is_jit(call.func) or al.is_vmap(call.func):
        target = call.args[0] if call.args else None
    elif isinstance(call.func, ast.Call):
        inner = call.func
        if al.is_partial(inner.func) and inner.args and (
                al.is_jit(inner.args[0]) or al.is_vmap(inner.args[0])):
            target = call.args[0] if call.args else None
    if isinstance(target, ast.Call) and al.is_partial(target.func):
        target = target.args[0] if target.args else None
    if isinstance(target, ast.Name):
        return target.id
    return None


def _decorated_jit(fn: ast.FunctionDef, al: _Aliases) -> bool:
    for dec in fn.decorator_list:
        if al.is_jit(dec) or al.is_vmap(dec):
            return True
        if isinstance(dec, ast.Call):
            if al.is_jit(dec.func) or al.is_vmap(dec.func):
                return True
            if al.is_partial(dec.func) and dec.args and (
                    al.is_jit(dec.args[0]) or al.is_vmap(dec.args[0])):
                return True
    return False


class _Module:
    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        self.aliases = _Aliases(sf.tree)
        self.defs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in sf.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # local name -> (source module, original name, relative level)
        self.imports: Dict[str, Tuple[Optional[str], str, int]] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.imports[a.asname or a.name] = (
                        node.module, a.name, node.level or 0)

    def roots(self) -> Set[str]:
        out: Set[str] = set()
        for name, fn in self.defs.items():
            if _decorated_jit(fn, self.aliases):
                out.add(name)
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Call):
                w = _wrapped_name(node, self.aliases)
                if w is not None:
                    out.add(w)
        return out & set(self.defs)


def _resolve_module(cur_path: str, module: Optional[str], level: int,
                    paths: Sequence[str]) -> Optional[str]:
    """The scanned file a from-import refers to, or None.  Modules are
    keyed by FULL path (basenames collide: many __init__.py, two
    metrics.py); absolute imports match by path suffix ('from
    karmada_tpu.ops.solver import X' -> .../karmada_tpu/ops/solver.py),
    relative imports resolve against the importing file's directory."""
    import os

    if level > 0:
        base = os.path.dirname(cur_path)
        for _ in range(level - 1):
            base = os.path.dirname(base)
        rel = (module or "").replace(".", os.sep)
        stem = os.path.join(base, rel) if rel else base
        for cand in (stem + ".py", os.path.join(stem, "__init__.py")):
            cand = os.path.normpath(cand)
            if cand in paths:
                return cand
        return None
    if not module:
        return None
    suffix = module.replace(".", os.sep)
    for cand_suffix in (suffix + ".py", os.path.join(suffix, "__init__.py")):
        for path in sorted(paths):
            if path == cand_suffix or path.endswith(os.sep + cand_suffix):
                return path
    return None


def _check_body(
    fn: ast.FunctionDef, mod: _Module, findings: List[Finding],
    calls_out: Set[str],
) -> None:
    al = mod.aliases
    path = mod.sf.path

    def has_traced_expr(node: ast.AST) -> bool:
        return any(al.traced_array_call(n) for n in ast.walk(node))

    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)) and has_traced_expr(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            findings.append(Finding(
                rule="trace-branch", file=path, line=node.lineno,
                message=f"Python `{kind}` on a traced value inside "
                        f"jit-compiled `{fn.name}` — use jnp.where/"
                        "lax.cond/lax.while_loop",
            ))
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                findings.append(Finding(
                    rule="trace-host-sync", file=path, line=node.lineno,
                    message=f".item() host sync inside jit-compiled "
                            f"`{fn.name}`",
                ))
            elif isinstance(node.func, ast.Name) and \
                    node.func.id in _HOST_CASTS and node.args and \
                    has_traced_expr(node.args[0]):
                findings.append(Finding(
                    rule="trace-host-sync", file=path, line=node.lineno,
                    message=f"{node.func.id}() of a traced value inside "
                            f"jit-compiled `{fn.name}` forces a host sync",
                ))
            elif d is not None and "." in d:
                base, attr = d.rsplit(".", 1)
                if base in al.np and attr in ("asarray", "array"):
                    findings.append(Finding(
                        rule="trace-host-sync", file=path, line=node.lineno,
                        message=f"np.{attr}() inside jit-compiled "
                                f"`{fn.name}` materializes to host",
                    ))
                elif base in al.jnp and attr in _WEAK_CTORS:
                    # a positional arg beyond the dtype slot IS the dtype
                    # (zeros(shape, dtype), full(shape, fill, dtype), ...)
                    dtype_pos = _WEAK_CTORS[attr]
                    has_dtype = (
                        len(node.args) > dtype_pos
                        or any(k.arg == "dtype" for k in node.keywords)
                    )
                    if not has_dtype:
                        findings.append(Finding(
                            rule="trace-weak-int", file=path,
                            line=node.lineno,
                            message=f"jnp.{attr}() without an explicit "
                                    f"dtype inside jit-compiled `{fn.name}` "
                                    "defaults to s64/f64 under x64 (the "
                                    "mixed s64/s32 SPMD bug class)",
                        ))
            if isinstance(node.func, ast.Name):
                calls_out.add(node.func.id)
            # partial(F, ...) passed onward keeps F traced
            if isinstance(node.func, ast.Name) and \
                    node.func.id in {p.split(".")[-1] for p in al.partial} \
                    and node.args and isinstance(node.args[0], ast.Name):
                calls_out.add(node.args[0].id)


def run(files: Sequence[SourceFile]) -> List[Finding]:
    mods = {sf.path: _Module(sf) for sf in files}
    findings: List[Finding] = []
    # worklist of (module path, function name), starting from jit roots
    work: List[Tuple[str, str]] = []
    seen: Set[Tuple[str, str]] = set()
    for path, mod in mods.items():
        for r in sorted(mod.roots()):
            work.append((path, r))
    while work:
        path, name = work.pop()
        if (path, name) in seen:
            continue
        seen.add((path, name))
        mod = mods.get(path)
        if mod is None or name not in mod.defs:
            continue
        calls: Set[str] = set()
        _check_body(mod.defs[name], mod, findings, calls)
        for c in sorted(calls):
            if c in mod.defs:
                work.append((path, c))
            elif c in mod.imports:
                src_module, orig, level = mod.imports[c]
                src_path = _resolve_module(path, src_module, level, mods)
                if src_path is not None:
                    work.append((src_path, orig))
    return findings
